"""Cross-task host-pipeline prefetch: decode ahead while the device runs.

The reference's worker overlapped host decode with device compute through
tf.data's internal threading plus ``prefetch(1)``
(``elasticdl/python/worker/worker.py:977``).  The TPU runtimes get the
same overlap here, one level up: a single producer thread walks the TASK
stream (dispatcher -> task -> minibatch pipeline) and fills a bounded
queue, so while the device executes the current stacked dispatch — and
while the main thread is blocked in host->device transfers, both of which
release the GIL — the next task's records are already being read, decoded
and batched.  On a single-core host this is the only free parallelism
there is: decode burns the core exactly when the main thread isn't using
it.

Ordering and accounting semantics are unchanged from the serial loop:
batches arrive in task order, a task's batches are contiguous, and the
caller reports each task only after consuming all its batches — so
exactly-once accounting, milestone hooks, and lockstep's deterministic
batch stream behave identically.

With ``--device_prefetch`` (trainer/device_pipeline.py) this queue is
the DECODE stage of a three-deep pipeline: the TaskPrefetcher reads and
decodes task N+1's records while the device-side stager pads/places the
next dispatch group of task N and the device computes the current one —
decode -> stage -> compute, each on its own thread, each bounded.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Iterator

import jax
import numpy as np

from elasticdl_tpu.trainer.stacking import PreStacked

_TASK = "task"
_BATCH = "batch"
_END_TASK = "end"
_ERROR = "error"
_DONE = "done"


class TaskPrefetcher:
    """Iterate ``(task_id, task, batches)`` triples with the host
    pipeline running ahead on a background thread.

    ``next_task()`` -> ``(task_id, task)`` or ``(_, None)`` at end of
    stream (the dispatcher contract).  ``make_batches(task)`` -> iterable
    of minibatches.  Decode-ahead memory is bounded by BOTH
    ``max_buffered_batches`` (size it in batches the consumer works
    ahead by, e.g. two ``--steps_per_dispatch`` groups) and
    ``max_buffered_bytes`` (so large-image batches can't multiply the
    count bound into gigabytes).

    Each yielded ``batches`` iterator must be consumed before advancing
    the outer iteration (the runtimes' per-task loops do).
    """

    def __init__(
        self,
        next_task: Callable,
        make_batches: Callable,
        max_buffered_batches: int = 32,
        max_buffered_bytes: int = 64 << 20,
    ):
        self._next_task = next_task
        self._make_batches = make_batches
        # the queue itself is unbounded; _put blocks on whichever budget
        # (batch count or BYTES) is exhausted first — a flat batch count
        # alone would buffer gigabytes for large-image models
        self._q: queue.Queue = queue.Queue()
        self._max_batches = max(1, max_buffered_batches)
        self._max_bytes = max_buffered_bytes
        self._credit = threading.Condition()
        self._buffered_batches = 0
        self._buffered_bytes = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._produce, name="task-prefetch", daemon=True
        )
        self._started = False
        # memory-ledger accounting: the decode-ahead buffer is exactly
        # the bytes budget this class already tracks (GIL-atomic read)
        from elasticdl_tpu.telemetry import memory as memory_mod

        self._ledger_cb = lambda: self._buffered_bytes
        memory_mod.register_component(
            memory_mod.COMPONENT_TASK_PREFETCHER, self._ledger_cb
        )

    # ---- producer ---------------------------------------------------------

    @staticmethod
    def _batch_bytes(batch) -> int:
        # module-level imports: this runs once per produced batch on the
        # decode thread — a per-call import chain (jax + numpy +
        # stacking) was measurable overhead on the prefetch hot path
        if isinstance(batch, PreStacked):
            batch = (batch.features, batch.labels)
        return sum(
            getattr(leaf, "nbytes", 0) or np.asarray(leaf).nbytes
            for leaf in jax.tree_util.tree_leaves(batch)
        )

    def _put(self, item, count: int = 0, nbytes: int = 0) -> bool:
        """Blocking put that aborts when the consumer closed us; batch
        items charge both buffering budgets (``count`` = batches carried
        — a PreStacked group counts its steps, not 1), and marker items
        (task boundaries etc., count=0) are throttled by total queue
        depth so a stream of empty tasks cannot drain the whole
        dispatcher into the unbounded queue."""
        marker_cap = 2 * self._max_batches + 8
        with self._credit:
            while not self._stop.is_set():
                if count == 0:
                    if self._q.qsize() < marker_cap:
                        self._q.put(item)
                        return True
                elif (
                    self._buffered_batches < self._max_batches
                    and self._buffered_bytes < self._max_bytes
                ):
                    self._buffered_batches += count
                    self._buffered_bytes += nbytes
                    self._q.put(item)
                    return True
                self._credit.wait(timeout=0.1)
        return False

    def _release(self, count: int, nbytes: int):
        with self._credit:
            self._buffered_batches -= count
            self._buffered_bytes -= nbytes
            self._credit.notify()

    def _produce(self):
        try:
            while not self._stop.is_set():
                tid, task = self._next_task()
                if task is None:
                    break
                if not self._put((_TASK, (tid, task))):
                    return
                for batch in self._make_batches(task):
                    count = (
                        batch.num_steps
                        if isinstance(batch, PreStacked)
                        else 1
                    )
                    nbytes = max(1, self._batch_bytes(batch))
                    if not self._put(
                        (_BATCH, (batch, count, nbytes)),
                        count=count,
                        nbytes=nbytes,
                    ):
                        return
                if not self._put((_END_TASK, tid)):
                    return
        except BaseException as e:  # noqa: BLE001 — re-raised by consumer
            self._put((_ERROR, e))
            return
        self._put((_DONE, None))

    # ---- consumer ---------------------------------------------------------

    def __iter__(self) -> Iterator:
        if not self._started:
            self._started = True
            self._thread.start()
        while True:
            kind, payload = self._q.get()
            if kind == _DONE:
                return
            if kind == _ERROR:
                raise payload
            assert kind == _TASK, f"protocol error: {kind} outside a task"
            tid, task = payload
            batches = self._task_batches(tid)
            yield tid, task, batches
            # the runtimes drain `batches` inside the loop body; guard
            # against a partial consumer (e.g. an exception path) by
            # draining the remainder so the stream stays aligned
            for _ in batches:
                pass

    def _task_batches(self, expect_tid) -> Iterator:
        while True:
            kind, payload = self._q.get()
            if kind == _BATCH:
                batch, count, nbytes = payload
                self._release(count, nbytes)
                yield batch
            elif kind == _END_TASK:
                assert payload == expect_tid
                return
            elif kind == _ERROR:
                raise payload
            else:  # pragma: no cover — protocol violation
                raise AssertionError(f"unexpected {kind} inside task")

    def close(self):
        """Stop the producer and release it if blocked on a full queue."""
        self._stop.set()
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        if self._started:
            self._thread.join(timeout=5)
        # drop the ledger callback so a closed prefetcher (and the
        # batches it pins) is not kept alive by the component registry
        from elasticdl_tpu.telemetry import memory as memory_mod

        memory_mod.unregister_component(
            memory_mod.COMPONENT_TASK_PREFETCHER, self._ledger_cb
        )
