"""Evaluation metrics.

Reference: the model zoo's ``eval_metrics_fn`` returns a dict of Keras
metric objects that the master's EvaluationJob accumulates from reported
output/label tensors (``evaluation_service.py:69-124``).  The TPU build
replaces Keras metrics with this dependency-free library: each metric is a
small accumulator over numpy arrays (metric accumulation happens on the
master's CPU from control-plane tensor reports, never on device — same
topology as the reference).

Metrics accept ``update(labels, predictions)`` in any mix of numpy/JAX
arrays and support nested-output models via dict-valued metric trees
(reference ``deepfm_edl_embedding.py:104-111``).
"""

from __future__ import annotations

import numpy as np


def _np(x) -> np.ndarray:
    return np.asarray(x)


class Metric:
    name = "metric"

    def update(self, labels, predictions):
        raise NotImplementedError

    def result(self) -> float:
        raise NotImplementedError

    def reset(self):
        raise NotImplementedError


class Mean(Metric):
    """Running mean of a per-batch value (loss tracking)."""

    name = "mean"

    def __init__(self):
        self.reset()

    def reset(self):
        self._total = 0.0
        self._count = 0

    def update_value(self, value, weight: int = 1):
        self._total += float(_np(value)) * weight
        self._count += weight

    def update(self, labels, predictions):
        self.update_value(predictions)

    def result(self) -> float:
        return self._total / self._count if self._count else 0.0


class Accuracy(Metric):
    """Sparse categorical accuracy: labels are class ids, predictions are
    logits/probs [batch, classes] (argmax) or already class ids."""

    name = "accuracy"

    def __init__(self):
        self.reset()

    def reset(self):
        self._correct = 0
        self._count = 0

    def update(self, labels, predictions):
        labels = _np(labels).reshape(-1)
        predictions = _np(predictions)
        if predictions.ndim > 1 and predictions.shape[-1] > 1:
            predicted = predictions.reshape(
                -1, predictions.shape[-1]
            ).argmax(axis=-1)
        else:
            predicted = predictions.reshape(-1)
        self._correct += int((predicted.astype(np.int64) == labels.astype(np.int64)).sum())
        self._count += labels.shape[0]

    def result(self) -> float:
        return self._correct / self._count if self._count else 0.0


class BinaryAccuracy(Metric):
    """Labels in {0,1}; predictions are probabilities or logits (>0.5 / >0)."""

    name = "binary_accuracy"

    def __init__(self, threshold: float = 0.5, from_logits: bool = False):
        self._threshold = 0.0 if from_logits else threshold
        self.reset()

    def reset(self):
        self._correct = 0
        self._count = 0

    def update(self, labels, predictions):
        labels = _np(labels).reshape(-1)
        predicted = (_np(predictions).reshape(-1) > self._threshold).astype(
            np.int64
        )
        self._correct += int((predicted == labels.astype(np.int64)).sum())
        self._count += labels.shape[0]

    def result(self) -> float:
        return self._correct / self._count if self._count else 0.0


class AUC(Metric):
    """Exact ROC-AUC via the Mann-Whitney rank statistic over all reported
    scores (the master sees every eval example, so no binning is needed)."""

    name = "auc"

    def __init__(self):
        self.reset()

    def reset(self):
        self._scores: list[np.ndarray] = []
        self._labels: list[np.ndarray] = []

    def update(self, labels, predictions):
        self._labels.append(_np(labels).reshape(-1).astype(np.int64))
        self._scores.append(_np(predictions).reshape(-1).astype(np.float64))

    def result(self) -> float:
        if not self._labels:
            return 0.0
        y = np.concatenate(self._labels)
        s = np.concatenate(self._scores)
        pos = int(y.sum())
        neg = y.shape[0] - pos
        if pos == 0 or neg == 0:
            return 0.0
        order = np.argsort(s, kind="mergesort")
        ranks = np.empty_like(order, dtype=np.float64)
        ranks[order] = np.arange(1, y.shape[0] + 1)
        # average ranks over ties
        sorted_s = s[order]
        i = 0
        while i < len(sorted_s):
            j = i
            while j + 1 < len(sorted_s) and sorted_s[j + 1] == sorted_s[i]:
                j += 1
            if j > i:
                avg = (i + j + 2) / 2.0
                ranks[order[i : j + 1]] = avg
            i = j + 1
        rank_sum_pos = ranks[y == 1].sum()
        return float(
            (rank_sum_pos - pos * (pos + 1) / 2.0) / (pos * neg)
        )


class MeanSquaredError(Metric):
    name = "mse"

    def __init__(self):
        self.reset()

    def reset(self):
        self._total = 0.0
        self._count = 0

    def update(self, labels, predictions):
        labels = _np(labels).reshape(-1).astype(np.float64)
        predictions = _np(predictions).reshape(-1).astype(np.float64)
        self._total += float(((labels - predictions) ** 2).sum())
        self._count += labels.shape[0]

    def result(self) -> float:
        return self._total / self._count if self._count else 0.0


def update_metric_tree(metrics, labels, outputs):
    """Update a (possibly nested) metric dict.

    Shapes supported (mirroring ``evaluation_service.py:39-61``):
    - {name: Metric} with a single model output;
    - {name: {output_key: Metric}} for multi-output models, where
      ``outputs`` is a dict keyed the same way.
    """
    for name, metric in metrics.items():
        if isinstance(metric, dict):
            for key, sub in metric.items():
                out = outputs[key] if isinstance(outputs, dict) else outputs
                sub.update(labels, out)
        else:
            out = (
                next(iter(outputs.values()))
                if isinstance(outputs, dict)
                else outputs
            )
            metric.update(labels, out)


def metric_tree_results(metrics) -> dict:
    out = {}
    for name, metric in metrics.items():
        if isinstance(metric, dict):
            for key, sub in metric.items():
                out[f"{name}_{key}"] = sub.result()
        else:
            out[name] = metric.result()
    return out


def reset_metric_tree(metrics):
    for metric in metrics.values():
        if isinstance(metric, dict):
            for sub in metric.values():
                sub.reset()
        else:
            metric.reset()
