"""Device-path pipelining: double-buffered h2d staging, batch-buffer
donation and async retire-behind dispatch.

BENCH_r04 measured every e2e config binding on ``device_path`` with
``vs_step_only`` ~0.1: the jitted step standalone is ~10x faster than
the end-to-end record flow, and PR 9's anatomy says the gap is
host-side serialization — every dispatch group's batch is padded,
stacked and placed on device ON the dispatching thread, between
dispatches.  This module closes that gap for the canonical-shape path
(shapes are pure functions of config since PR 5, so staging buffers
never change shape):

- **Staging** (:class:`DeviceStager`): a daemon thread pulls host
  batches from the upstream stream (the ``TaskPrefetcher`` host queue,
  so decode -> stage -> compute form a three-deep pipeline), assembles
  them to the canonical dispatch shape and places them on device while
  the CURRENT group computes.  The queue is bounded (double buffering:
  one group being consumed, one staged, one in assembly) so device
  memory stays bounded.
- **Donation**: the runtimes construct their ``SPMDTrainer`` with
  ``donate_batch=True`` when the feature is on, extending ``jax.jit``
  donation from state-only to the batch/mask buffers — XLA reuses the
  staged input buffers for outputs, so steady-state dispatches do zero
  fresh h2d allocations.  A donated buffer is dead after its dispatch;
  :class:`StagedGroup` enforces single ``take()`` ownership so a
  read-after-retire is caught at the staging layer too (and JAX itself
  raises on a deleted Array — both are pinned by falsification tests).
- **Retire-behind** (:func:`run_pipelined_steps`): dispatch outputs are
  retired one group behind inside a bounded in-flight window
  (:data:`RETIRE_WINDOW`), so XLA async dispatch actually overlaps; the
  full barrier is retained at task boundaries (the function drains
  before returning, so a task is only ever reported after every one of
  its groups retired), and ``--step_anatomy`` collapses the window to 1
  (:func:`stage_depth`) because exact per-group walls need the
  per-group block — the documented cost of measuring.

- **Cross-task staging** (:func:`run_pipelined_task_stream`): with
  ``--boundary_fusion`` the pipeline survives TASK boundaries instead
  of draining and re-staging from host at each one.  One persistent
  :class:`DeviceStager` walks the whole task stream; in-stream
  :class:`TaskMark` sentinels delimit tasks, so at a boundary the
  consumer only retires the PREVIOUS task's in-flight window and runs
  the boundary bookkeeping (report, milestone checks, memory sample)
  while the stager concurrently stages the NEXT task's groups — the
  next pull finds task N+1's first group already device-resident.
  Exactly-once is preserved by retiring-and-reporting per task: a task
  is reported only after its own window drained, and staged-but-
  unreported groups of a reclaimed/fenced task die un-taken when the
  stager closes (single-take ownership — nothing dispatched, nothing
  reported).  The boundary gap is measured as the ``boundary_stall``
  counter (device-idle time between the last retire of task N and the
  first dispatch of task N+1), shipped on the heartbeat next to the
  prefetch totals and mirrored as ``elasticdl_boundary_stall_ms_total``.

Enablement: the master's ``--device_prefetch`` flag, env-forwarded to
workers as ``ELASTICDL_TPU_DEVICE_PREFETCH`` (never argv — worker
command lines stay byte-identical with the feature off); cross-task
staging adds ``--boundary_fusion`` (``ELASTICDL_TPU_BOUNDARY_FUSION``)
and the window/queue bound becomes ``--pipeline_depth``
(``ELASTICDL_TPU_PIPELINE_DEPTH``, default preserving the classic 2),
with the memory ledger's ``device_stager`` component bounding how deep
staging may actually run (admission against the live device headroom /
``ELASTICDL_TPU_STAGING_BUDGET_BYTES``, loud degrade to depth 1 on
pressure).  Disabled cost: the runtimes resolve the flags ONCE at build
time and ``run_stacked_steps`` takes one boolean branch per call — no
thread, no queue, no clock reads (the annotated gates below are
machine-checked by elastic-lint's hot-path checker).

Lockstep safety: staging changes WHEN placement happens, never what is
dispatched — dispatch order, shapes and programs remain pure functions
of (task data, k, canonical rows), identical on every process.  The
enabling env is master-forwarded, so a world can never mix donated and
undonated step programs.
"""

from __future__ import annotations

import contextlib
import os
import queue
import threading
import time
from collections import deque
from typing import Callable, Iterable

import jax
import numpy as np

from elasticdl_tpu.trainer.stacking import (
    PreStacked,
    assemble_canonical_group,
    prestacked_weights,
    resolve_steps_per_dispatch,
)

DEVICE_PREFETCH_ENV = "ELASTICDL_TPU_DEVICE_PREFETCH"
BOUNDARY_FUSION_ENV = "ELASTICDL_TPU_BOUNDARY_FUSION"
PIPELINE_DEPTH_ENV = "ELASTICDL_TPU_PIPELINE_DEPTH"
# absolute byte budget for staged-but-untaken device buffers (admission
# control when --pipeline_depth > 1); unset = live device headroom from
# memory_stats, and backends without allocator stats stay unbounded —
# the ledger's device_stager component still records what is held
STAGING_BUDGET_ENV = "ELASTICDL_TPU_STAGING_BUDGET_BYTES"

# bounded in-flight dispatch window: how many dispatched groups may be
# un-retired before the consumer blocks on the oldest.  2 = the classic
# one-behind pipeline (group N computes while group N+1 enqueues).
# --pipeline_depth overrides it per job (resolve_pipeline_depth).
RETIRE_WINDOW = 2
# staging queue depth: 1 = double buffering (one staged group ready
# while the consumer's current group dispatches; the stager may be
# assembling a third).  Scales as pipeline_depth - 1 when tuned.
STAGE_DEPTH = 1

_STAGE_KIND_GROUP = "group"
_STAGE_KIND_ERROR = "error"
_STAGE_KIND_DONE = "done"
_STAGE_KIND_MARK = "mark"


# ---- flag resolution (shared by all three runtimes) -------------------------


# explicit spellings the env accepts — the env must parse like the
# flag's parse_bool, not truthy-string: "0"/"false" silently ENABLING
# the feature on some hosts would build the mixed donated/undonated
# world the uniformity contract forbids, and an unrecognized spelling
# (typo) must fail SAFE (off, with an error log), never silently on
_FALSEY_ENV = frozenset({"", "0", "false", "no", "off"})
_TRUTHY_ENV = frozenset({"1", "true", "yes", "on"})


def resolve_device_prefetch(flag=None) -> bool:
    """THE enablement rule: the master's ``--device_prefetch`` flag when
    set, else the master-forwarded env (workers never see the flag in
    argv; parse_bool spellings — ``1``/``true``/``yes``/``on`` on,
    ``0``/``false``/``no``/``off``/unset off, anything else logs an
    ERROR and stays off).  Resolved once per runtime at build time."""
    if flag is not None:
        return bool(flag)
    raw = os.environ.get(DEVICE_PREFETCH_ENV, "").strip().lower()
    if raw in _TRUTHY_ENV:
        return True
    if raw not in _FALSEY_ENV:
        from elasticdl_tpu.utils.log_utils import default_logger

        default_logger.error(
            "Unrecognized %s=%r; device prefetch stays OFF (use "
            "1/true/yes/on or 0/false/no/off)",
            DEVICE_PREFETCH_ENV,
            raw,
        )
    return False


def resolve_boundary_fusion(flag=None) -> bool:
    """THE ``--boundary_fusion`` enablement rule — same discipline as
    :func:`resolve_device_prefetch` (master flag wins, else the
    master-forwarded env, parse_bool spellings, typo fails SAFE to
    off).  Cross-task staging additionally requires device prefetch:
    the runtimes fuse only when BOTH resolve on."""
    if flag is not None:
        return bool(flag)
    raw = os.environ.get(BOUNDARY_FUSION_ENV, "").strip().lower()
    if raw in _TRUTHY_ENV:
        return True
    if raw not in _FALSEY_ENV:
        from elasticdl_tpu.utils.log_utils import default_logger

        default_logger.error(
            "Unrecognized %s=%r; boundary fusion stays OFF (use "
            "1/true/yes/on or 0/false/no/off)",
            BOUNDARY_FUSION_ENV,
            raw,
        )
    return False


def resolve_pipeline_depth(flag=None) -> int:
    """THE ``--pipeline_depth`` resolution: the master flag when set,
    else the master-forwarded env, else :data:`RETIRE_WINDOW` (2 — the
    classic one-behind pipeline, byte-identical to the pre-flag
    behavior).  Values clamp to >= 1; a malformed env logs an ERROR
    and keeps the default (fail SAFE to the proven depth)."""
    if flag is not None:
        return max(1, int(flag))
    raw = os.environ.get(PIPELINE_DEPTH_ENV, "").strip()
    if not raw:
        return RETIRE_WINDOW
    try:
        depth = int(raw)
    except ValueError:
        depth = 0
    if depth < 1:
        from elasticdl_tpu.utils.log_utils import default_logger

        default_logger.error(
            "Unrecognized %s=%r; pipeline depth stays %d (use a "
            "positive integer)",
            PIPELINE_DEPTH_ENV,
            raw,
            RETIRE_WINDOW,
        )
        return RETIRE_WINDOW
    return depth


def staging_budget_bytes() -> int | None:
    """Byte budget for staged-but-untaken device buffers, or None for
    unbounded: the env override when set, else half the live device
    headroom (``bytes_limit - bytes_in_use`` from the allocator
    stats), else None — backends without allocator stats (CPU) stay
    unbounded and rely on the queue bound alone."""
    raw = os.environ.get(STAGING_BUDGET_ENV, "").strip()
    if raw:
        try:
            budget = int(raw)
        except ValueError:
            from elasticdl_tpu.utils.log_utils import default_logger

            default_logger.error(
                "Unrecognized %s=%r; staging budget falls back to "
                "device headroom (use a byte count)",
                STAGING_BUDGET_ENV,
                raw,
            )
        else:
            return budget if budget > 0 else None
    from elasticdl_tpu.telemetry.memory import read_device_memory

    stats = read_device_memory()
    limit = int(stats.get("bytes_limit", 0)) if stats else 0
    if limit <= 0:
        return None
    return max(0, limit - int(stats.get("bytes_in_use", 0))) // 2


def resolve_donate_state(args) -> bool:
    """THE ``--donate_state`` resolution — one definition site for what
    was copied verbatim into all three runtimes (local_executor, worker,
    lockstep).  Default True: the state buffers are always dead after
    the optimizer update."""
    return bool(getattr(args, "donate_state", True))


def stage_depth(anatomy, depth=None) -> int:  # elastic-lint: hot-path
    """The retire window for a dispatch loop: ``depth``
    (``--pipeline_depth``, default :data:`RETIRE_WINDOW`) groups in
    flight normally; 1 (retire every group before the next dispatch)
    under ``--step_anatomy``, whose ``enqueue``/``ready_wait`` split
    needs exact per-group walls — the barrier the design doc documents
    as the cost of measuring."""
    if anatomy is None:
        return RETIRE_WINDOW if depth is None else depth
    return 1


# ---- heartbeat-shipped staging totals ---------------------------------------

_TOTALS_LOCK = threading.Lock()
# monotone process-lifetime totals; ms accumulate as floats here and
# ship as ints (the wire merge is utils.merge.max_merge_counters,
# integer-only — truncating per-event sub-ms samples would lose them)
_TOTALS = {
    "groups": 0,
    "stall_ms": 0.0,
    "stage_ms": 0.0,
    "boundaries": 0,
    "boundary_stall_ms": 0.0,
}
_active = False
# monotonic stamp armed at a task boundary (after the previous task's
# window drained and its bookkeeping ran) and closed by the FIRST
# dispatch of the next task — the gap is the boundary_stall counter.
# Single-writer (the dispatch thread), so no lock on the mark itself.
_boundary_mark = None


def _note_staged(stage_secs: float):
    global _active
    with _TOTALS_LOCK:
        _active = True
        _TOTALS["groups"] += 1
        _TOTALS["stage_ms"] += stage_secs * 1000.0


def _note_stall(stall_secs: float):
    global _active
    with _TOTALS_LOCK:
        _active = True
        _TOTALS["stall_ms"] += stall_secs * 1000.0


def _boundary_armed() -> bool:
    """Whether boundary-stall timing is worth a clock read: a stager
    ran in this process (the pipelined paths) or an anatomy recorder is
    installed (the serial measurement windows)."""
    if _active:
        return True
    from elasticdl_tpu.telemetry.anatomy import get_recorder

    return get_recorder() is not None


def note_task_boundary():  # elastic-lint: hot-path
    """Arm the boundary-stall clock — called at each task boundary, as
    soon as the previous task's window has drained and BEFORE its
    boundary bookkeeping (report, milestone checks, memory sample)
    runs, so the counter covers the whole device-idle gap the fused
    path shrinks.  Unarmed (no stager, no anatomy) this is one
    zero-arg gate call."""
    global _boundary_mark
    if not _boundary_armed():
        return
    _boundary_mark = time.monotonic()


def note_boundary_dispatch():  # elastic-lint: hot-path
    """Close a pending boundary mark: the FIRST dispatch after a task
    boundary records the device-idle gap as ``boundary_stall``.  Every
    other dispatch pays one global load and a None check."""
    global _boundary_mark, _active
    mark = _boundary_mark
    if mark is None:
        return
    _boundary_mark = None
    gap = time.monotonic() - mark
    with _TOTALS_LOCK:
        _active = True
        _TOTALS["boundaries"] += 1
        _TOTALS["boundary_stall_ms"] += gap * 1000.0


def clear_boundary_mark():
    """Disarm a pending boundary mark (end of run / stream teardown),
    so the final task's mark never attributes cross-run idle time to
    the first dispatch of a LATER run in the same process."""
    global _boundary_mark
    _boundary_mark = None


def heartbeat_snapshot() -> dict:  # elastic-lint: hot-path
    """Monotone staging totals for ``HeartbeatRequest.prefetch``; ``{}``
    when no stager ever ran in this process (the off state costs one
    global load, like the anatomy snapshot)."""
    if not _active:
        return {}
    with _TOTALS_LOCK:
        return {
            "groups": int(_TOTALS["groups"]),
            "stall_ms": int(_TOTALS["stall_ms"]),
            "stage_ms": int(_TOTALS["stage_ms"]),
            "boundaries": int(_TOTALS["boundaries"]),
            "boundary_stall_ms": int(_TOTALS["boundary_stall_ms"]),
        }


def _reset_totals_for_tests():
    global _active, _boundary_mark
    with _TOTALS_LOCK:
        _active = False
        _boundary_mark = None
        for key in _TOTALS:
            _TOTALS[key] = 0


# ---- staged groups ----------------------------------------------------------


class RetiredBufferError(RuntimeError):
    """A staged group's device buffers were taken twice.

    With ``donate_batch`` the buffers are DONATED to the first dispatch
    — XLA reuses their memory for outputs — so a second consumer would
    read garbage (or trip JAX's deleted-Array check).  Single ``take()``
    ownership turns that read-after-retire into a loud, immediate
    error at the staging layer."""


class StagedGroup:
    """One dispatch group, already assembled and device-resident.

    ``kind``: ``KIND_STACKED`` — ``placed`` is the ``(features, labels,
    weights)`` stacked ``(k, rows, ...)`` tuple for one
    ``train_steps_stacked`` dispatch; ``KIND_SINGLES`` — ``placed`` is a
    list of per-batch ``(features, labels, mask)`` tuples (a trailing
    partial group, dispatched through the single-step program).

    ``hook_features``: one host features ref per STEP, for the
    consumer's ``pre_batch`` hook cadence.  ``host``: the original host
    item(s), kept so a failed dispatch can retry from host memory after
    the staged buffers were donated.

    ``error``: staging itself (assemble or placement) failed — no
    placed buffers exist, but ``host`` still carries the group, so the
    task-stream worker can fall back to its serial per-minibatch
    retry/containment path instead of losing the error policy the
    serial loop had (the grouped runtimes re-raise, which is exactly
    what their serial path would have done)."""

    KIND_STACKED = "stacked"
    KIND_SINGLES = "singles"

    __slots__ = (
        "kind",
        "steps",
        "records",
        "hook_features",
        "host",
        "error",
        "nbytes",
        "_placed",
        "_release",
    )

    def __init__(
        self, kind, placed, steps, records, hook_features, host=None,
        error=None, nbytes=0, release=None,
    ):
        self.kind = kind
        self.steps = int(steps)
        self.records = int(records)
        self.hook_features = hook_features
        self.host = host
        self.error = error
        # staged device bytes this group holds until taken (memory
        # ledger accounting); `release` hands them back to the stager
        self.nbytes = int(nbytes)
        self._placed = placed
        self._release = release

    def take(self):
        """Transfer ownership of the placed buffers to the caller —
        exactly once.  The dispatch donates them; a second take is a
        read-after-retire and raises :class:`RetiredBufferError`."""
        if self._placed is None:
            raise RetiredBufferError(
                "staged dispatch group already taken: its device buffers "
                "were donated to the dispatch and no longer exist"
            )
        placed, self._placed = self._placed, None
        if self._release is not None:
            release, self._release = self._release, None
            release(self.nbytes)
        return placed


def _batch_rows(tree) -> int:
    leaves = jax.tree_util.tree_leaves(tree)
    return int(np.shape(leaves[0])[0]) if leaves else 0


def _assemble_prestacked(item: PreStacked):
    """A ready-made ``(k, B, ...)`` group with its all-ones scan-shape
    weights (``stacking.prestacked_weights`` — the shared policy)."""
    return (item.features, item.labels, prestacked_weights(item))


def _place_assembled(trainer, kind, assembled):
    if kind == StagedGroup.KIND_STACKED:
        feats, labels, weights = assembled
        return (
            trainer.place_stacked(feats),
            trainer.place_stacked(labels),
            trainer.place_stacked(weights),
        )
    return [
        (
            trainer.place_batch(f),
            trainer.place_batch(l),
            trainer.place_batch(m),
        )
        for f, l, m in assembled
    ]


class TaskMark:
    """In-stream task delimiter for cross-task staging
    (:func:`run_pipelined_task_stream` and the task-stream worker's
    fused loop).

    ``START`` — the next groups belong to this task (open its span,
    reset per-task accounting); ``END`` — all of the task's groups were
    handed over (retire the window, run the boundary bookkeeping).  The
    stager forwards marks in stream order and FLUSHES any pending
    partial group at a mark, so a trailing partial of task N never
    merges with task N+1's first batch — grouping (and therefore the
    dispatch-shape sequence) stays per-task, bit-identical to the
    drain-at-boundary path.

    ``payload`` carries an arbitrary serial item for tasks that do not
    stage (evaluation, non-training types): the consumer processes it
    inline at the mark's position, preserving stream order."""

    START = "start"
    END = "end"

    __slots__ = ("kind", "tid", "task", "payload")

    def __init__(self, kind, tid, task, payload=None):
        self.kind = kind
        self.tid = tid
        self.task = task
        self.payload = payload


# ---- the staging thread -----------------------------------------------------


class DeviceStager:
    """Background host->device staging for a canonical-shape batch
    stream.

    A daemon thread walks ``batches`` (plain ``(features, labels)``
    pairs and/or :class:`~elasticdl_tpu.trainer.stacking.PreStacked`
    groups), forms dispatch groups of ``k`` under the shared grouping
    policy, assembles and PLACES them on device, and hands
    :class:`StagedGroup` objects to the consumer through a bounded
    queue (:data:`STAGE_DEPTH`) — so the h2d transfer of group N+1
    overlaps the device compute of group N.  Groups arrive in exact
    stream order (single producer, FIFO queue); a producer-side error
    is re-raised by :meth:`next_staged` at its position in the stream.

    Placement from a non-dispatch thread is safe: ``device_put`` /
    ``make_array_from_callback`` are process-local (no collectives), and
    the trainer's placement caches are pure memoizations (a benign
    double-compute under the GIL).  The lockstep dispatch ORDER stays on
    the consumer thread, untouched.
    """

    def __init__(
        self,
        get_trainer: Callable,
        batches: Iterable,
        k,
        canonical_rows: int,
        deterministic_auto: bool = False,
        depth: int = STAGE_DEPTH,
    ):
        self._get_trainer = get_trainer
        self._batches = batches
        self._k = k
        self._rows = int(canonical_rows)
        self._deterministic_auto = deterministic_auto
        self._depth = max(1, int(depth))
        self._q: queue.Queue = queue.Queue(maxsize=self._depth)
        # admission control (memory ledger): how many staged groups may
        # wait un-taken.  Starts at the configured depth and degrades —
        # loudly, once — to 1 when staged bytes would exceed the budget
        # (env override, else half the live device headroom).
        self._admitted = self._depth
        self._stop = threading.Event()
        self._done = False
        # staged-but-untaken device bytes (memory ledger): incremented
        # when a group lands in the queue, released at take()
        self._bytes_lock = threading.Lock()
        self._staged_bytes = 0  # guarded-by: _bytes_lock
        from elasticdl_tpu.telemetry import memory as memory_mod

        self._ledger_cb = lambda: self._staged_bytes
        memory_mod.register_component(
            memory_mod.COMPONENT_DEVICE_STAGER, self._ledger_cb
        )
        self._thread = threading.Thread(
            target=self._produce, name="device-stage", daemon=True
        )
        self._thread.start()

    # ---- producer ----------------------------------------------------------

    def _put(self, item) -> bool:
        """Bounded put that aborts when the consumer closed us (the
        queue bound is the device-memory bound: at most ``depth`` staged
        groups wait while one more is in assembly).  A degraded
        ``_admitted`` shrinks the effective bound below the queue's
        configured maxsize."""
        while not self._stop.is_set():
            # only the DEGRADED state needs the poll: at full admission
            # the queue's own maxsize is the bound, and its blocking put
            # wakes the instant the consumer takes a slot
            if (
                self._admitted < self._depth
                and self._q.qsize() >= self._admitted
            ):
                self._stop.wait(0.02)
                continue
            try:
                self._q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _admit(self, nbytes: int):
        """Admission against the staging budget: when the staged-but-
        untaken bytes plus this group would exceed it, degrade the
        staging depth to 1 for the rest of this stager's life."""
        if self._admitted <= 1:
            return
        budget = staging_budget_bytes()
        if budget is None:
            return
        with self._bytes_lock:
            pending = self._staged_bytes
        if pending + nbytes <= budget:
            return
        self._admitted = 1
        from elasticdl_tpu.utils.log_utils import default_logger

        default_logger.warning(
            "device_stager: staged bytes %d + next group %d exceed the "
            "staging budget %d; degrading staging depth %d -> 1 (set "
            "%s to override the budget)",
            pending,
            nbytes,
            budget,
            self._depth,
            STAGING_BUDGET_ENV,
        )

    def _stage(self, trainer, assemble, steps, records, hooks, host):
        """Assemble + place one group; a STAGING failure (bad batch
        shape, transient placement error) degrades to an error-carrying
        group instead of poisoning the stream — upstream ITERATOR
        errors (decode) keep the crash contract via ``_produce``'s
        outer handler."""
        t0 = time.monotonic()
        try:
            kind, assembled = assemble()
            placed = _place_assembled(trainer, kind, assembled)
        except Exception as e:  # noqa: BLE001 — consumer decides policy
            staged = StagedGroup(
                StagedGroup.KIND_SINGLES,
                None,
                steps=steps,
                records=records,
                hook_features=hooks,
                host=host,
                error=e,
            )
            return self._put((_STAGE_KIND_GROUP, staged))
        from elasticdl_tpu.telemetry.memory import pytree_bytes

        nbytes = pytree_bytes(placed)
        staged = StagedGroup(
            kind,
            placed,
            steps=steps,
            records=records,
            hook_features=hooks,
            host=host,
            nbytes=nbytes,
            release=self._release_bytes,
        )
        self._admit(nbytes)
        with self._bytes_lock:
            self._staged_bytes += nbytes
        _note_staged(time.monotonic() - t0)
        return self._put((_STAGE_KIND_GROUP, staged))

    def _release_bytes(self, nbytes: int):
        with self._bytes_lock:
            self._staged_bytes -= nbytes

    def _stage_plain(self, trainer, group) -> bool:
        return self._stage(
            trainer,
            lambda: assemble_canonical_group(
                trainer, group, self._k, self._rows
            ),
            steps=len(group),
            records=sum(n for _f, _l, n in group),
            hooks=[f for f, _l, _n in group],
            host=list(group),
        )

    def _stage_prestacked(self, trainer, item: PreStacked) -> bool:
        return self._stage(
            trainer,
            lambda: (
                StagedGroup.KIND_STACKED,
                _assemble_prestacked(item),
            ),
            steps=item.num_steps,
            records=item.num_records,
            hooks=[item.sample_features] * item.num_steps,
            host=item,
        )

    def _produce(self):
        group: list = []
        try:
            trainer = self._get_trainer()
            for item in self._batches:
                if self._stop.is_set():
                    return
                if isinstance(item, TaskMark):
                    # task boundary: flush the pending partial group —
                    # grouping resets per task, so the dispatch-shape
                    # sequence matches the drain-at-boundary path —
                    # then forward the mark in stream order
                    if group:
                        if not self._stage_plain(trainer, group):
                            return
                        group = []
                    if not self._put((_STAGE_KIND_MARK, item)):
                        return
                    continue
                if isinstance(item, PreStacked):
                    # ready-made group: flush pending plain batches first
                    # (stream order is the contract)
                    if group:
                        if not self._stage_plain(trainer, group):
                            return
                        group = []
                    if not self._stage_prestacked(trainer, item):
                        return
                    continue
                features, labels = item
                if self._k == "auto":
                    self._k = resolve_steps_per_dispatch(
                        self._k,
                        (features, labels),
                        deterministic=self._deterministic_auto,
                    )
                group.append((features, labels, _batch_rows(labels)))
                if len(group) == self._k:
                    if not self._stage_plain(trainer, group):
                        return
                    group = []
            if group and not self._stage_plain(trainer, group):
                return
        except BaseException as e:  # noqa: BLE001 — re-raised by consumer
            self._put((_STAGE_KIND_ERROR, e))
            return
        self._put((_STAGE_KIND_DONE, None))

    # ---- consumer ----------------------------------------------------------

    def next_event(self, anatomy=None):
        """The next stream event as a ``(kind, payload)`` pair — a
        staged GROUP, a :class:`TaskMark` (cross-task streams), DONE,
        or a producer-side ERROR (returned, not raised: the cross-task
        consumer owns the boundary policy).

        The blocking wait is the CONSUMER-VISIBLE h2d cost — everything
        the stager overlapped is gone from this thread's critical path —
        so under ``--step_anatomy`` it is attributed to the
        ``h2d_transfer`` phase (whose share dropping vs prefetch-off is
        the goodput smoke's gate)."""
        if self._done:
            return _STAGE_KIND_DONE, None
        if anatomy is None:
            t0 = time.monotonic()
            kind, payload = self._q.get()
            _note_stall(time.monotonic() - t0)
        else:
            from elasticdl_tpu.telemetry.anatomy import PHASE_H2D_TRANSFER

            with anatomy.phase(PHASE_H2D_TRANSFER):
                t0 = time.monotonic()
                kind, payload = self._q.get()
                _note_stall(time.monotonic() - t0)
        if kind in (_STAGE_KIND_DONE, _STAGE_KIND_ERROR):
            self._done = True
        return kind, payload

    def next_staged(self, anatomy=None) -> StagedGroup | None:
        """The next :class:`StagedGroup` in stream order, or None at end
        of stream; a producer-side error (decode failure, placement
        failure) is re-raised here, at its position in the stream.
        Marks, if the stream carries any, are skipped."""
        while True:
            kind, payload = self.next_event(anatomy)
            if kind == _STAGE_KIND_DONE:
                return None
            if kind == _STAGE_KIND_ERROR:
                raise payload
            if kind == _STAGE_KIND_MARK:
                continue
            return payload

    def __iter__(self):
        while True:
            staged = self.next_staged()
            if staged is None:
                return
            yield staged

    def close(self):
        """Stop the producer and release it if blocked on a full
        queue."""
        self._stop.set()
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=5)
        # drop the ledger callback: a closed stager (and any untaken
        # staged buffers) must not be pinned by the component registry
        from elasticdl_tpu.telemetry import memory as memory_mod

        memory_mod.unregister_component(
            memory_mod.COMPONENT_DEVICE_STAGER, self._ledger_cb
        )


# ---- the pipelined dispatch loop --------------------------------------------


class _DispatchEngine:
    """The dispatch half of the pipelined loops — single-take dispatch,
    hook cadence, retire-behind window, anatomy attribution — shared by
    :func:`run_pipelined_steps` (per-task) and
    :func:`run_pipelined_task_stream` (cross-task), so the parity pins
    on one cover both."""

    def __init__(self, get_trainer, depth, pre_batch, post_group, ctx, anatomy):
        from elasticdl_tpu.telemetry.anatomy import timed_device_dispatch

        self._timed = timed_device_dispatch
        self._get_trainer = get_trainer
        self._depth = depth
        self._pre = pre_batch
        self._post = post_group
        self._ctx = ctx
        self._anatomy = anatomy
        self._inflight: deque = deque()
        self.processed = 0

    def _retire_push(self, out):
        # async retire-behind: keep at most `depth` dispatched groups
        # un-retired; blocking on the OLDEST keeps the device queue
        # bounded while group N+1's enqueue overlaps group N's compute
        self._inflight.append(out)
        if len(self._inflight) > self._depth:
            jax.block_until_ready(self._inflight.popleft())

    def _dispatch_stacked(self, trainer, placed):
        if self._anatomy is None:
            with self._ctx():
                out = trainer.train_steps_stacked(*placed)
            self._retire_push(out)
            return
        with self._ctx():
            self._timed(
                self._anatomy, lambda: trainer.train_steps_stacked(*placed)
            )

    def _dispatch_singles(self, trainer, placed_list):
        for placed in placed_list:
            if self._anatomy is None:
                with self._ctx():
                    out = trainer.train_step(*placed)
                self._retire_push(out)
            else:
                with self._ctx():
                    self._timed(
                        self._anatomy,
                        lambda placed=placed: trainer.train_step(*placed),
                    )

    def dispatch(self, staged: StagedGroup, run_hooks: bool = True):
        if staged.error is not None:
            # staging failed: the serial path would have raised from the
            # same pad/place call on this thread — keep that contract
            # (lockstep report-and-crash, LocalExecutor propagation)
            raise staged.error
        if run_hooks and self._pre is not None:
            for feats in staged.hook_features:
                self._pre(feats)
        trainer = self._get_trainer()
        note_boundary_dispatch()
        if staged.kind == StagedGroup.KIND_STACKED:
            self._dispatch_stacked(trainer, staged.take())
        else:
            self._dispatch_singles(trainer, staged.take())
        self.processed += staged.records
        if self._post is not None:
            self._post()
        if self._anatomy is not None:
            self._anatomy.commit(
                steps=staged.steps,
                records=staged.records,
                step=getattr(trainer, "step", None),
            )

    def drain(self):
        # the boundary barrier: every dispatched group retires before
        # the caller may report its task (exactly-once)
        while self._inflight:
            jax.block_until_ready(self._inflight.popleft())


def run_pipelined_steps(
    get_trainer: Callable,
    batches: Iterable,
    k,
    pre_batch: Callable | None = None,
    post_group: Callable | None = None,
    dispatch_ctx: Callable | None = None,
    deterministic_auto: bool = False,
    canonical_rows: int | None = None,
    anatomy=None,
    pipeline_depth: int | None = None,
) -> int:
    """The ``--device_prefetch`` body of
    :func:`~elasticdl_tpu.trainer.stacking.run_stacked_steps`
    (canonical-shape mode only — staging requires shapes that are pure
    functions of config).  Same grouping policy, same hook cadence
    (``pre_batch`` once per step before its group dispatches — the
    PreStacked precedent — ``post_group`` after every dispatch), same
    accounting; what changes is the execution discipline:

    - the FIRST group runs on the serial path (its ``pre_batch`` lazily
      creates the trainer the stager needs for placement), then a
      :class:`DeviceStager` stages every later group off-thread;
    - dispatch outputs retire one group behind in a window of
      :func:`stage_depth` (``pipeline_depth``, default 2; 1 — the
      per-group barrier — under ``--step_anatomy``), and the function
      DRAINS before returning, so the caller's task report never covers
      an un-retired group (exactly-once holds across the async window).
    """
    from elasticdl_tpu.telemetry.anatomy import (
        PHASE_ASSEMBLE,
        PHASE_H2D_TRANSFER,
        PHASE_HOST_FETCH,
    )

    ctx = dispatch_ctx or contextlib.nullcontext
    rows = int(canonical_rows)
    depth = stage_depth(anatomy, pipeline_depth)
    if anatomy is not None:
        pre_batch = anatomy.wrapped_hook(pre_batch)
        post_group = anatomy.wrapped_hook(post_group)
    engine = _DispatchEngine(
        get_trainer, depth, pre_batch, post_group, ctx, anatomy
    )
    _dispatch = engine.dispatch

    it = iter(batches)

    def _pull():
        if anatomy is None:
            return next(it, None)
        with anatomy.phase(PHASE_HOST_FETCH):
            return next(it, None)

    # ---- warmup: first group on the serial path (creates the trainer) ------
    warm: list = []
    warm_prestacked = None
    ended = False
    while True:
        item = _pull()
        if item is None:
            ended = True
            break
        if isinstance(item, PreStacked):
            warm_prestacked = item
            break
        features, labels = item
        if pre_batch is not None:
            pre_batch(features)
        if k == "auto":
            k = resolve_steps_per_dispatch(
                k, (features, labels), deterministic=deterministic_auto
            )
        warm.append((features, labels, _batch_rows(labels)))
        if len(warm) == k:
            break

    def _warm_stage(trainer, kind_assembled):
        kind, assembled = kind_assembled
        if anatomy is None:
            return kind, _place_assembled(trainer, kind, assembled)
        with anatomy.phase(PHASE_H2D_TRANSFER):
            return kind, _place_assembled(trainer, kind, assembled)

    if warm:
        trainer = get_trainer()
        if anatomy is None:
            kind_assembled = assemble_canonical_group(trainer, warm, k, rows)
        else:
            with anatomy.phase(PHASE_ASSEMBLE):
                kind_assembled = assemble_canonical_group(trainer, warm, k, rows)
        kind, placed = _warm_stage(trainer, kind_assembled)
        _dispatch(
            StagedGroup(
                kind,
                placed,
                steps=len(warm),
                records=sum(n for _f, _l, n in warm),
                hook_features=(),
            ),
            run_hooks=False,  # already ran as the batches arrived
        )
    if warm_prestacked is not None:
        if pre_batch is not None:
            # one call per STEP, the plain path's hook cadence
            for _ in range(warm_prestacked.num_steps):
                pre_batch(warm_prestacked.sample_features)
        trainer = get_trainer()
        kind, placed = _warm_stage(
            trainer,
            (StagedGroup.KIND_STACKED, _assemble_prestacked(warm_prestacked)),
        )
        _dispatch(
            StagedGroup(
                kind,
                placed,
                steps=warm_prestacked.num_steps,
                records=warm_prestacked.num_records,
                hook_features=(),
            ),
            run_hooks=False,
        )

    if ended:
        engine.drain()
        return engine.processed

    # ---- steady state: stage off-thread, retire one group behind -----------
    stager = DeviceStager(
        get_trainer,
        it,
        k,
        rows,
        deterministic_auto=deterministic_auto,
        depth=max(1, depth - 1),
    )
    try:
        while True:
            staged = stager.next_staged(anatomy)
            if staged is None:
                break
            _dispatch(staged)
    finally:
        stager.close()
        # the task-boundary barrier: every dispatched group retires
        # before the caller can report the task (exactly-once)
        engine.drain()
    return engine.processed


def run_pipelined_task_stream(
    get_trainer: Callable,
    tasks: Iterable,
    k,
    pre_batch: Callable | None = None,
    post_group: Callable | None = None,
    dispatch_ctx: Callable | None = None,
    deterministic_auto: bool = False,
    canonical_rows: int | None = None,
    anatomy=None,
    task_start: Callable | None = None,
    task_done: Callable | None = None,
    pipeline_depth: int | None = None,
) -> int:
    """The ``--boundary_fusion`` task loop: one persistent
    :class:`DeviceStager` walks the WHOLE task stream, so task N+1's
    first groups assemble and place while task N's last groups compute,
    and the boundary barrier shrinks from "drain + re-stage from host"
    to "retire the previous task's in-flight window".

    ``tasks`` yields ``(task_id, task, batches)`` triples (the
    ``TaskPrefetcher`` consumer shape); the stream is pulled from the
    STAGER thread, so host decode keeps running through boundaries too.
    ``task_start(task_id, task)`` runs when a task's first group is
    about to dispatch; ``task_done(task_id, task, records)`` is the
    boundary bookkeeping (report, milestone checks, memory sample) and
    runs only AFTER that task's own dispatch window drained — a task is
    reported exactly when all its groups retired (exactly-once), while
    the stager concurrently stages the next task.

    The FIRST task runs through :func:`run_pipelined_steps` (its serial
    warmup creates the trainer the persistent stager needs for
    placement).  If ``task_done`` raises (lease reclaimed, preemption
    fence), the stager closes and every staged-but-undispatched group
    dies un-taken — never dispatched, never reported, so a re-lease of
    those tasks replays them from scratch.  Bit-exactness: marks flush
    the grouping per task, so dispatch order, shapes and outputs are
    identical to the drain-at-boundary path.
    """
    it = iter(tasks)
    first = next(it, None)
    if first is None:
        return 0
    tid, task, batches = first
    if task_start is not None:
        task_start(tid, task)
    n = run_pipelined_steps(
        get_trainer,
        batches,
        k,
        pre_batch=pre_batch,
        post_group=post_group,
        dispatch_ctx=dispatch_ctx,
        deterministic_auto=deterministic_auto,
        canonical_rows=canonical_rows,
        anatomy=anatomy,
        pipeline_depth=pipeline_depth,
    )
    total = n
    note_task_boundary()
    if task_done is not None:
        task_done(tid, task, n)

    ctx = dispatch_ctx or contextlib.nullcontext
    depth = stage_depth(anatomy, pipeline_depth)
    if anatomy is not None:
        pre_batch = anatomy.wrapped_hook(pre_batch)
        post_group = anatomy.wrapped_hook(post_group)
    engine = _DispatchEngine(
        get_trainer, depth, pre_batch, post_group, ctx, anatomy
    )

    def _flatten():
        # runs on the stager thread: marks delimit tasks in-stream, so
        # the producer flushes grouping at each boundary and the
        # consumer learns boundaries in exact stream order
        for tid_, task_, batches_ in it:
            yield TaskMark(TaskMark.START, tid_, task_)
            for item in batches_:
                yield item
            yield TaskMark(TaskMark.END, tid_, task_)

    # one extra queue slot vs the per-task stager: the END/START marks
    # occupy slots at each boundary, and the whole point is for the
    # next task's first group to be staged while they drain
    stager = DeviceStager(
        get_trainer,
        _flatten(),
        k,
        int(canonical_rows),
        deterministic_auto=deterministic_auto,
        depth=depth,
    )
    task_records = 0
    try:
        while True:
            kind, payload = stager.next_event(anatomy)
            if kind == _STAGE_KIND_DONE:
                break
            if kind == _STAGE_KIND_ERROR:
                raise payload
            if kind == _STAGE_KIND_MARK:
                if payload.kind == TaskMark.START:
                    task_records = 0
                    if task_start is not None:
                        task_start(payload.tid, payload.task)
                else:
                    # the fused boundary: retire THIS task's window,
                    # then its bookkeeping — the stager keeps staging
                    # the next task's groups meanwhile
                    engine.drain()
                    note_task_boundary()
                    if task_done is not None:
                        task_done(payload.tid, payload.task, task_records)
                continue
            engine.dispatch(payload)
            total += payload.records
            task_records += payload.records
    finally:
        stager.close()
        engine.drain()
        clear_boundary_mark()
    return total
