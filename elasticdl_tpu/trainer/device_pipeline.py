"""Device-path pipelining: double-buffered h2d staging, batch-buffer
donation and async retire-behind dispatch.

BENCH_r04 measured every e2e config binding on ``device_path`` with
``vs_step_only`` ~0.1: the jitted step standalone is ~10x faster than
the end-to-end record flow, and PR 9's anatomy says the gap is
host-side serialization — every dispatch group's batch is padded,
stacked and placed on device ON the dispatching thread, between
dispatches.  This module closes that gap for the canonical-shape path
(shapes are pure functions of config since PR 5, so staging buffers
never change shape):

- **Staging** (:class:`DeviceStager`): a daemon thread pulls host
  batches from the upstream stream (the ``TaskPrefetcher`` host queue,
  so decode -> stage -> compute form a three-deep pipeline), assembles
  them to the canonical dispatch shape and places them on device while
  the CURRENT group computes.  The queue is bounded (double buffering:
  one group being consumed, one staged, one in assembly) so device
  memory stays bounded.
- **Donation**: the runtimes construct their ``SPMDTrainer`` with
  ``donate_batch=True`` when the feature is on, extending ``jax.jit``
  donation from state-only to the batch/mask buffers — XLA reuses the
  staged input buffers for outputs, so steady-state dispatches do zero
  fresh h2d allocations.  A donated buffer is dead after its dispatch;
  :class:`StagedGroup` enforces single ``take()`` ownership so a
  read-after-retire is caught at the staging layer too (and JAX itself
  raises on a deleted Array — both are pinned by falsification tests).
- **Retire-behind** (:func:`run_pipelined_steps`): dispatch outputs are
  retired one group behind inside a bounded in-flight window
  (:data:`RETIRE_WINDOW`), so XLA async dispatch actually overlaps; the
  full barrier is retained at task boundaries (the function drains
  before returning, so a task is only ever reported after every one of
  its groups retired), and ``--step_anatomy`` collapses the window to 1
  (:func:`stage_depth`) because exact per-group walls need the
  per-group block — the documented cost of measuring.

Enablement: the master's ``--device_prefetch`` flag, env-forwarded to
workers as ``ELASTICDL_TPU_DEVICE_PREFETCH`` (never argv — worker
command lines stay byte-identical with the feature off).  Disabled
cost: the runtimes resolve the flag ONCE at build time and
``run_stacked_steps`` takes one boolean branch per call — no thread, no
queue, no clock reads (the annotated gates below are machine-checked by
elastic-lint's hot-path checker).

Lockstep safety: staging changes WHEN placement happens, never what is
dispatched — dispatch order, shapes and programs remain pure functions
of (task data, k, canonical rows), identical on every process.  The
enabling env is master-forwarded, so a world can never mix donated and
undonated step programs.
"""

from __future__ import annotations

import contextlib
import os
import queue
import threading
import time
from collections import deque
from typing import Callable, Iterable

import jax
import numpy as np

from elasticdl_tpu.trainer.stacking import (
    PreStacked,
    assemble_canonical_group,
    prestacked_weights,
    resolve_steps_per_dispatch,
)

DEVICE_PREFETCH_ENV = "ELASTICDL_TPU_DEVICE_PREFETCH"

# bounded in-flight dispatch window: how many dispatched groups may be
# un-retired before the consumer blocks on the oldest.  2 = the classic
# one-behind pipeline (group N computes while group N+1 enqueues).
RETIRE_WINDOW = 2
# staging queue depth: 1 = double buffering (one staged group ready
# while the consumer's current group dispatches; the stager may be
# assembling a third).
STAGE_DEPTH = 1

_STAGE_KIND_GROUP = "group"
_STAGE_KIND_ERROR = "error"
_STAGE_KIND_DONE = "done"


# ---- flag resolution (shared by all three runtimes) -------------------------


# explicit spellings the env accepts — the env must parse like the
# flag's parse_bool, not truthy-string: "0"/"false" silently ENABLING
# the feature on some hosts would build the mixed donated/undonated
# world the uniformity contract forbids, and an unrecognized spelling
# (typo) must fail SAFE (off, with an error log), never silently on
_FALSEY_ENV = frozenset({"", "0", "false", "no", "off"})
_TRUTHY_ENV = frozenset({"1", "true", "yes", "on"})


def resolve_device_prefetch(flag=None) -> bool:
    """THE enablement rule: the master's ``--device_prefetch`` flag when
    set, else the master-forwarded env (workers never see the flag in
    argv; parse_bool spellings — ``1``/``true``/``yes``/``on`` on,
    ``0``/``false``/``no``/``off``/unset off, anything else logs an
    ERROR and stays off).  Resolved once per runtime at build time."""
    if flag is not None:
        return bool(flag)
    raw = os.environ.get(DEVICE_PREFETCH_ENV, "").strip().lower()
    if raw in _TRUTHY_ENV:
        return True
    if raw not in _FALSEY_ENV:
        from elasticdl_tpu.utils.log_utils import default_logger

        default_logger.error(
            "Unrecognized %s=%r; device prefetch stays OFF (use "
            "1/true/yes/on or 0/false/no/off)",
            DEVICE_PREFETCH_ENV,
            raw,
        )
    return False


def resolve_donate_state(args) -> bool:
    """THE ``--donate_state`` resolution — one definition site for what
    was copied verbatim into all three runtimes (local_executor, worker,
    lockstep).  Default True: the state buffers are always dead after
    the optimizer update."""
    return bool(getattr(args, "donate_state", True))


def stage_depth(anatomy) -> int:  # elastic-lint: hot-path
    """The retire window for a dispatch loop: ``RETIRE_WINDOW`` groups
    in flight normally; 1 (retire every group before the next dispatch)
    under ``--step_anatomy``, whose ``enqueue``/``ready_wait`` split
    needs exact per-group walls — the barrier the design doc documents
    as the cost of measuring."""
    if anatomy is None:
        return RETIRE_WINDOW
    return 1


# ---- heartbeat-shipped staging totals ---------------------------------------

_TOTALS_LOCK = threading.Lock()
# monotone process-lifetime totals; ms accumulate as floats here and
# ship as ints (the wire merge is utils.merge.max_merge_counters,
# integer-only — truncating per-event sub-ms samples would lose them)
_TOTALS = {"groups": 0, "stall_ms": 0.0, "stage_ms": 0.0}
_active = False


def _note_staged(stage_secs: float):
    global _active
    with _TOTALS_LOCK:
        _active = True
        _TOTALS["groups"] += 1
        _TOTALS["stage_ms"] += stage_secs * 1000.0


def _note_stall(stall_secs: float):
    global _active
    with _TOTALS_LOCK:
        _active = True
        _TOTALS["stall_ms"] += stall_secs * 1000.0


def heartbeat_snapshot() -> dict:  # elastic-lint: hot-path
    """Monotone staging totals for ``HeartbeatRequest.prefetch``; ``{}``
    when no stager ever ran in this process (the off state costs one
    global load, like the anatomy snapshot)."""
    if not _active:
        return {}
    with _TOTALS_LOCK:
        return {
            "groups": int(_TOTALS["groups"]),
            "stall_ms": int(_TOTALS["stall_ms"]),
            "stage_ms": int(_TOTALS["stage_ms"]),
        }


def _reset_totals_for_tests():
    global _active
    with _TOTALS_LOCK:
        _active = False
        for key in _TOTALS:
            _TOTALS[key] = 0


# ---- staged groups ----------------------------------------------------------


class RetiredBufferError(RuntimeError):
    """A staged group's device buffers were taken twice.

    With ``donate_batch`` the buffers are DONATED to the first dispatch
    — XLA reuses their memory for outputs — so a second consumer would
    read garbage (or trip JAX's deleted-Array check).  Single ``take()``
    ownership turns that read-after-retire into a loud, immediate
    error at the staging layer."""


class StagedGroup:
    """One dispatch group, already assembled and device-resident.

    ``kind``: ``KIND_STACKED`` — ``placed`` is the ``(features, labels,
    weights)`` stacked ``(k, rows, ...)`` tuple for one
    ``train_steps_stacked`` dispatch; ``KIND_SINGLES`` — ``placed`` is a
    list of per-batch ``(features, labels, mask)`` tuples (a trailing
    partial group, dispatched through the single-step program).

    ``hook_features``: one host features ref per STEP, for the
    consumer's ``pre_batch`` hook cadence.  ``host``: the original host
    item(s), kept so a failed dispatch can retry from host memory after
    the staged buffers were donated.

    ``error``: staging itself (assemble or placement) failed — no
    placed buffers exist, but ``host`` still carries the group, so the
    task-stream worker can fall back to its serial per-minibatch
    retry/containment path instead of losing the error policy the
    serial loop had (the grouped runtimes re-raise, which is exactly
    what their serial path would have done)."""

    KIND_STACKED = "stacked"
    KIND_SINGLES = "singles"

    __slots__ = (
        "kind",
        "steps",
        "records",
        "hook_features",
        "host",
        "error",
        "nbytes",
        "_placed",
        "_release",
    )

    def __init__(
        self, kind, placed, steps, records, hook_features, host=None,
        error=None, nbytes=0, release=None,
    ):
        self.kind = kind
        self.steps = int(steps)
        self.records = int(records)
        self.hook_features = hook_features
        self.host = host
        self.error = error
        # staged device bytes this group holds until taken (memory
        # ledger accounting); `release` hands them back to the stager
        self.nbytes = int(nbytes)
        self._placed = placed
        self._release = release

    def take(self):
        """Transfer ownership of the placed buffers to the caller —
        exactly once.  The dispatch donates them; a second take is a
        read-after-retire and raises :class:`RetiredBufferError`."""
        if self._placed is None:
            raise RetiredBufferError(
                "staged dispatch group already taken: its device buffers "
                "were donated to the dispatch and no longer exist"
            )
        placed, self._placed = self._placed, None
        if self._release is not None:
            release, self._release = self._release, None
            release(self.nbytes)
        return placed


def _batch_rows(tree) -> int:
    leaves = jax.tree_util.tree_leaves(tree)
    return int(np.shape(leaves[0])[0]) if leaves else 0


def _assemble_prestacked(item: PreStacked):
    """A ready-made ``(k, B, ...)`` group with its all-ones scan-shape
    weights (``stacking.prestacked_weights`` — the shared policy)."""
    return (item.features, item.labels, prestacked_weights(item))


def _place_assembled(trainer, kind, assembled):
    if kind == StagedGroup.KIND_STACKED:
        feats, labels, weights = assembled
        return (
            trainer.place_stacked(feats),
            trainer.place_stacked(labels),
            trainer.place_stacked(weights),
        )
    return [
        (
            trainer.place_batch(f),
            trainer.place_batch(l),
            trainer.place_batch(m),
        )
        for f, l, m in assembled
    ]


# ---- the staging thread -----------------------------------------------------


class DeviceStager:
    """Background host->device staging for a canonical-shape batch
    stream.

    A daemon thread walks ``batches`` (plain ``(features, labels)``
    pairs and/or :class:`~elasticdl_tpu.trainer.stacking.PreStacked`
    groups), forms dispatch groups of ``k`` under the shared grouping
    policy, assembles and PLACES them on device, and hands
    :class:`StagedGroup` objects to the consumer through a bounded
    queue (:data:`STAGE_DEPTH`) — so the h2d transfer of group N+1
    overlaps the device compute of group N.  Groups arrive in exact
    stream order (single producer, FIFO queue); a producer-side error
    is re-raised by :meth:`next_staged` at its position in the stream.

    Placement from a non-dispatch thread is safe: ``device_put`` /
    ``make_array_from_callback`` are process-local (no collectives), and
    the trainer's placement caches are pure memoizations (a benign
    double-compute under the GIL).  The lockstep dispatch ORDER stays on
    the consumer thread, untouched.
    """

    def __init__(
        self,
        get_trainer: Callable,
        batches: Iterable,
        k,
        canonical_rows: int,
        deterministic_auto: bool = False,
        depth: int = STAGE_DEPTH,
    ):
        self._get_trainer = get_trainer
        self._batches = batches
        self._k = k
        self._rows = int(canonical_rows)
        self._deterministic_auto = deterministic_auto
        self._q: queue.Queue = queue.Queue(maxsize=max(1, depth))
        self._stop = threading.Event()
        self._done = False
        # staged-but-untaken device bytes (memory ledger): incremented
        # when a group lands in the queue, released at take()
        self._bytes_lock = threading.Lock()
        self._staged_bytes = 0  # guarded-by: _bytes_lock
        from elasticdl_tpu.telemetry import memory as memory_mod

        self._ledger_cb = lambda: self._staged_bytes
        memory_mod.register_component(
            memory_mod.COMPONENT_DEVICE_STAGER, self._ledger_cb
        )
        self._thread = threading.Thread(
            target=self._produce, name="device-stage", daemon=True
        )
        self._thread.start()

    # ---- producer ----------------------------------------------------------

    def _put(self, item) -> bool:
        """Bounded put that aborts when the consumer closed us (the
        queue bound is the device-memory bound: at most ``depth`` staged
        groups wait while one more is in assembly)."""
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _stage(self, trainer, assemble, steps, records, hooks, host):
        """Assemble + place one group; a STAGING failure (bad batch
        shape, transient placement error) degrades to an error-carrying
        group instead of poisoning the stream — upstream ITERATOR
        errors (decode) keep the crash contract via ``_produce``'s
        outer handler."""
        t0 = time.monotonic()
        try:
            kind, assembled = assemble()
            placed = _place_assembled(trainer, kind, assembled)
        except Exception as e:  # noqa: BLE001 — consumer decides policy
            staged = StagedGroup(
                StagedGroup.KIND_SINGLES,
                None,
                steps=steps,
                records=records,
                hook_features=hooks,
                host=host,
                error=e,
            )
            return self._put((_STAGE_KIND_GROUP, staged))
        from elasticdl_tpu.telemetry.memory import pytree_bytes

        nbytes = pytree_bytes(placed)
        staged = StagedGroup(
            kind,
            placed,
            steps=steps,
            records=records,
            hook_features=hooks,
            host=host,
            nbytes=nbytes,
            release=self._release_bytes,
        )
        with self._bytes_lock:
            self._staged_bytes += nbytes
        _note_staged(time.monotonic() - t0)
        return self._put((_STAGE_KIND_GROUP, staged))

    def _release_bytes(self, nbytes: int):
        with self._bytes_lock:
            self._staged_bytes -= nbytes

    def _stage_plain(self, trainer, group) -> bool:
        return self._stage(
            trainer,
            lambda: assemble_canonical_group(
                trainer, group, self._k, self._rows
            ),
            steps=len(group),
            records=sum(n for _f, _l, n in group),
            hooks=[f for f, _l, _n in group],
            host=list(group),
        )

    def _stage_prestacked(self, trainer, item: PreStacked) -> bool:
        return self._stage(
            trainer,
            lambda: (
                StagedGroup.KIND_STACKED,
                _assemble_prestacked(item),
            ),
            steps=item.num_steps,
            records=item.num_records,
            hooks=[item.sample_features] * item.num_steps,
            host=item,
        )

    def _produce(self):
        group: list = []
        try:
            trainer = self._get_trainer()
            for item in self._batches:
                if self._stop.is_set():
                    return
                if isinstance(item, PreStacked):
                    # ready-made group: flush pending plain batches first
                    # (stream order is the contract)
                    if group:
                        if not self._stage_plain(trainer, group):
                            return
                        group = []
                    if not self._stage_prestacked(trainer, item):
                        return
                    continue
                features, labels = item
                if self._k == "auto":
                    self._k = resolve_steps_per_dispatch(
                        self._k,
                        (features, labels),
                        deterministic=self._deterministic_auto,
                    )
                group.append((features, labels, _batch_rows(labels)))
                if len(group) == self._k:
                    if not self._stage_plain(trainer, group):
                        return
                    group = []
            if group and not self._stage_plain(trainer, group):
                return
        except BaseException as e:  # noqa: BLE001 — re-raised by consumer
            self._put((_STAGE_KIND_ERROR, e))
            return
        self._put((_STAGE_KIND_DONE, None))

    # ---- consumer ----------------------------------------------------------

    def next_staged(self, anatomy=None) -> StagedGroup | None:
        """The next :class:`StagedGroup` in stream order, or None at end
        of stream; a producer-side error (decode failure, placement
        failure) is re-raised here, at its position in the stream.

        The blocking wait is the CONSUMER-VISIBLE h2d cost — everything
        the stager overlapped is gone from this thread's critical path —
        so under ``--step_anatomy`` it is attributed to the
        ``h2d_transfer`` phase (whose share dropping vs prefetch-off is
        the goodput smoke's gate)."""
        if self._done:
            return None
        if anatomy is None:
            t0 = time.monotonic()
            kind, payload = self._q.get()
            _note_stall(time.monotonic() - t0)
        else:
            from elasticdl_tpu.telemetry.anatomy import PHASE_H2D_TRANSFER

            with anatomy.phase(PHASE_H2D_TRANSFER):
                t0 = time.monotonic()
                kind, payload = self._q.get()
                _note_stall(time.monotonic() - t0)
        if kind == _STAGE_KIND_DONE:
            self._done = True
            return None
        if kind == _STAGE_KIND_ERROR:
            self._done = True
            raise payload
        return payload

    def __iter__(self):
        while True:
            staged = self.next_staged()
            if staged is None:
                return
            yield staged

    def close(self):
        """Stop the producer and release it if blocked on a full
        queue."""
        self._stop.set()
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=5)
        # drop the ledger callback: a closed stager (and any untaken
        # staged buffers) must not be pinned by the component registry
        from elasticdl_tpu.telemetry import memory as memory_mod

        memory_mod.unregister_component(
            memory_mod.COMPONENT_DEVICE_STAGER, self._ledger_cb
        )


# ---- the pipelined dispatch loop --------------------------------------------


def run_pipelined_steps(
    get_trainer: Callable,
    batches: Iterable,
    k,
    pre_batch: Callable | None = None,
    post_group: Callable | None = None,
    dispatch_ctx: Callable | None = None,
    deterministic_auto: bool = False,
    canonical_rows: int | None = None,
    anatomy=None,
) -> int:
    """The ``--device_prefetch`` body of
    :func:`~elasticdl_tpu.trainer.stacking.run_stacked_steps`
    (canonical-shape mode only — staging requires shapes that are pure
    functions of config).  Same grouping policy, same hook cadence
    (``pre_batch`` once per step before its group dispatches — the
    PreStacked precedent — ``post_group`` after every dispatch), same
    accounting; what changes is the execution discipline:

    - the FIRST group runs on the serial path (its ``pre_batch`` lazily
      creates the trainer the stager needs for placement), then a
      :class:`DeviceStager` stages every later group off-thread;
    - dispatch outputs retire one group behind in a window of
      :func:`stage_depth` (2 normally; 1 — the per-group barrier —
      under ``--step_anatomy``), and the function DRAINS before
      returning, so the caller's task report never covers an un-retired
      group (exactly-once holds across the async window).
    """
    from elasticdl_tpu.telemetry.anatomy import (
        PHASE_ASSEMBLE,
        PHASE_H2D_TRANSFER,
        PHASE_HOST_FETCH,
        timed_device_dispatch,
    )

    ctx = dispatch_ctx or contextlib.nullcontext
    rows = int(canonical_rows)
    depth = stage_depth(anatomy)
    if anatomy is not None:
        pre_batch = anatomy.wrapped_hook(pre_batch)
        post_group = anatomy.wrapped_hook(post_group)
    processed = 0
    inflight: deque = deque()

    def _retire_push(out):
        # async retire-behind: keep at most `depth` dispatched groups
        # un-retired; blocking on the OLDEST keeps the device queue
        # bounded while group N+1's enqueue overlaps group N's compute
        inflight.append(out)
        if len(inflight) > depth:
            jax.block_until_ready(inflight.popleft())

    def _dispatch_stacked(trainer, placed):
        if anatomy is None:
            with ctx():
                out = trainer.train_steps_stacked(*placed)
            _retire_push(out)
            return
        with ctx():
            timed_device_dispatch(
                anatomy, lambda: trainer.train_steps_stacked(*placed)
            )

    def _dispatch_singles(trainer, placed_list):
        for placed in placed_list:
            if anatomy is None:
                with ctx():
                    out = trainer.train_step(*placed)
                _retire_push(out)
            else:
                with ctx():
                    timed_device_dispatch(
                        anatomy,
                        lambda placed=placed: trainer.train_step(*placed),
                    )

    def _dispatch(staged: StagedGroup, run_hooks: bool = True):
        nonlocal processed
        if staged.error is not None:
            # staging failed: the serial path would have raised from the
            # same pad/place call on this thread — keep that contract
            # (lockstep report-and-crash, LocalExecutor propagation)
            raise staged.error
        if run_hooks and pre_batch is not None:
            for feats in staged.hook_features:
                pre_batch(feats)
        trainer = get_trainer()
        if staged.kind == StagedGroup.KIND_STACKED:
            _dispatch_stacked(trainer, staged.take())
        else:
            _dispatch_singles(trainer, staged.take())
        processed += staged.records
        if post_group is not None:
            post_group()
        if anatomy is not None:
            anatomy.commit(
                steps=staged.steps,
                records=staged.records,
                step=getattr(trainer, "step", None),
            )

    it = iter(batches)

    def _pull():
        if anatomy is None:
            return next(it, None)
        with anatomy.phase(PHASE_HOST_FETCH):
            return next(it, None)

    # ---- warmup: first group on the serial path (creates the trainer) ------
    warm: list = []
    warm_prestacked = None
    ended = False
    while True:
        item = _pull()
        if item is None:
            ended = True
            break
        if isinstance(item, PreStacked):
            warm_prestacked = item
            break
        features, labels = item
        if pre_batch is not None:
            pre_batch(features)
        if k == "auto":
            k = resolve_steps_per_dispatch(
                k, (features, labels), deterministic=deterministic_auto
            )
        warm.append((features, labels, _batch_rows(labels)))
        if len(warm) == k:
            break

    def _warm_stage(trainer, kind_assembled):
        kind, assembled = kind_assembled
        if anatomy is None:
            return kind, _place_assembled(trainer, kind, assembled)
        with anatomy.phase(PHASE_H2D_TRANSFER):
            return kind, _place_assembled(trainer, kind, assembled)

    if warm:
        trainer = get_trainer()
        if anatomy is None:
            kind_assembled = assemble_canonical_group(trainer, warm, k, rows)
        else:
            with anatomy.phase(PHASE_ASSEMBLE):
                kind_assembled = assemble_canonical_group(trainer, warm, k, rows)
        kind, placed = _warm_stage(trainer, kind_assembled)
        _dispatch(
            StagedGroup(
                kind,
                placed,
                steps=len(warm),
                records=sum(n for _f, _l, n in warm),
                hook_features=(),
            ),
            run_hooks=False,  # already ran as the batches arrived
        )
    if warm_prestacked is not None:
        if pre_batch is not None:
            # one call per STEP, the plain path's hook cadence
            for _ in range(warm_prestacked.num_steps):
                pre_batch(warm_prestacked.sample_features)
        trainer = get_trainer()
        kind, placed = _warm_stage(
            trainer,
            (StagedGroup.KIND_STACKED, _assemble_prestacked(warm_prestacked)),
        )
        _dispatch(
            StagedGroup(
                kind,
                placed,
                steps=warm_prestacked.num_steps,
                records=warm_prestacked.num_records,
                hook_features=(),
            ),
            run_hooks=False,
        )

    if ended:
        while inflight:
            jax.block_until_ready(inflight.popleft())
        return processed

    # ---- steady state: stage off-thread, retire one group behind -----------
    stager = DeviceStager(
        get_trainer,
        it,
        k,
        rows,
        deterministic_auto=deterministic_auto,
        depth=STAGE_DEPTH,
    )
    try:
        while True:
            staged = stager.next_staged(anatomy)
            if staged is None:
                break
            _dispatch(staged)
    finally:
        stager.close()
        # the task-boundary barrier: every dispatched group retires
        # before the caller can report the task (exactly-once)
        while inflight:
            jax.block_until_ready(inflight.popleft())
    return processed
