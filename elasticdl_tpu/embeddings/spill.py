"""Host-RAM spill tier: tables bigger than HBM, staged per step.

The reference's PS held EVERY sparse table in host RAM and served rows
over gRPC mid-forward (``embedding_delegate.py:64-96``) — which is why
it could host 100M-row tables on CPU pods, and why every lookup paid an
RPC.  The XLA translation keeps the host tier but moves it OUT of the
traced step: before dispatch, the runtime pulls exactly the UNIQUE rows
this batch touches from :class:`ShardedHostTable` (numpy row shards,
``shard_row_ranges`` ownership) into a fixed-capacity device minitable
written into ``state.params`` at the table's leaf; ids are remapped
onto minitable slots with ``np.searchsorted``; the UNCHANGED jitted
step runs (fixed shapes — one compile, ever); updated rows are read
back and scattered to the owning host shard (:meth:`commit`).

This is the honest analogue of ``pull_embedding_vector`` /
``push_gradient``: the pull/push still exists, but it is host-side
numpy indexing at batch cadence, not per-id RPC inside the forward.
The minitable trick constrains the optimizer to slot-free updates
(plain SGD — the rows outside this batch receive exactly zero gradient
and must not decay), which the runtime asserts rather than silently
mis-training momentum.

Byte accounting: every live table registers under the memory ledger's
``embedding_spill`` component (device-tier shards register under
``embedding_table`` via :func:`track_device_table`); teardown is
identity-guarded so a replacement owner registered under the same name
survives a stale owner's close.  Resident bytes are also exposed as
the ``elasticdl_embedding_bytes{table=,tier=}`` gauge family — the one
registration site for that required metric.
"""

from __future__ import annotations

import threading

import numpy as np

from elasticdl_tpu.embeddings import planner
from elasticdl_tpu.telemetry import memory as memory_ledger
from elasticdl_tpu.telemetry.registry import MetricsRegistry

# ---- metrics (the single elasticdl_embedding_bytes registration site) --------

_registry = MetricsRegistry()
_gauge_lock = threading.Lock()


def metrics_registry() -> MetricsRegistry:
    """The subsystem's registry — mounted by whichever /metrics endpoint
    the hosting process exposes (master hooks, serving replica, tests)."""
    return _registry


def set_table_bytes(table: str, tier: str, value: int):
    """Point the ``elasticdl_embedding_bytes`` gauge for one table/tier
    at its current resident bytes."""
    with _gauge_lock:
        gauge = _registry.gauge(
            "elasticdl_embedding_bytes",
            "Resident embedding bytes by table and tier",
            labels={"table": table, "tier": tier},
        )
    gauge.set(int(value))


# ---- ledger aggregation ------------------------------------------------------
#
# The ledger holds ONE callback per component, so per-table owners
# aggregate through module registries; the component callback identity
# is stable, which is exactly what makes unregister_component's
# identity guard meaningful (a foreign registration under the same
# name is left alone on teardown).

_spill_tables: dict[str, "ShardedHostTable"] = {}
_device_tables: dict[str, object] = {}
_tables_lock = threading.Lock()


def _spill_bytes() -> int:
    with _tables_lock:
        tables = list(_spill_tables.values())
    return sum(t.nbytes for t in tables)


def _device_bytes() -> int:
    with _tables_lock:
        fns = list(_device_tables.values())
    total = 0
    for fn in fns:
        try:
            total += int(fn())
        except Exception:  # noqa: BLE001 — accounting must never raise
            continue
    return total


def track_device_table(name: str, bytes_fn):
    """Account a device-tier table's local shard bytes under the
    ledger's ``embedding_table`` component (``bytes_fn`` -> current
    bytes of the rows THIS process holds)."""
    with _tables_lock:
        _device_tables[name] = bytes_fn
    memory_ledger.register_component(
        memory_ledger.COMPONENT_EMBEDDING_TABLE, _device_bytes
    )
    try:
        set_table_bytes(name, "device", int(bytes_fn()))
    except Exception:  # noqa: BLE001 — accounting must never raise
        pass


def untrack_device_table(name: str):
    with _tables_lock:
        _device_tables.pop(name, None)
        empty = not _device_tables
    set_table_bytes(name, "device", 0)
    if empty:
        memory_ledger.unregister_component(
            memory_ledger.COMPONENT_EMBEDDING_TABLE, _device_bytes
        )


# ---- the host tier -----------------------------------------------------------


class ShardedHostTable:
    """A ``(num_rows, dim)`` table held in host RAM as contiguous
    per-host row shards (``planner.shard_row_ranges`` ownership — the
    same convention as checkpoint parts, so harvest/restore and the
    spill tier agree about who owns row r).

    ``num_hosts`` simulates the multi-host layout on one machine the
    same way the CPU smokes simulate multi-process meshes; on a real
    fleet each process constructs only its own shard.
    """

    def __init__(
        self,
        name: str,
        num_rows: int,
        dim: int,
        num_hosts: int = 1,
        dtype=np.float32,
        seed: int = 0,
        init_scale: float = 0.05,
        rows: np.ndarray | None = None,
    ):
        self.name = name
        self.num_rows = int(num_rows)
        self.dim = int(dim)
        self.ranges = tuple(planner.shard_row_ranges(num_rows, num_hosts))
        if rows is not None:
            rows = np.asarray(rows)
            if rows.shape != (num_rows, dim):
                raise ValueError(
                    f"rows shape {rows.shape} != ({num_rows}, {dim})"
                )
            self._shards = [
                np.array(rows[lo:hi], dtype=dtype) for lo, hi in self.ranges
            ]
        else:
            rng = np.random.default_rng(seed)
            self._shards = [
                rng.uniform(-init_scale, init_scale, size=(hi - lo, dim)).astype(
                    dtype
                )
                for lo, hi in self.ranges
            ]
        self._closed = False
        with _tables_lock:
            _spill_tables[name] = self
        memory_ledger.register_component(
            memory_ledger.COMPONENT_EMBEDDING_SPILL, _spill_bytes
        )
        set_table_bytes(name, "spill", self.nbytes)

    @property
    def num_hosts(self) -> int:
        return len(self.ranges)

    @property
    def dtype(self):
        return self._shards[0].dtype if self._shards else np.dtype(np.float32)

    @property
    def nbytes(self) -> int:
        return sum(int(s.nbytes) for s in self._shards)

    def shard(self, host: int) -> np.ndarray:
        return self._shards[host]

    def _check_ids(self, ids: np.ndarray):
        if ids.size and (ids.min() < 0 or ids.max() >= self.num_rows):
            raise ValueError(
                f"table {self.name!r}: ids outside [0, {self.num_rows}) — "
                "the host tier refuses out-of-vocab ids instead of clipping"
            )

    def gather(self, ids) -> np.ndarray:
        """Rows for ``ids`` (1-D), assembled across owning shards."""
        ids = np.asarray(ids).ravel()
        self._check_ids(ids)
        out = np.empty((ids.size, self.dim), dtype=self.dtype)
        for (lo, hi), shard in zip(self.ranges, self._shards):
            mask = (ids >= lo) & (ids < hi)
            if mask.any():
                out[mask] = shard[ids[mask] - lo]
        return out

    def scatter(self, ids, rows):
        """Write ``rows`` back to the owning shards (last write wins on
        duplicate ids, matching numpy fancy-assignment)."""
        ids = np.asarray(ids).ravel()
        self._check_ids(ids)
        rows = np.asarray(rows)
        if rows.shape != (ids.size, self.dim):
            raise ValueError(
                f"rows shape {rows.shape} != ({ids.size}, {self.dim})"
            )
        for (lo, hi), shard in zip(self.ranges, self._shards):
            mask = (ids >= lo) & (ids < hi)
            if mask.any():
                shard[ids[mask] - lo] = rows[mask]

    def close(self):
        """Tear down: drop from the ledger aggregate (identity-guarded —
        a replacement component callback registered after this table's
        construction is left alone) and zero the gauge."""
        if self._closed:
            return
        self._closed = True
        with _tables_lock:
            if _spill_tables.get(self.name) is self:
                _spill_tables.pop(self.name, None)
            empty = not _spill_tables
        set_table_bytes(self.name, "spill", 0)
        if empty:
            memory_ledger.unregister_component(
                memory_ledger.COMPONENT_EMBEDDING_SPILL, _spill_bytes
            )


# ---- the per-step staging runtime --------------------------------------------


class SpillEmbeddingRuntime:
    """Stage/commit loop around an UNCHANGED jitted step.

    ``tables`` maps parameter paths inside ``params`` (e.g.
    ``"embedding/embedding"``) to their host tables; every table shares
    one id space (DeepFM's feature table and id-bias table are looked
    up with the same ids).  The model is built with ``input_dim =
    capacity`` so the staged minitables ARE the table leaves — fixed
    shapes, one compile.

    Id 0 always occupies slot 0: the staged unique-id set is
    ``np.unique([pad_id] + batch_ids)`` and ``np.unique`` sorts, so a
    model's mask-zero/pad conventions survive the remap verbatim.
    """

    def __init__(self, tables: dict, capacity: int, pad_id: int = 0, emit=None):
        if not tables:
            raise ValueError("SpillEmbeddingRuntime needs at least one table")
        sizes = {t.num_rows for t in tables.values()}
        if len(sizes) != 1:
            raise ValueError(
                f"tables must share one id space, got row counts {sizes}"
            )
        self._tables = dict(tables)
        self.capacity = int(capacity)
        self.pad_id = int(pad_id)
        self._emit = emit
        self.gathers = 0
        self.rows_gathered = 0

    @property
    def num_rows(self) -> int:
        return next(iter(self._tables.values())).num_rows

    def minitable_params(self, params):
        """``params`` with every table leaf replaced by a zero
        ``(capacity, dim)`` minitable — the shape the step compiles
        against (call once at state build)."""
        for path, table in self._tables.items():
            mini = np.zeros((self.capacity, table.dim), dtype=table.dtype)
            params = _with_leaf(params, path, mini)
        return params

    def stage(self, params, ids):
        """Pull the unique rows ``ids`` touches into the minitable
        leaves.  Returns ``(staged_params, remapped_ids, handle)``;
        pass ``handle`` to :meth:`commit` after the step."""
        ids_arr = np.asarray(ids)
        # negative ids are the sparse layer's missing-value sentinel —
        # never fetched, passed through remapping unchanged
        flat = ids_arr.ravel()
        flat = flat[flat >= 0]
        unique = np.unique(np.concatenate(([self.pad_id], flat)))
        if unique.size > self.capacity:
            raise ValueError(
                f"batch touches {unique.size} unique rows > minitable "
                f"capacity {self.capacity}; raise the capacity or shrink "
                "the batch"
            )
        remapped = np.searchsorted(unique, np.clip(ids_arr, 0, None))
        remapped = np.where(ids_arr < 0, ids_arr, remapped).astype(
            ids_arr.dtype
        )
        staged_bytes = 0
        for path, table in self._tables.items():
            mini = np.zeros((self.capacity, table.dim), dtype=table.dtype)
            mini[: unique.size] = table.gather(unique)
            staged_bytes += int(mini.nbytes)
            params = _with_leaf(params, path, mini)
        self.gathers += 1
        self.rows_gathered += int(unique.size)
        if self._emit is None:
            from elasticdl_tpu.telemetry.worker_hooks import emit_event

            emit = emit_event
        else:
            emit = self._emit
        try:
            from elasticdl_tpu.telemetry.events import EVENT_EMBEDDING_GATHER

            emit(
                EVENT_EMBEDDING_GATHER,
                rows=int(unique.size),
                tables=len(self._tables),
                staged_bytes=staged_bytes,
            )
        except Exception:  # noqa: BLE001 — telemetry never raises here
            pass
        return params, remapped, unique

    def commit(self, params, handle):
        """Scatter the (updated) staged rows back to their owning host
        shards; ``params`` is the post-step params, ``handle`` the
        unique-id array :meth:`stage` returned."""
        unique = np.asarray(handle)
        for path, table in self._tables.items():
            leaf = np.asarray(_get_leaf(params, path))
            table.scatter(unique, leaf[: unique.size])

    def close(self):
        for table in self._tables.values():
            table.close()


# ---- pytree path helpers (plain nested dicts, shallow-copied) ----------------


def _get_leaf(params, path: str):
    node = params
    for key in path.split("/"):
        node = node[key]
    return node


def _with_leaf(params, path: str, value):
    keys = path.split("/")
    out = dict(params)
    node = out
    for key in keys[:-1]:
        node[key] = dict(node[key])
        node = node[key]
    node[keys[-1]] = value
    return out


__all__ = [
    "ShardedHostTable",
    "SpillEmbeddingRuntime",
    "metrics_registry",
    "set_table_bytes",
    "track_device_table",
    "untrack_device_table",
]
