"""Sharded embedding subsystem — the XLA-native sparse data plane.

The reference hosted recommender-scale tables on a gRPC parameter
server (``ps/embedding_table.py``: id-hash dict shards, pulled
mid-forward by ``pull_embedding_vector``, gradients pushed back by
id-hash scatter).  This package completes the repo's founding "gRPC PS
-> XLA collectives" translation for that signature workload:

- :func:`sharded_table_rules` row-partitions declared
  ``SparseEmbedding`` tables over the mesh (ep > tp > fsdp, falling
  back to dp so pure-data-parallel ELASTIC worlds shard too — the axis
  is re-inferred every reform, so tables re-shard across slice loss);
  lookup lowers to gather -> all-to-all INSIDE the jitted step and the
  gradient scatter-add lands on the owning shard, both emitted by
  GSPMD from the ``P(axis, None)`` spec;
- :func:`plan_placement` admits each table onto a tier — device HBM
  when the shard fits the measured budget, else the host-RAM spill
  tier gated on the memory ledger's measured headroom
  (``host_memory_health``), raising :class:`EmbeddingAdmissionError`
  rather than walking the host into OOM;
- :class:`ShardedHostTable` + :class:`SpillEmbeddingRuntime` implement
  the spill tier: unique-row pull into a fixed-capacity minitable
  around an unchanged jitted step (one compile), scatter-back after;
- elasticity and serving ride the EXISTING owned-rows machinery:
  dim-0-sharded leaves checkpoint/replicate as per-host ``(ids,
  rows)`` parts (``parallel/elastic.state_checkpoint_parts``), slice
  loss re-forms them through harvest/restore by global row id, and the
  serving engine places tables by the same rules so hot swaps stay
  treedef-preserving with zero recompiles.

See docs/designs/sharded_embeddings.md for the full design.
"""

from elasticdl_tpu.embeddings.planner import (
    DEVICE_BUDGET_ENV,
    HOST_SHARE_ENV,
    EmbeddingAdmissionError,
    Placement,
    device_budget_bytes,
    embedding_axis,
    owning_shard,
    plan_placement,
    shard_row_ranges,
    sharded_table_rules,
)
from elasticdl_tpu.embeddings.spill import (
    ShardedHostTable,
    SpillEmbeddingRuntime,
    metrics_registry,
    set_table_bytes,
    track_device_table,
    untrack_device_table,
)

__all__ = [
    "DEVICE_BUDGET_ENV",
    "HOST_SHARE_ENV",
    "EmbeddingAdmissionError",
    "Placement",
    "ShardedHostTable",
    "SpillEmbeddingRuntime",
    "device_budget_bytes",
    "embedding_axis",
    "metrics_registry",
    "owning_shard",
    "plan_placement",
    "set_table_bytes",
    "shard_row_ranges",
    "sharded_table_rules",
    "track_device_table",
    "untrack_device_table",
]
