"""Placement planning for sharded embedding tables.

Answers the two questions the reference answered with its PS topology
(``ps/embedding_table.py`` hash-sharding ids over PS pods, and "the PS
is host RAM, full stop"):

1. WHICH mesh axis row-shards a declared table (:func:`embedding_axis`,
   :func:`sharded_table_rules`).  Preference order is ep (dedicated
   embedding axis) > tp > fsdp, same as the size-triggered policy in
   ``layers/embedding.py``; unlike that policy this one FALLS BACK TO
   ``dp``.  Rationale: the auto rules refuse dp because batch sharding
   lives there and replicated small tables are cheaper than an
   all-to-all — but a DECLARED sharded table is by definition too big to
   replicate, and dp is the one axis every elastic world has (it is
   re-inferred from the surviving process set on every reform, so a
   dp-sharded table re-shards across a slice loss instead of dying with
   a fixed ``ep=2`` mesh shape).  Batch ``P(dp)`` + table ``P(dp,
   None)`` makes GSPMD emit exactly the gather -> all-to-all exchange
   the reference hand-rolled over gRPC.

2. WHICH TIER holds the rows (:func:`plan_placement`): device HBM when
   the per-host shard fits the measured device budget, else the
   host-RAM spill tier — gated on the memory ledger's measured
   ``host_memory_health`` headroom rather than optimism.  A table
   neither tier admits raises :class:`EmbeddingAdmissionError` and
   emits ``embedding_spill_fault``: walking the host into OOM is the
   exact failure the ledger exists to prevent.

3. WHO owns which rows (:func:`shard_row_ranges`): contiguous
   ``np.array_split`` ranges, the same lowest-index-gets-the-remainder
   convention as ``parallel/elastic._owned_row_ranges`` so host-tier
   shard ownership and checkpoint-part ownership never disagree.
"""

from __future__ import annotations

import dataclasses
import os
import re

from elasticdl_tpu.telemetry import memory as memory_ledger
from elasticdl_tpu.utils.constants import MeshAxis
from elasticdl_tpu.utils.log_utils import default_logger as logger

# Device-tier byte budget override.  On CPU backends ``memory_stats()``
# is absent (the ledger's graceful-None contract), so the measured
# budget is unknowable and the device tier admits everything; smokes
# and tests set this to a small value to force tables onto the spill
# tier deterministically.
DEVICE_BUDGET_ENV = "ELASTICDL_TPU_EMBEDDING_DEVICE_BUDGET_BYTES"

# Fraction of host MemAvailable a spill table may claim (admission is
# against MEASURED availability, not MemTotal — other tenants count).
HOST_SHARE_ENV = "ELASTICDL_TPU_EMBEDDING_HOST_SHARE"
DEFAULT_HOST_SHARE = 0.5


class EmbeddingAdmissionError(RuntimeError):
    """Neither the device budget nor host-RAM headroom admits the table."""


def shard_row_ranges(num_rows: int, num_hosts: int) -> list[tuple[int, int]]:
    """Contiguous per-host row ranges ``[(lo, hi), ...]`` covering
    ``[0, num_rows)`` — ``np.array_split`` semantics: the first
    ``num_rows % num_hosts`` hosts carry one extra row, so uneven
    vocabs split without padding and without gaps."""
    if num_hosts < 1:
        raise ValueError(f"num_hosts must be >= 1, got {num_hosts}")
    if num_rows < 0:
        raise ValueError(f"num_rows must be >= 0, got {num_rows}")
    base, extra = divmod(num_rows, num_hosts)
    ranges = []
    lo = 0
    for host in range(num_hosts):
        hi = lo + base + (1 if host < extra else 0)
        ranges.append((lo, hi))
        lo = hi
    return ranges


def owning_shard(row: int, ranges) -> int:
    """Index of the shard whose range contains ``row``."""
    for i, (lo, hi) in enumerate(ranges):
        if lo <= row < hi:
            return i
    raise ValueError(f"row {row} outside all shard ranges {ranges}")


def embedding_axis(mesh, rows: int | None = None, allow_dp: bool = True):
    """The mesh axis that row-shards declared tables: first of
    ep > tp > fsdp > dp with size > 1 that divides ``rows`` (when
    given); None when no axis fits (single-device world — the table
    stays replicated and lookup is a local gather)."""
    axes = [MeshAxis.EP, MeshAxis.TP, MeshAxis.FSDP]
    if allow_dp:
        axes.append(MeshAxis.DP)
    for axis in axes:
        if axis not in mesh.axis_names or mesh.shape[axis] <= 1:
            continue
        if rows is not None and rows % mesh.shape[axis] != 0:
            continue
        return axis
    return None


def sharded_table_rules(mesh, tables: dict, allow_dp: bool = True) -> list:
    """First-match-wins sharding rules row-partitioning each declared
    table: ``tables`` maps the table's parameter path (e.g.
    ``"embedding/embedding"``) to its (padded) row count.  Each entry
    becomes ``Rule(r"(^|/)<path>$", P(axis, None))`` over
    :func:`embedding_axis`; tables with no fitting axis are skipped
    (``infer_param_specs`` then replicates them)."""
    from jax.sharding import PartitionSpec as P

    from elasticdl_tpu.parallel.sharding import Rule

    rules = []
    for path, rows in tables.items():
        axis = embedding_axis(mesh, rows=rows, allow_dp=allow_dp)
        if axis is None:
            logger.warning(
                "sharded_table_rules: no mesh axis divides %s rows of %r; "
                "leaving it replicated",
                rows,
                path,
            )
            continue
        rules.append(Rule(r"(^|/)" + re.escape(path) + "$", P(axis, None)))
    return rules


# ---- tier admission ----------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Placement:
    """One table's admission decision: which tier holds the rows and the
    measured budgets the decision was made against."""

    tier: str  # "device" | "spill"
    table_bytes: int
    device_budget_bytes: int | None
    host_available_bytes: int | None
    reason: str


def _host_share() -> float:
    raw = os.environ.get(HOST_SHARE_ENV, "")
    try:
        return float(raw) if raw else DEFAULT_HOST_SHARE
    except ValueError:
        return DEFAULT_HOST_SHARE


def device_budget_bytes() -> int | None:
    """Free HBM across this process's local devices (``bytes_limit -
    bytes_in_use``), or the env override; None where allocator stats
    are absent (CPU) AND no override is set — an unknowable budget
    admits (the graceful-None contract; CPU "HBM" is just host RAM)."""
    raw = os.environ.get(DEVICE_BUDGET_ENV, "")
    if raw:
        try:
            return int(float(raw))
        except ValueError:
            pass
    try:
        import jax

        devices = jax.local_devices()
    except Exception:  # noqa: BLE001 — no backend is a valid state
        return None
    total = 0
    found = False
    for device in devices:
        try:
            stats = device.memory_stats()
        except Exception:  # noqa: BLE001 — per-device stats are optional
            stats = None
        if not stats or "bytes_limit" not in stats:
            continue
        found = True
        total += max(
            0,
            int(stats.get("bytes_limit", 0) or 0)
            - int(stats.get("bytes_in_use", 0) or 0),
        )
    return total if found else None


def plan_placement(
    table_bytes: int,
    name: str = "",
    prefer: str = "device",
    emit=None,
) -> Placement:
    """Admit a table onto a tier or refuse loudly.

    Device tier first (unless ``prefer="spill"``): admits when the
    per-host bytes fit the measured free-HBM budget (or the budget is
    unknowable).  Spill tier next: admits when the bytes fit within
    ``HOST_SHARE`` of the ledger's measured ``MemAvailable``.  Neither
    fitting emits ``embedding_spill_fault`` and raises — the caller
    must shard wider or shrink, not gamble on the OOM killer."""
    budget = device_budget_bytes()
    if prefer != "spill" and (budget is None or table_bytes <= budget):
        return Placement(
            tier="device",
            table_bytes=table_bytes,
            device_budget_bytes=budget,
            host_available_bytes=None,
            reason="fits device budget"
            if budget is not None
            else "device budget unknowable; admitted",
        )
    health = memory_ledger.host_memory_health()
    available = health.get("host_available_bytes")
    share = _host_share()
    if available is None or table_bytes <= available * share:
        return Placement(
            tier="spill",
            table_bytes=table_bytes,
            device_budget_bytes=budget,
            host_available_bytes=available,
            reason=f"fits {share:.2f} of host MemAvailable"
            if available is not None
            else "host availability unknowable; admitted",
        )
    if emit is None:
        from elasticdl_tpu.telemetry.worker_hooks import emit_event

        emit = emit_event
    try:
        from elasticdl_tpu.telemetry.events import EVENT_EMBEDDING_SPILL_FAULT

        emit(
            EVENT_EMBEDDING_SPILL_FAULT,
            table=name,
            table_bytes=int(table_bytes),
            device_budget_bytes=budget,
            host_available_bytes=available,
            host_share=share,
        )
    except Exception:  # noqa: BLE001 — telemetry never raises into admission
        logger.exception("embedding_spill_fault emit failed")
    raise EmbeddingAdmissionError(
        f"table {name or '<unnamed>'} ({table_bytes} bytes) fits neither "
        f"the device budget ({budget}) nor {share:.2f} of host "
        f"MemAvailable ({available}); shard wider or shrink the table"
    )


__all__ = [
    "DEVICE_BUDGET_ENV",
    "HOST_SHARE_ENV",
    "EmbeddingAdmissionError",
    "Placement",
    "device_budget_bytes",
    "embedding_axis",
    "owning_shard",
    "plan_placement",
    "shard_row_ranges",
    "sharded_table_rules",
]
