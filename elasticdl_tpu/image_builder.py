"""Docker image assembly for cluster submission.

Reference: ``elasticdl/python/elasticdl/image_builder.py:12-212`` —
copies the framework source + model zoo into a docker context,
synthesizes a Dockerfile on a framework base image, builds, pushes, and
can remove job images.  TPU differences: the base image must carry
``jax[tpu]`` (default below) instead of TensorFlow, and the sanity check
asserts jax imports.
"""

from __future__ import annotations

import os
import shutil
import tempfile
import uuid
from urllib.parse import urlparse

from elasticdl_tpu.utils.log_utils import default_logger as logger

DEFAULT_BASE_IMAGE = "python:3.12-slim"


def _framework_root() -> str:
    """Directory containing the ``elasticdl_tpu`` package."""
    import elasticdl_tpu

    return os.path.dirname(os.path.dirname(os.path.abspath(
        elasticdl_tpu.__file__
    )))


def create_dockerfile(
    model_zoo: str,
    base_image: str = "",
    extra_pypi_index: str = "",
    cluster_spec: str = "",
) -> str:
    """Synthesize the job Dockerfile (reference :137-212).

    The framework source is COPYed to ``/elasticdl_tpu``; a local model
    zoo is COPYed to ``/model_zoo``, a remote (git URL) zoo is cloned.
    The final check fails the build early if jax is missing from the
    base image rather than at pod start.
    """
    base = base_image or DEFAULT_BASE_IMAGE
    index = (
        f' --extra-index-url="{extra_pypi_index}"' if extra_pypi_index else ""
    )
    lines = [
        f"FROM {base} as base",
        "ENV PYTHONPATH=/framework:/model_zoo",
        "COPY elasticdl_tpu /framework/elasticdl_tpu",
        f"RUN pip install 'jax[tpu]' flax optax msgpack grpcio numpy{index}",
    ]
    if cluster_spec:
        # the master applies cluster hooks in-cluster, so the spec module
        # rides in the image at a fixed path (reference api.py:42-43)
        lines.append(
            f"COPY {os.path.basename(cluster_spec)} /cluster_spec/"
            f"{os.path.basename(cluster_spec)}"
        )
    if model_zoo:
        parsed = urlparse(model_zoo)
        if not parsed.path:
            raise ValueError(f"model_zoo has no path: {model_zoo!r}")
        if parsed.scheme in ("", "file"):
            zoo_base = os.path.basename(os.path.abspath(parsed.path))
            lines.append(f"COPY {zoo_base} /model_zoo/{zoo_base}")
            lines.append(
                f"RUN if [ -f /model_zoo/{zoo_base}/requirements.txt ]; then"
                f" pip install -r /model_zoo/{zoo_base}/requirements.txt"
                f"{index}; fi"
            )
        else:
            lines.append("RUN apt-get update && apt-get install -y git")
            lines.append(f"RUN git clone --recursive {model_zoo} /model_zoo")
    lines.append(
        'RUN python -c "import jax; print(\'jax\', jax.__version__)"'
    )
    return "\n".join(lines) + "\n"


def build_and_push_docker_image(
    model_zoo: str,
    docker_image_repository: str = "",
    base_image: str = "",
    extra_pypi: str = "",
    docker_base_url: str = "unix://var/run/docker.sock",
    docker_tlscert: str = "",
    docker_tlskey: str = "",
    client=None,
    cluster_spec: str = "",
) -> str:
    """Assemble the context, build, and (when a repository is given) push.
    Returns the full image name (reference :12-79)."""
    image_name = _unique_image_name(docker_image_repository)
    with tempfile.TemporaryDirectory() as ctx_dir:
        src = os.path.join(_framework_root(), "elasticdl_tpu")
        shutil.copytree(src, os.path.join(ctx_dir, "elasticdl_tpu"))
        if model_zoo:
            parsed = urlparse(model_zoo)
            if parsed.scheme in ("", "file"):
                zoo = os.path.abspath(parsed.path)
                shutil.copytree(
                    zoo, os.path.join(ctx_dir, os.path.basename(zoo))
                )
        if cluster_spec:
            shutil.copy(
                os.path.abspath(cluster_spec),
                os.path.join(ctx_dir, os.path.basename(cluster_spec)),
            )
        dockerfile = os.path.join(ctx_dir, "Dockerfile")
        with open(dockerfile, "w") as f:
            f.write(
                create_dockerfile(
                    model_zoo, base_image, extra_pypi, cluster_spec
                )
            )

        client = client or _docker_client(
            docker_base_url, docker_tlscert, docker_tlskey
        )
        logger.info("Building image %s", image_name)
        for line in client.api.build(
            path=ctx_dir,
            dockerfile=dockerfile,
            rm=True,
            tag=image_name,
            decode=True,
        ):
            _log_docker_line(line)
        if docker_image_repository:
            logger.info("Pushing image %s", image_name)
            for line in client.api.push(image_name, stream=True, decode=True):
                _log_docker_line(line)
    return image_name


def remove_images(
    docker_image_repository: str = "",
    docker_base_url: str = "unix://var/run/docker.sock",
    docker_tlscert: str = "",
    docker_tlskey: str = "",
    client=None,
) -> list[str]:
    """Remove job images by repository prefix (reference :82-128)."""
    client = client or _docker_client(
        docker_base_url, docker_tlscert, docker_tlskey
    )
    removed: list[str] = []
    for image in client.images.list():
        tags = [
            t
            for t in image.tags
            if not docker_image_repository
            or t.startswith(docker_image_repository)
        ]
        if tags:
            client.images.remove(image.id, force=True)
            removed.extend(tags)
    logger.info("Removed %d images", len(removed))
    return removed


def _unique_image_name(repository: str) -> str:
    basename = f"elasticdl-tpu-{uuid.uuid4().hex[:12]}"
    return f"{repository}:{basename}" if repository else basename


def _docker_client(base_url: str, tlscert: str, tlskey: str):
    try:
        import docker
    except ImportError as ex:  # gated: not baked into this image
        raise RuntimeError(
            "docker SDK is required to build job images; install 'docker' "
            "or pass --docker_image to use a prebuilt image"
        ) from ex
    if tlscert and tlskey:
        tls_config = docker.tls.TLSConfig(client_cert=(tlscert, tlskey))
        return docker.DockerClient(base_url=base_url, tls=tls_config)
    return docker.DockerClient(base_url=base_url)


def _log_docker_line(line: dict):
    text = line.get("stream") or line.get("status") or line.get("error")
    if text:
        text = str(text).strip()
        if text:
            logger.info("docker: %s", text)
        if line.get("error"):
            raise RuntimeError(f"docker build/push failed: {text}")
