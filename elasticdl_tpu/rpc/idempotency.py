"""THE retry-safety registry: every RPC method, classified once.

The retry contract (rpc/retry.py) is only as safe as the claim that a
re-delivered request cannot double its effect.  That claim used to live
in prose — a docstring list in retry.py, per-method comments in
service.py — which is exactly how ``report_evaluation_metrics`` shipped
non-idempotent (the PR-8 double-accumulation).  This module is the one
machine-checked source of truth: the ``rpc-contract`` checker
(``python -m elasticdl_tpu.analysis``) fails the build when any method
named in a server method table or a retryable set is missing here, so a
NEW RPC method cannot land without someone writing down WHY a duplicate
delivery is safe (or explicitly classifying it unsafe to retry).

Classification vocabulary:

- ``read-only``          — no server-side effect at all;
- ``fenced-read``        — read gated on a generation fence;
- ``memoized``           — first call computes, re-delivery replays the
  memo (the lockstep step stream);
- ``monotone-merge``     — the server max-merges, so replays are
  absorbed (heartbeat counters, version reports);
- ``deduped``            — the server drops duplicates by a stable id
  (task_id / lease id report dedup);
- ``duplicate-work-bounded`` — a lost reply can orphan work the lease
  timeout reclaims: duplicate WORK, never duplicate ACCOUNTING;
- ``reconciling``        — the request presents state and the server
  converges on it (the re-home handshake);
- ``versioned-put``      — a keyed put deduplicated by (source,
  version); replays are refused as stale;
- ``not-retryable``      — a duplicate WOULD double its effect: the
  method must never appear in a retryable set (the checker enforces
  this too).
"""

from __future__ import annotations

# method name -> (classification, one-line why).  Keep alphabetical.
IDEMPOTENCY: dict[str, tuple[str, str]] = {
    "fetch_replica": (
        "read-only",
        "pure read of the replica store; probe and fetch mutate nothing",
    ),
    "get_restore_state": (
        "fenced-read",
        "serves the staged payload only to its generation; re-delivery "
        "re-serves the same bytes (the served-set release is per process "
        "id, so a replay cannot over-release)",
    ),
    "get_step_task": (
        "memoized",
        "memoized by seq under the stream lock; every process and every "
        "replay sees the first resolution",
    ),
    "get_task": (
        "duplicate-work-bounded",
        "a lost reply orphans a lease the timeout/re-home reconciliation "
        "reclaims — duplicate work, never duplicate accounting",
    ),
    "get_world_assignment": (
        "duplicate-work-bounded",
        "pops the standby mailbox; a lost reply loses one assignment the "
        "instance manager's replenish loop re-posts",
    ),
    "heartbeat": (
        "monotone-merge",
        "liveness timestamp overwrite + max-merged rpc/phase counters; "
        "replays are absorbed",
    ),
    "predict": (
        "read-only",
        "pure forward pass over replicated state; no server-side effect, "
        "so the router may re-send it to another replica after a "
        "deadline/UNAVAILABLE without double-counting anything (the "
        "request counters it bumps are observability, not accounting)",
    ),
    "push_replica": (
        "versioned-put",
        "keyed by (source, version, generation) with checksum; a replay "
        "is refused as a duplicate version",
    ),
    "rehome_worker": (
        "reconciling",
        "presents the worker's live leases; reconcile_leases re-accepts "
        "what is presented and requeues the rest — converges under "
        "re-delivery",
    ),
    "report_evaluation_metrics": (
        "deduped",
        "lease-id dedup in the servicer (the PR-8 fix): a re-delivered "
        "still-active report is dropped before accumulation",
    ),
    "report_task_result": (
        "deduped",
        "task_id dedup in the dispatcher (a re-send of a processed "
        "report is an unknown/inactive lease; exec counters bank once)",
    ),
    "report_version": (
        "monotone-merge",
        "server takes max(version); replays are absorbed",
    ),
    "request_profile": (
        "deduped",
        "arming while a window is still being distributed returns the "
        "existing window id (absorbed), and workers dedupe the "
        "heartbeat-borne command by window_id — so neither a "
        "re-delivered arm nor a duplicated response can open a second "
        "capture",
    ),
    "serving_status": (
        "read-only",
        "pure snapshot of replica counters/version; doubles as the "
        "serving plane's liveness probe AND the probe-beat telemetry "
        "ride-along (monotone counters + phase totals + memory ledger "
        "in the response, max/last-merged router-side), so it MUST be "
        "retry-safe — the payload is read-only on the replica and the "
        "router merge absorbs replays",
    ),
    "swap_model": (
        "versioned-put",
        "a swap to a version <= the replica's current one is refused as "
        "stale (engine guard), so a re-delivered swap is absorbed — the "
        "router fans it to every replica with retries on; the streaming "
        "live push rides the same method with an inline snapshot "
        "payload and the same guard, so a replayed push converges as "
        "stale instead of double-applying",
    ),
}


def classification(method: str) -> str | None:
    entry = IDEMPOTENCY.get(method)
    return entry[0] if entry else None
