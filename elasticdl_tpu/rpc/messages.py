"""Control-plane message types.

Reference: the protobuf messages in ``elasticdl/proto/elasticdl.proto``
(Task, GetTaskRequest, ReportTaskResultRequest, ReportEvaluationMetricsRequest,
ReportVersionRequest).  The TPU build represents them as plain dataclasses
serialized with msgpack; tensors ride as raw frames from
:mod:`elasticdl_tpu.utils.tensor` inside the msgpack map.  This keeps the
wire binary and schema'd without a protoc/grpc_tools build step.
"""

from __future__ import annotations

from dataclasses import asdict, dataclass, field

import msgpack

from elasticdl_tpu.utils.constants import TaskType
from elasticdl_tpu.utils.tensor import (
    Tensor,
    deserialize_tensors,
    serialize_tensors,
)


@dataclass
class GetTaskRequest:
    worker_id: int
    task_type: int = -1  # -1 = any; TaskType.EVALUATION for eval-only pulls
    # optional trace context ({"trace_id", "span_id"}); empty dict on old
    # payloads — decode() fills defaults, so the field is wire-compatible
    trace: dict = field(default_factory=dict)


@dataclass
class TaskResponse:
    """A leased task (or WAIT/empty sentinel).

    ``task_id == -1`` with ``type == WAIT`` means poll again later;
    ``task_id == -1`` with ``type == -1`` means the job is complete.
    """

    task_id: int = -1
    shard_name: str = ""
    start: int = 0
    end: int = 0
    type: int = -1
    model_version: int = -1
    minibatch_size: int = 0
    extended: dict = field(default_factory=dict)
    # trace context of the master's dispatch span: ONE task is ONE trace
    # across master and workers (telemetry/tracing.py); empty when the
    # master runs without tracing or on pre-trace payloads
    trace: dict = field(default_factory=dict)

    @property
    def is_wait(self) -> bool:
        return self.task_id == -1 and self.type == int(TaskType.WAIT)

    @property
    def is_empty(self) -> bool:
        return self.task_id == -1 and self.type == -1


@dataclass
class GetStepTaskRequest:
    """Lockstep task pull for multi-process SPMD training.

    All processes of one distributed world request the same monotonically
    increasing ``seq``; the master resolves each seq to ONE task exactly
    once and memoizes the answer, so every process sees an identical task
    stream (the lockstep invariant: the same jitted collectives run on
    every process).  ``cluster_version`` fences stale worlds after a mesh
    re-formation.
    """

    seq: int
    worker_id: int
    cluster_version: int = 0


@dataclass
class ReportTaskResultRequest:
    task_id: int
    err_message: str = ""
    exec_counters: dict = field(default_factory=dict)
    # the dispatch trace context echoed back for wire symmetry and
    # offline log joins; the master's own span bookkeeping is by task_id
    trace: dict = field(default_factory=dict)


@dataclass
class ReportVersionRequest:
    model_version: int
    worker_id: int = 0


@dataclass
class ReportEvaluationMetricsRequest:
    """Eval forward outputs + labels for master-side metric accumulation.

    Tensors are carried out-of-band as serialized frames so msgpack never
    sees large binary blobs it would copy.
    """

    model_outputs: dict = field(default_factory=dict)  # name -> Tensor
    labels: Tensor | None = None
    model_version: int = -1
    # lease guard: metrics are dropped unless this task is still actively
    # leased, so a reclaimed/retried eval task can't double-count
    task_id: int = -1
    # the step of the state the worker ACTUALLY evaluated with (may trail
    # or lead the milestone model_version; surfaced in the eval summary)
    evaluated_version: int = -1


@dataclass
class HeartbeatRequest:
    worker_id: int
    step: int = 0
    timestamp: float = 0.0
    # peer-replication advertisement (elasticdl_tpu.replication): the
    # worker's replica-server address plus the shards its RAM currently
    # holds ({"addr", "process_id", "generation", "holdings": [...]}).
    # Empty when replication is off; old payloads decode to {} so the
    # field is wire-compatible
    replica: dict = field(default_factory=dict)
    # client-side RPC outcome totals (rpc/stats.py): monotone counts of
    # retries / deadline_exceeded / unavailable since process start.
    # The heartbeat carries them BECAUSE it keeps flowing when task
    # reports stall — exactly when these spike.  Empty on a clean link;
    # old payloads decode to {} so the field is wire-compatible
    rpc: dict = field(default_factory=dict)
    # step-anatomy phase totals (telemetry/anatomy.py): monotone
    # per-phase {ms, count, buckets} the master mirrors onto the
    # elasticdl_step_phase_* metric families.  Empty when --step_anatomy
    # is off; old payloads decode to {} so the field is wire-compatible
    phases: dict = field(default_factory=dict)
    # device-prefetch staging totals (trainer/device_pipeline.py):
    # monotone {groups, stall_ms, stage_ms} the master mirrors onto the
    # elasticdl_device_prefetch_* counters.  Empty when
    # --device_prefetch is off; old payloads decode to {} so the field
    # is wire-compatible
    prefetch: dict = field(default_factory=dict)
    # memory-ledger snapshot (telemetry/memory.py): {"at": <sender wall
    # clock>, "current": {component: bytes}, "peak": {component:
    # bytes}}.  NON-monotone by nature (a swap releases, a queue
    # drains), so the master merges "current" with timestamped
    # last-writer-wins (utils/merge.last_merge_counters) and "peak"
    # with the usual max rule.  Empty when the ledger is off; old
    # payloads decode to {} so the field is wire-compatible
    memory: dict = field(default_factory=dict)


@dataclass
class HeartbeatResponse:
    accepted: bool = True
    # master may instruct the worker to quiesce for mesh re-formation
    should_quiesce: bool = False
    cluster_version: int = 0
    # process_id -> replica-server addr of the current generation (the
    # ring-push targets, from the master's replica directory); empty
    # when replication is off or peers have not advertised yet
    replica_peers: dict = field(default_factory=dict)
    # identity of the master PROCESS serving this response (non-empty
    # only when the journaled-HA control plane is on).  A worker that
    # sees the boot id CHANGE has outlived a master: it re-homes —
    # presents its generation and in-flight leases so the restarted
    # master reconciles accounting (master/journal.py).  Old payloads
    # decode to "" — wire-compatible
    boot_id: str = ""
    # on-demand profiler command (utils/profiling.py): {"window_id",
    # "num_steps", "out_dir"} when a request_profile window is being
    # distributed; workers dedupe by window_id, so the master can keep
    # re-sending the latest command and every replay is absorbed.
    # Empty otherwise; old payloads decode to {} — wire-compatible
    profile: dict = field(default_factory=dict)


@dataclass
class RehomeRequest:
    """Worker -> restarted master: the re-homing handshake.

    ``lease_ids`` are the task leases this worker still holds in
    flight; ``cluster_version`` is the world generation it belongs to
    (the fence — a stale generation is rejected); ``pid`` lets a local
    master ADOPT the orphaned process (the previous master spawned it,
    so the restarted one holds no handle)."""

    worker_id: int
    cluster_version: int = 0
    pid: int = 0
    lease_ids: list = field(default_factory=list)


@dataclass
class RehomeResponse:
    # False = generation fence rejected the worker (stale world): it
    # must exit like any fenced worker
    accepted: bool = False
    cluster_version: int = 0
    boot_id: str = ""
    # the presented leases the master re-accepted; the worker must drop
    # any lease NOT in this list (its eventual report would be dropped
    # and the task re-trains from the queue exactly once)
    accepted_leases: list = field(default_factory=list)


@dataclass
class GetWorldAssignmentRequest:
    """Hot-standby poll: a pre-warmed worker (pod) asks whether it has
    been assigned a place in a (re-)formed world.  ``standby_id`` is the
    identity the instance manager addressed the assignment to (the pod
    name on k8s)."""

    standby_id: str


@dataclass
class WorldAssignmentResponse:
    has: bool = False
    # True once the job is shutting down: the standby exits cleanly
    shutdown: bool = False
    worker_id: int = 0
    coordinator_addr: str = ""
    num_processes: int = 1
    process_id: int = 0
    cluster_version: int = 0
    # slice coordinates of a multi-slice world (slice-granular
    # elasticity); defaults keep old payloads wire-compatible
    slice_id: int = 0
    num_slices: int = 1
    # reform trace context: the activated standby's world_join span links
    # into the master's re-formation trace
    trace: dict = field(default_factory=dict)


@dataclass
class PushReplicaRequest:
    """Ring-neighbor state push (worker -> worker, replica service).

    ``payload`` is one encoded state shard (:mod:`..replication.blob`);
    ``checksum`` lets the receiver detect a torn transfer and refuse to
    commit it; ``generation`` fences pushes from stale worlds.
    """

    source: int  # process index whose state shard this is
    version: int  # model version the shard was snapshotted at
    generation: int = 0
    checksum: str = ""
    payload: bytes = b""


@dataclass
class PushReplicaResponse:
    accepted: bool = False
    reason: str = ""


@dataclass
class FetchReplicaRequest:
    """Master-side harvest pull (master -> worker, replica service).
    ``probe=True`` returns metadata only (version/generation/checksum
    plus every retained version), so the harvester can pick a complete
    replica set before moving any payload bytes.  ``version=-1`` means
    the newest retained shard; a specific version fetches exactly that
    one (an older shard may be the only COMPLETE set left)."""

    source: int
    probe: bool = False
    version: int = -1


@dataclass
class FetchReplicaResponse:
    has: bool = False
    source: int = -1
    version: int = -1
    generation: int = -1
    checksum: str = ""
    payload: bytes = b""
    # every version the store retains for this source (probe responses;
    # the store keeps more than the advertised newest — see ReplicaStore)
    versions: list = field(default_factory=list)


# ---- serving plane (elasticdl_tpu/serving) ----------------------------------
#
# Feature/output trees ride as tensor frames like the eval-metrics
# payload: ``pack_array_tree``/``unpack_array_tree`` flatten a bare
# ndarray or a {name: ndarray} dict into the serialize_tensors form (a
# bare array travels under the reserved name below), so msgpack never
# copies large binary blobs.

BARE_ARRAY_KEY = "__bare__"


def pack_array_tree(tree) -> bytes:
    """Serialize a bare ndarray or a flat {name: ndarray} dict."""
    import numpy as np

    if isinstance(tree, dict):
        named = {
            str(k): Tensor(str(k), np.asarray(v)) for k, v in tree.items()
        }
    else:
        named = {BARE_ARRAY_KEY: Tensor(BARE_ARRAY_KEY, np.asarray(tree))}
    return serialize_tensors(named)


def unpack_array_tree(buf: bytes):
    """Inverse of :func:`pack_array_tree`."""
    tensors = deserialize_tensors(buf)
    if set(tensors) == {BARE_ARRAY_KEY}:
        return tensors[BARE_ARRAY_KEY].values
    return {name: t.values for name, t in tensors.items()}


@dataclass
class PredictRequest:
    """One inference request: ``rows`` rows of features (any row count —
    the replica's micro-batcher coalesces/splits them into the one
    canonical batch shape).  ``request_id`` is the client-chosen
    identity (router retries re-send the SAME id; predict is read-only
    so a re-delivery is harmless either way)."""

    request_id: str = ""
    features: bytes = b""  # pack_array_tree frames
    rows: int = 0
    # PR-3 trace context ({"trace_id", "span_id"}): the client's root
    # span, so the router's (re)route children and the replica's
    # queue/engine spans land in the SAME trace.  Empty when the client
    # does not trace; old payloads decode to {} — wire-compatible
    trace: dict = field(default_factory=dict)


@dataclass
class PredictResponse:
    outputs: bytes = b""  # pack_array_tree frames
    model_version: int = -1
    rows: int = 0
    # sum-exact per-request anatomy, ms keyed by serving phase name
    # (queue_wait/assemble/h2d_transfer/device_compute/d2h_transfer/
    # untracked) plus total_ms; empty on error responses
    phases: dict = field(default_factory=dict)
    # non-empty = the request failed (overload, shape mismatch, ...);
    # the error classes a client may retry are marked retryable=True
    error: str = ""
    retryable: bool = False


@dataclass
class ServingStatusRequest:
    """Replica/router status snapshot; doubles as the liveness probe."""

    detail: bool = False
    # trace context of the caller (probe beats usually omit it; an
    # operator's traced status read parents the replica's work).  Old
    # payloads decode to {} — wire-compatible
    trace: dict = field(default_factory=dict)


@dataclass
class ServingStatusResponse:
    replica_id: int = -1
    model_version: int = -1
    # process-wide XLA compile count (telemetry/compile_tracker): the
    # observable face of the serving compile-once guarantee — flat
    # across steady-state traffic, whatever the request-size mix
    compile_count: int = 0
    requests: int = 0
    rows: int = 0
    rejected: int = 0
    swaps: int = 0
    queue_rows: int = 0
    canonical_rows: int = 0
    # router responses: one status dict per live replica (detail=True)
    replicas: list = field(default_factory=list)
    # probe-beat telemetry fan-in (the PR-8/9 heartbeat pattern riding
    # the RPC that keeps flowing — here the liveness probe itself):
    # monotone request/error counters since process start.  The router
    # max-merges per replica (utils/merge.max_merge_counters), so
    # duplicated or reordered probe replies are absorbed.  Empty when
    # telemetry is off; old payloads decode to {} — wire-compatible
    counters: dict = field(default_factory=dict)
    # monotone per-phase {ms, count, buckets} serving-request totals in
    # the step-anatomy heartbeat shape (bucket keys stringified for
    # msgpack), max-merged per replica and fed to the router's SLO
    # watchdog.  Empty when telemetry is off; old payloads decode to {}
    phases: dict = field(default_factory=dict)
    # memory-ledger snapshot {"at", "current", "peak"} — NON-monotone,
    # merged last-writer-wins like the heartbeat field of the same
    # name.  Empty when the ledger is off; old payloads decode to {}
    memory: dict = field(default_factory=dict)


@dataclass
class SwapModelRequest:
    """Hot-swap the served model.  ``model_dir`` names an export
    directory (manifest + npz); ``min_version`` guards staleness — the
    replica refuses a swap that would not advance its version, which is
    what makes the method a safe versioned-put under re-delivery."""

    model_dir: str = ""
    min_version: int = -1
    # trace context of the operator's swap request: the router's
    # per-replica fan-out spans and every replica's model_swap span
    # parent into it, so one swap = one trace across the fleet.  Empty
    # when untraced; old payloads decode to {} — wire-compatible
    trace: dict = field(default_factory=dict)
    # live train->serve push (streaming subsystem): a non-empty
    # ``payload`` carries an encoded replica snapshot
    # (replication/blob.py) to swap from directly — no export dir, no
    # disk.  ``version`` stamps the swap (the versioned-put guard is
    # unchanged: a version <= the served one is refused as stale);
    # ``source`` labels provenance for the model_swap event; the two
    # watermarks ride along so the replica's swap telemetry carries the
    # freshness pair (trained-at-push vs source).  All default-valued,
    # so old payloads decode cleanly — wire-compatible
    payload: bytes = b""
    version: int = -1
    source: str = ""
    trained_watermark: int = -1
    source_watermark: int = -1


@dataclass
class SwapModelResponse:
    accepted: bool = False
    model_version: int = -1
    reason: str = ""
    # structured staleness marker: True when the refusal means "already
    # at/past this version" — the absorbed-replay case of the
    # versioned-put contract.  A FIELD, not a reason-string prefix, so
    # the router's convergence logic cannot be broken by rewording
    stale: bool = False
    # router fan-out: per-replica outcomes
    replicas: list = field(default_factory=list)


@dataclass
class RequestProfileRequest:
    """Arm an on-demand XLA profiler window on the running job: the
    master rides the command down on every heartbeat response until the
    distribution TTL lapses, and each worker opens one
    ``num_steps``-step capture into its telemetry dir (or ``out_dir``
    when given).  Arming while a window is already being distributed is
    ABSORBED (the response carries the existing window id) — that is
    what makes a re-delivered arm safe to retry."""

    num_steps: int = 5
    out_dir: str = ""


@dataclass
class RequestProfileResponse:
    accepted: bool = False
    window_id: int = 0
    reason: str = ""


@dataclass
class GetRestoreStateRequest:
    """A re-formed world asks the master for the harvested in-memory
    replica set.  ``cluster_version`` fences the stage: only the
    generation the harvest was staged FOR may restore from it."""

    cluster_version: int
    process_id: int = 0


@dataclass
class RestoreStateResponse:
    has: bool = False
    version: int = -1
    checksum: str = ""
    payload: bytes = b""


_SIMPLE_TYPES = {
    "GetTaskRequest": GetTaskRequest,
    "GetStepTaskRequest": GetStepTaskRequest,
    "TaskResponse": TaskResponse,
    "ReportTaskResultRequest": ReportTaskResultRequest,
    "ReportVersionRequest": ReportVersionRequest,
    "HeartbeatRequest": HeartbeatRequest,
    "HeartbeatResponse": HeartbeatResponse,
    "RehomeRequest": RehomeRequest,
    "RehomeResponse": RehomeResponse,
    "GetWorldAssignmentRequest": GetWorldAssignmentRequest,
    "WorldAssignmentResponse": WorldAssignmentResponse,
    "PushReplicaRequest": PushReplicaRequest,
    "PushReplicaResponse": PushReplicaResponse,
    "FetchReplicaRequest": FetchReplicaRequest,
    "FetchReplicaResponse": FetchReplicaResponse,
    "GetRestoreStateRequest": GetRestoreStateRequest,
    "RestoreStateResponse": RestoreStateResponse,
    "RequestProfileRequest": RequestProfileRequest,
    "RequestProfileResponse": RequestProfileResponse,
    "PredictRequest": PredictRequest,
    "PredictResponse": PredictResponse,
    "ServingStatusRequest": ServingStatusRequest,
    "ServingStatusResponse": ServingStatusResponse,
    "SwapModelRequest": SwapModelRequest,
    "SwapModelResponse": SwapModelResponse,
}


def encode(msg) -> bytes:
    """Serialize any message dataclass to bytes."""
    kind = type(msg).__name__
    if kind == "ReportEvaluationMetricsRequest":
        payload = {
            "model_version": msg.model_version,
            "task_id": msg.task_id,
            "evaluated_version": msg.evaluated_version,
            "outputs": serialize_tensors(msg.model_outputs),
            "labels": b""
            if msg.labels is None
            else msg.labels.to_bytes(),
        }
    else:
        payload = asdict(msg)
    return msgpack.packb({"kind": kind, "body": payload}, use_bin_type=True)


def decode(buf: bytes):
    """Deserialize bytes back into the right message dataclass."""
    obj = msgpack.unpackb(buf, raw=False)
    kind, body = obj["kind"], obj["body"]
    if kind == "ReportEvaluationMetricsRequest":
        return ReportEvaluationMetricsRequest(
            model_outputs=deserialize_tensors(body["outputs"]),
            labels=Tensor.from_bytes(body["labels"])
            if body["labels"]
            else None,
            model_version=body["model_version"],
            task_id=body.get("task_id", -1),
            evaluated_version=body.get("evaluated_version", -1),
        )
    cls = _SIMPLE_TYPES.get(kind)
    if cls is None:
        raise ValueError(f"unknown message kind: {kind}")
    return cls(**body)


def task_to_response(
    task_id: int,
    task,
    model_version: int,
    minibatch_size: int,
    trace: dict | None = None,
) -> TaskResponse:
    return TaskResponse(
        task_id=task_id,
        shard_name=task.shard_name,
        start=task.start,
        end=task.end,
        type=int(task.type),
        model_version=task.model_version
        if task.type == TaskType.EVALUATION
        else model_version,
        minibatch_size=minibatch_size,
        extended=dict(task.extended),
        trace=dict(trace or {}),
    )
