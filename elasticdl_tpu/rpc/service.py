"""gRPC transport for the master control plane.

Reference: the ``Master`` gRPC service (``elasticdl/proto/elasticdl.proto:
108-113``) built with protoc stubs.  The TPU build keeps gRPC for the same
low-rate control traffic (tasks, versions, eval metrics, heartbeats) but
skips the protoc toolchain: methods are registered with
``grpc.method_handlers_generic_handler`` and payloads are the msgpack
frames of :mod:`elasticdl_tpu.rpc.messages`.  Handlers delegate to a
transport-agnostic ``MasterServicer`` — the same object tests call
directly (the in-process-master pattern, reference test_utils.py:357-360).

Tensor payloads (eval outputs/labels) ride inside the same frames; the
256MB message cap matches the reference (constants.py:1-5).
"""

from __future__ import annotations

import threading
import time
from concurrent import futures

import grpc

from elasticdl_tpu.rpc import messages as msg
from elasticdl_tpu.rpc import stats as rpc_stats
from elasticdl_tpu.utils.constants import GRPC
from elasticdl_tpu.utils.log_utils import default_logger as logger

SERVICE_NAME = "elasticdl_tpu.Master"

# method name -> servicer attribute (unary-unary, bytes in/out)
_METHODS = (
    "get_task",
    "get_step_task",
    "report_task_result",
    "report_version",
    "report_evaluation_metrics",
    "heartbeat",
    "get_world_assignment",
    "get_restore_state",
    "rehome_worker",
    "request_profile",
)

# every master control-plane method is retry-safe (classified in
# rpc/idempotency.py — the registry the rpc-contract analyzer checks
# every method table against), so the MasterClient opts them all in
# when a retry policy is installed
MASTER_RETRYABLE_METHODS = frozenset(_METHODS)

# grpc status codes worth backing off on: the server is down,
# restarting, or the deadline raced a restart.  Anything else
# (UNIMPLEMENTED, INVALID_ARGUMENT, ...) is a bug, not an outage.
_RETRYABLE_CODES = frozenset(
    {
        grpc.StatusCode.UNAVAILABLE,
        grpc.StatusCode.DEADLINE_EXCEEDED,
    }
)


def _retryable_grpc_error(ex) -> bool:
    code = getattr(ex, "code", None)
    return callable(code) and code() in _RETRYABLE_CODES


def _note_rpc_failure(ex):
    """Mirror an outage-class failure into the process-local stats the
    heartbeat ships to the master's /metrics (rpc/stats.py).  Anything
    without a status code (or outside the tracked set) is ignored."""
    code = getattr(ex, "code", None)
    if callable(code):
        try:
            rpc_stats.note_failure(code().name.lower())
        except Exception:  # noqa: BLE001 — accounting never breaks a call
            pass


# ---- chaos netem seam (chaos/netem.py) --------------------------------------
#
# The transport-level fault shim plugs in HERE, at the two choke points
# every msgpack-framed RPC passes: the client's per-attempt invoke and
# the server's generic handler.  Production code never sets these; the
# netem layer installs them from an env-armed fault plan, so a run with
# no plan pays one module-global None check per call and nothing else.
# The server observer is the telemetry hook for per-method handler
# latency (master_hooks registers it).

_client_fault_shim = None
_server_fault_shim = None
_server_rpc_observer = None


def set_client_fault_shim(shim):
    global _client_fault_shim
    _client_fault_shim = shim


def set_server_fault_shim(shim):
    global _server_fault_shim
    _server_fault_shim = shim


def set_server_rpc_observer(observer):
    """``observer(method, seconds)`` after every server-side handler."""
    global _server_rpc_observer
    _server_rpc_observer = observer


_CHANNEL_OPTIONS = [
    ("grpc.max_send_message_length", GRPC.MAX_SEND_MESSAGE_LENGTH),
    ("grpc.max_receive_message_length", GRPC.MAX_RECEIVE_MESSAGE_LENGTH),
]


def _handler(servicer, name, service_name=SERVICE_NAME):
    fn = getattr(servicer, name)

    def unary(request_bytes: bytes, context) -> bytes:
        request = msg.decode(request_bytes)
        t0 = time.monotonic()
        try:
            shim = _server_fault_shim
            if shim is not None:
                response = shim.server_call(service_name, name, fn, request)
            else:
                response = fn(request)
        finally:
            observer = _server_rpc_observer
            if observer is not None:
                try:
                    observer(name, time.monotonic() - t0)
                except Exception:  # noqa: BLE001 — telemetry never
                    # breaks an RPC
                    pass
        return msg.encode(response) if response is not None else b""

    return grpc.unary_unary_rpc_method_handler(unary)


def create_server(
    servicer,
    port: int,
    max_workers: int = 64,
    methods: tuple[str, ...] = _METHODS,
    service_name: str = SERVICE_NAME,
) -> grpc.Server:
    """Bind a servicer behind gRPC (reference master.py:301-324:
    64-thread pool, 256MB messages).  The default method table is the
    master control plane; the replication subsystem binds its own
    worker-side service through the same transport with its own table."""
    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers),
        options=_CHANNEL_OPTIONS,
    )
    handlers = {
        name: _handler(servicer, name, service_name) for name in methods
    }
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(service_name, handlers),)
    )
    bound = server.add_insecure_port(f"[::]:{port}")
    if bound == 0:
        raise RuntimeError(f"could not bind {service_name} port {port}")
    logger.info("%s server bound to port %d", service_name, bound)
    server._edl_bound_port = bound  # for port=0 ephemeral binds in tests
    return server


class RpcClient:
    """Generic stub over a msgpack-framed unary channel — the shared
    base of :class:`MasterClient` and the replication subsystem's
    worker-to-worker client.

    ``retry`` (a :class:`~elasticdl_tpu.rpc.retry.RetryPolicy`) makes
    outage-class failures (UNAVAILABLE / DEADLINE_EXCEEDED) back off
    and re-send instead of raising — but only for methods named in
    ``retryable_methods`` (default: the naturally idempotent subset;
    see rpc/retry.py for the safety contract).  ``resolve_addr`` is the
    re-resolve hook: called after repeated failures, and a changed
    address rebuilds the channel — how a worker follows a master that
    restarted on a new port.  ``deadlines`` (a :class:`~elasticdl_tpu.
    rpc.deadline.DeadlinePolicy`) supplies a per-method timeout when the
    caller passes none, so a blackholed link degrades to
    DEADLINE_EXCEEDED instead of hanging the calling thread forever.
    With ``retry=None`` and ``deadlines=None`` (the defaults) every
    code path is byte-identical to the policy-less client."""

    # failed attempts between re-resolve probes (the first probe fires
    # early so a fast master relaunch is caught within ~2 backoffs)
    _RERESOLVE_EVERY = 2

    def __init__(
        self,
        addr: str,
        methods: tuple[str, ...] = _METHODS,
        service_name: str = SERVICE_NAME,
        retry=None,
        retryable_methods: frozenset[str] | set[str] | None = None,
        resolve_addr=None,
        deadlines=None,
    ):
        self._addr = addr  # guarded-by: _channel_lock
        self._methods = tuple(methods)
        self._service_name = service_name
        self._retry = retry
        self._deadlines = deadlines
        if retryable_methods is None:
            from elasticdl_tpu.rpc.retry import DEFAULT_IDEMPOTENT

            retryable_methods = DEFAULT_IDEMPOTENT
        self._retryable = frozenset(retryable_methods) & set(methods)
        self._resolve_addr = resolve_addr
        self._channel_lock = threading.Lock()
        self._stale_channels: list = []  # guarded-by: _channel_lock
        self._connect(addr)

    # lock-holding: _channel_lock — callers: __init__ (single-threaded
    # construction) and _maybe_reresolve (under the lock); the channel
    # and call table swap must be atomic w.r.t. _invoke's snapshot
    def _connect(self, addr: str):
        # guarded-by: _channel_lock
        self._channel = grpc.insecure_channel(addr, options=_CHANNEL_OPTIONS)
        # guarded-by: _channel_lock
        self._calls = {
            name: self._channel.unary_unary(
                f"/{self._service_name}/{name}",
                request_serializer=None,
                response_deserializer=None,
            )
            for name in self._methods
        }

    def _maybe_reresolve(self, attempt: int, _ex):
        """on_retry hook: every few failures, re-read the master address
        and rebuild the channel if it moved."""
        if self._resolve_addr is None:
            return
        if attempt % self._RERESOLVE_EVERY != 0:
            return
        try:
            addr = self._resolve_addr()
        except Exception:  # noqa: BLE001 — a broken resolver must not
            # end the retry loop; the old channel may still come back
            logger.exception("Master address re-resolution failed")
            return
        with self._channel_lock:
            if not addr or addr == self._addr:
                return
            logger.warning(
                "Master address changed %s -> %s; reconnecting",
                self._addr,
                addr,
            )
            old, self._addr = self._channel, addr
            self._connect(addr)
            # do NOT close the old channel here: another thread's retry
            # attempt may have read its call object and be invoking it
            # right now — close() would turn that into a non-retryable
            # ValueError that escapes the retry loop.  Park it until
            # client close; re-resolves only happen on an address
            # change, so the parked set is bounded by master restarts.
            self._stale_channels.append(old)

    def _invoke(self, name, payload, timeout):
        """One wire attempt.  The call table is snapshotted under the
        channel lock on EVERY path (a concurrent re-resolve can swap it
        mid-read), and the netem shim — when chaos armed one — wraps
        the attempt so injected latency/blackholes/duplicates apply per
        attempt, exactly like a real flaky link."""
        with self._channel_lock:
            call = self._calls[name]
        shim = _client_fault_shim
        if shim is not None:
            return shim.client_call(
                self._service_name,
                name,
                lambda: call(payload, timeout=timeout),
                timeout,
            )
        return call(payload, timeout=timeout)

    def _call(self, name, request, timeout: float | None = None):
        if timeout is None and self._deadlines is not None:
            timeout = self._deadlines.deadline_for(name)
        payload = msg.encode(request)
        if self._retry is None or name not in self._retryable:
            try:
                out = self._invoke(name, payload, timeout)
            except Exception as ex:  # noqa: BLE001 — re-raised below
                _note_rpc_failure(ex)
                raise
            return msg.decode(out) if out else None
        from elasticdl_tpu.rpc.retry import call_with_retry

        def attempt():
            return self._invoke(name, payload, timeout)

        def is_retryable(ex):
            retryable = _retryable_grpc_error(ex)
            if retryable:
                _note_rpc_failure(ex)
            return retryable

        def on_retry(attempt_no, ex):
            rpc_stats.note_retry()
            self._maybe_reresolve(attempt_no, ex)

        out = call_with_retry(
            attempt,
            self._retry,
            is_retryable=is_retryable,
            on_retry=on_retry,
        )
        return msg.decode(out) if out else None

    def close(self):
        # snapshot the live channel under the same lock that swaps it:
        # a close racing a re-resolve must not read a half-swapped pair
        with self._channel_lock:
            stale, self._stale_channels = self._stale_channels, []
            channel = self._channel
        for ch in stale:
            try:
                ch.close()
            except Exception:  # noqa: BLE001
                pass
        channel.close()


class MasterClient(RpcClient):
    """Worker-side stub implementing the servicer protocol over a channel.

    Drop-in for the in-process ``MasterServicer`` object (same method
    names, same dataclasses), so ``Worker`` code is transport-blind.
    """

    def get_task(self, request: msg.GetTaskRequest) -> msg.TaskResponse:
        return self._call("get_task", request)

    def get_step_task(
        self, request: msg.GetStepTaskRequest
    ) -> msg.TaskResponse:
        return self._call("get_step_task", request)

    def report_task_result(self, request: msg.ReportTaskResultRequest):
        return self._call("report_task_result", request)

    def report_version(self, request: msg.ReportVersionRequest):
        return self._call("report_version", request)

    def report_evaluation_metrics(
        self, request: msg.ReportEvaluationMetricsRequest
    ):
        return self._call("report_evaluation_metrics", request)

    def get_world_assignment(
        self, request: msg.GetWorldAssignmentRequest
    ) -> msg.WorldAssignmentResponse:
        return self._call("get_world_assignment", request)

    def get_restore_state(
        self, request: msg.GetRestoreStateRequest
    ) -> msg.RestoreStateResponse:
        return self._call("get_restore_state", request)

    def heartbeat(self, request: msg.HeartbeatRequest) -> msg.HeartbeatResponse:
        return self._call("heartbeat", request)

    def rehome_worker(
        self, request: msg.RehomeRequest
    ) -> msg.RehomeResponse:
        return self._call("rehome_worker", request)

    def request_profile(
        self, request: msg.RequestProfileRequest
    ) -> msg.RequestProfileResponse:
        return self._call("request_profile", request)
