"""gRPC transport for the master control plane.

Reference: the ``Master`` gRPC service (``elasticdl/proto/elasticdl.proto:
108-113``) built with protoc stubs.  The TPU build keeps gRPC for the same
low-rate control traffic (tasks, versions, eval metrics, heartbeats) but
skips the protoc toolchain: methods are registered with
``grpc.method_handlers_generic_handler`` and payloads are the msgpack
frames of :mod:`elasticdl_tpu.rpc.messages`.  Handlers delegate to a
transport-agnostic ``MasterServicer`` — the same object tests call
directly (the in-process-master pattern, reference test_utils.py:357-360).

Tensor payloads (eval outputs/labels) ride inside the same frames; the
256MB message cap matches the reference (constants.py:1-5).
"""

from __future__ import annotations

from concurrent import futures

import grpc

from elasticdl_tpu.rpc import messages as msg
from elasticdl_tpu.utils.constants import GRPC
from elasticdl_tpu.utils.log_utils import default_logger as logger

SERVICE_NAME = "elasticdl_tpu.Master"

# method name -> servicer attribute (unary-unary, bytes in/out)
_METHODS = (
    "get_task",
    "get_step_task",
    "report_task_result",
    "report_version",
    "report_evaluation_metrics",
    "heartbeat",
    "get_world_assignment",
)

_CHANNEL_OPTIONS = [
    ("grpc.max_send_message_length", GRPC.MAX_SEND_MESSAGE_LENGTH),
    ("grpc.max_receive_message_length", GRPC.MAX_RECEIVE_MESSAGE_LENGTH),
]


def _handler(servicer, name):
    fn = getattr(servicer, name)

    def unary(request_bytes: bytes, context) -> bytes:
        request = msg.decode(request_bytes)
        response = fn(request)
        return msg.encode(response) if response is not None else b""

    return grpc.unary_unary_rpc_method_handler(unary)


def create_server(
    servicer, port: int, max_workers: int = 64
) -> grpc.Server:
    """Bind a MasterServicer behind gRPC (reference master.py:301-324:
    64-thread pool, 256MB messages)."""
    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers),
        options=_CHANNEL_OPTIONS,
    )
    handlers = {name: _handler(servicer, name) for name in _METHODS}
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(SERVICE_NAME, handlers),)
    )
    bound = server.add_insecure_port(f"[::]:{port}")
    if bound == 0:
        raise RuntimeError(f"could not bind master port {port}")
    logger.info("Master control-plane server bound to port %d", bound)
    server._edl_bound_port = bound  # for port=0 ephemeral binds in tests
    return server


class MasterClient:
    """Worker-side stub implementing the servicer protocol over a channel.

    Drop-in for the in-process ``MasterServicer`` object (same method
    names, same dataclasses), so ``Worker`` code is transport-blind.
    """

    def __init__(self, addr: str):
        self._channel = grpc.insecure_channel(addr, options=_CHANNEL_OPTIONS)
        self._calls = {
            name: self._channel.unary_unary(
                f"/{SERVICE_NAME}/{name}",
                request_serializer=None,
                response_deserializer=None,
            )
            for name in _METHODS
        }

    def _call(self, name, request):
        payload = self._calls[name](msg.encode(request))
        return msg.decode(payload) if payload else None

    def get_task(self, request: msg.GetTaskRequest) -> msg.TaskResponse:
        return self._call("get_task", request)

    def get_step_task(
        self, request: msg.GetStepTaskRequest
    ) -> msg.TaskResponse:
        return self._call("get_step_task", request)

    def report_task_result(self, request: msg.ReportTaskResultRequest):
        return self._call("report_task_result", request)

    def report_version(self, request: msg.ReportVersionRequest):
        return self._call("report_version", request)

    def report_evaluation_metrics(
        self, request: msg.ReportEvaluationMetricsRequest
    ):
        return self._call("report_evaluation_metrics", request)

    def get_world_assignment(
        self, request: msg.GetWorldAssignmentRequest
    ) -> msg.WorldAssignmentResponse:
        return self._call("get_world_assignment", request)

    def heartbeat(self, request: msg.HeartbeatRequest) -> msg.HeartbeatResponse:
        return self._call("heartbeat", request)

    def close(self):
        self._channel.close()
