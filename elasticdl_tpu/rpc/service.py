"""gRPC transport for the master control plane.

Reference: the ``Master`` gRPC service (``elasticdl/proto/elasticdl.proto:
108-113``) built with protoc stubs.  The TPU build keeps gRPC for the same
low-rate control traffic (tasks, versions, eval metrics, heartbeats) but
skips the protoc toolchain: methods are registered with
``grpc.method_handlers_generic_handler`` and payloads are the msgpack
frames of :mod:`elasticdl_tpu.rpc.messages`.  Handlers delegate to a
transport-agnostic ``MasterServicer`` — the same object tests call
directly (the in-process-master pattern, reference test_utils.py:357-360).

Tensor payloads (eval outputs/labels) ride inside the same frames; the
256MB message cap matches the reference (constants.py:1-5).
"""

from __future__ import annotations

from concurrent import futures

import grpc

from elasticdl_tpu.rpc import messages as msg
from elasticdl_tpu.utils.constants import GRPC
from elasticdl_tpu.utils.log_utils import default_logger as logger

SERVICE_NAME = "elasticdl_tpu.Master"

# method name -> servicer attribute (unary-unary, bytes in/out)
_METHODS = (
    "get_task",
    "get_step_task",
    "report_task_result",
    "report_version",
    "report_evaluation_metrics",
    "heartbeat",
    "get_world_assignment",
    "get_restore_state",
)

_CHANNEL_OPTIONS = [
    ("grpc.max_send_message_length", GRPC.MAX_SEND_MESSAGE_LENGTH),
    ("grpc.max_receive_message_length", GRPC.MAX_RECEIVE_MESSAGE_LENGTH),
]


def _handler(servicer, name):
    fn = getattr(servicer, name)

    def unary(request_bytes: bytes, context) -> bytes:
        request = msg.decode(request_bytes)
        response = fn(request)
        return msg.encode(response) if response is not None else b""

    return grpc.unary_unary_rpc_method_handler(unary)


def create_server(
    servicer,
    port: int,
    max_workers: int = 64,
    methods: tuple[str, ...] = _METHODS,
    service_name: str = SERVICE_NAME,
) -> grpc.Server:
    """Bind a servicer behind gRPC (reference master.py:301-324:
    64-thread pool, 256MB messages).  The default method table is the
    master control plane; the replication subsystem binds its own
    worker-side service through the same transport with its own table."""
    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers),
        options=_CHANNEL_OPTIONS,
    )
    handlers = {name: _handler(servicer, name) for name in methods}
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(service_name, handlers),)
    )
    bound = server.add_insecure_port(f"[::]:{port}")
    if bound == 0:
        raise RuntimeError(f"could not bind {service_name} port {port}")
    logger.info("%s server bound to port %d", service_name, bound)
    server._edl_bound_port = bound  # for port=0 ephemeral binds in tests
    return server


class RpcClient:
    """Generic stub over a msgpack-framed unary channel — the shared
    base of :class:`MasterClient` and the replication subsystem's
    worker-to-worker client."""

    def __init__(
        self,
        addr: str,
        methods: tuple[str, ...] = _METHODS,
        service_name: str = SERVICE_NAME,
    ):
        self._channel = grpc.insecure_channel(addr, options=_CHANNEL_OPTIONS)
        self._calls = {
            name: self._channel.unary_unary(
                f"/{service_name}/{name}",
                request_serializer=None,
                response_deserializer=None,
            )
            for name in methods
        }

    def _call(self, name, request, timeout: float | None = None):
        payload = self._calls[name](msg.encode(request), timeout=timeout)
        return msg.decode(payload) if payload else None

    def close(self):
        self._channel.close()


class MasterClient(RpcClient):
    """Worker-side stub implementing the servicer protocol over a channel.

    Drop-in for the in-process ``MasterServicer`` object (same method
    names, same dataclasses), so ``Worker`` code is transport-blind.
    """

    def get_task(self, request: msg.GetTaskRequest) -> msg.TaskResponse:
        return self._call("get_task", request)

    def get_step_task(
        self, request: msg.GetStepTaskRequest
    ) -> msg.TaskResponse:
        return self._call("get_step_task", request)

    def report_task_result(self, request: msg.ReportTaskResultRequest):
        return self._call("report_task_result", request)

    def report_version(self, request: msg.ReportVersionRequest):
        return self._call("report_version", request)

    def report_evaluation_metrics(
        self, request: msg.ReportEvaluationMetricsRequest
    ):
        return self._call("report_evaluation_metrics", request)

    def get_world_assignment(
        self, request: msg.GetWorldAssignmentRequest
    ) -> msg.WorldAssignmentResponse:
        return self._call("get_world_assignment", request)

    def get_restore_state(
        self, request: msg.GetRestoreStateRequest
    ) -> msg.RestoreStateResponse:
        return self._call("get_restore_state", request)

    def heartbeat(self, request: msg.HeartbeatRequest) -> msg.HeartbeatResponse:
        return self._call("heartbeat", request)
