"""Per-method RPC deadline policy — gray-failure floor of the RPC plane.

A blackholed link does not say UNAVAILABLE; it says nothing, forever.
Before this policy existed no master-facing RPC carried a deadline
(``RpcClient._call`` defaulted ``timeout=None``), so a one-way partition
hung the calling worker thread without ever reaching the retry loop.
With a policy installed every call degrades to DEADLINE_EXCEEDED — an
outage-class, retryable failure — and flows into the
:mod:`elasticdl_tpu.rpc.retry` full-jitter loop instead of hanging.

Two tiers, not one number: control RPCs (task leases, reports,
heartbeats) move a few KB and should fail fast; state transfer
(``get_restore_state`` — a full model-state payload — and the
replication subsystem's ``push_replica``/``fetch_replica``) legitimately
takes long on big models, and a control-sized deadline there would turn
every reform restore into a spurious timeout.  The replication clients
adopt the SAME policy object, replacing their historical fixed
``PUSH_TIMEOUT_SECS``/``FETCH_TIMEOUT_SECS`` constants when a policy is
configured (and keeping them byte-for-byte when not).

The master owns the knob (``--rpc_deadline_secs``) and forwards it to
workers by env, like the retry budget — never argv, so worker command
lines and golden manifests stay byte-identical with the policy off.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

DEADLINE_SECS_ENV = "ELASTICDL_TPU_RPC_DEADLINE_SECS"

# state transfer gets this multiple of the control deadline, floored so
# a tight control deadline (chaos runs use ~1 s) can never squeeze a
# model-state payload below the historical 30 s transfer timeouts
TRANSFER_MULTIPLIER = 10.0
TRANSFER_FLOOR_SECS = 30.0

# methods that move model-state payloads rather than control frames
STATE_TRANSFER_METHODS = frozenset(
    {"get_restore_state", "push_replica", "fetch_replica"}
)


@dataclass(frozen=True)
class DeadlinePolicy:
    """Deadlines by method class; ``deadline_for`` is the one lookup
    :class:`~elasticdl_tpu.rpc.service.RpcClient` makes per call."""

    control_secs: float
    transfer_secs: float

    def deadline_for(self, method: str) -> float:
        if method in STATE_TRANSFER_METHODS:
            return self.transfer_secs
        return self.control_secs

    @classmethod
    def from_secs(cls, control_secs: float) -> "DeadlinePolicy":
        control = max(0.1, float(control_secs))
        return cls(
            control_secs=control,
            transfer_secs=max(
                TRANSFER_FLOOR_SECS, control * TRANSFER_MULTIPLIER
            ),
        )

    @classmethod
    def from_env(cls) -> "DeadlinePolicy | None":
        """The worker-side constructor: None (no deadlines — behavior
        byte-identical to a policy-less build) unless the master
        exported ``--rpc_deadline_secs``."""
        raw = os.environ.get(DEADLINE_SECS_ENV, "")
        if not raw:
            return None
        try:
            return cls.from_secs(float(raw))
        except ValueError:
            # loud, not silent: dropping the policy here restores the
            # infinite-hang failure mode it exists to prevent, so the
            # operator must be able to see WHY deadlines are off
            from elasticdl_tpu.utils.log_utils import (
                default_logger as logger,
            )

            logger.error(
                "Unparseable %s=%r; RPC DEADLINES ARE OFF — a "
                "blackholed link can hang calls again",
                DEADLINE_SECS_ENV,
                raw,
            )
            return None
