"""Control-plane transport: message types + gRPC service plumbing.

Reference: ``elasticdl/proto/elasticdl.proto`` + generated stubs.  The TPU
build keeps gRPC as the transport but replaces protobuf codegen with
hand-rolled msgpack message dataclasses (``messages.py``) registered via
generic method handlers (``service.py``) — no grpc_tools dependency, same
wire properties (binary, framed, 256MB cap).
"""
