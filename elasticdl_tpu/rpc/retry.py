"""RPC retry/backoff — the worker half of master high availability.

A small, self-contained unit: a :class:`RetryPolicy` (bounded attempts,
full-jitter exponential backoff, optional wall-clock budget) and
:func:`call_with_retry`, the loop :class:`~elasticdl_tpu.rpc.service.
RpcClient` drives.  Kept free of grpc imports so the backoff math is
unit-testable without a channel.

Retry safety contract: only calls the SERVER deduplicates or that are
naturally idempotent may retry — a retried non-idempotent call whose
first attempt actually landed would double its effect.  The
machine-checked source of truth is :mod:`elasticdl_tpu.rpc.idempotency`
(the ``rpc-contract`` analyzer fails the build when a method in any
retryable set is unclassified there).  The generic default
(:data:`DEFAULT_IDEMPOTENT`) is the read-only subset; ``MasterClient``
opts the full master control plane in because every master RPC is
dedup-safe by construction:

- ``get_step_task`` is memoized by seq; ``heartbeat`` / ``report_version``
  are monotone merges; ``get_world_assignment`` / ``get_restore_state``
  are fenced reads;
- ``report_task_result`` / ``report_evaluation_metrics`` are deduplicated
  by task_id (a re-send of an already-processed report is dropped as an
  unknown/inactive lease);
- ``get_task`` may orphan a lease when the first attempt's reply is
  lost, which the lease timeout and the re-homing reconciliation both
  reclaim — bounded duplicate WORK, never duplicate ACCOUNTING.

Workers enable retries only when the master exports
``ELASTICDL_TPU_RPC_RETRY_SECS`` (it does so exactly when
``--master_journal_dir`` is set), so an HA-off deployment keeps the
fail-fast behavior byte for byte.
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass

from elasticdl_tpu.utils.log_utils import default_logger as logger

RETRY_SECS_ENV = "ELASTICDL_TPU_RPC_RETRY_SECS"

# outage budget when --rpc_retry_secs is unset: the master exports it,
# the worker falls back to it on a missing/malformed env — ONE constant
# so the two sides can never disagree
DEFAULT_RETRY_SECS = 60.0

# heartbeat-timeout fallback when the parsed args carry no
# --heartbeat_timeout_secs (0 disables timeout-based failure detection).
# Kept next to DEFAULT_RETRY_SECS because operators size the two
# against each other — a silence tolerance shorter than the worker's
# retry budget turns every surviving blip into a re-formation.  The
# master resolves the value ONCE (Master._heartbeat_timeout_secs); its
# run-loop failure detector and rehome-grace computation both reuse it.
DEFAULT_HEARTBEAT_TIMEOUT_SECS = 0.0

# naturally idempotent / read-only master methods: safe to retry on ANY
# service without knowing its dedup story
DEFAULT_IDEMPOTENT = frozenset(
    {"heartbeat", "get_step_task", "get_world_assignment", "get_restore_state"}
)


@dataclass(frozen=True)
class RetryPolicy:
    """Bounded retry with full-jitter exponential backoff.

    ``max_attempts`` counts the FIRST try; ``total_timeout_secs`` is a
    wall budget — whichever limit trips first ends the loop.  Full
    jitter (delay uniform in [0, cap]) is deliberate: a master restart
    makes every worker retry at once, and synchronized backoff would
    thundering-herd the fresh server.
    """

    max_attempts: int = 5
    base_delay_secs: float = 0.1
    max_delay_secs: float = 2.0
    total_timeout_secs: float | None = None

    def delay_cap(self, attempt: int) -> float:
        """Backoff ceiling after ``attempt`` failures (1-based)."""
        return min(
            self.max_delay_secs,
            self.base_delay_secs * (2.0 ** max(0, attempt - 1)),
        )

    @classmethod
    def from_budget(cls, budget_secs: float) -> "RetryPolicy":
        """The HA-worker policy: attempts effectively unbounded, the
        wall budget is the limit (sized to cover a master relaunch)."""
        return cls(
            max_attempts=10_000,
            base_delay_secs=0.1,
            max_delay_secs=2.0,
            total_timeout_secs=max(0.1, budget_secs),
        )


def call_with_retry(
    fn,
    policy: RetryPolicy,
    is_retryable=lambda ex: True,
    on_retry=None,
    rng: random.Random | None = None,
    sleep=time.sleep,
    clock=time.monotonic,
):
    """Run ``fn()`` under ``policy``.

    ``is_retryable(exc) -> bool`` gates which failures back off (a
    non-retryable exception re-raises immediately); ``on_retry(attempt,
    exc)`` fires before each sleep — the RPC client uses it for its
    re-resolve hook.  ``rng``/``sleep``/``clock`` are injectable for
    deterministic tests."""
    rng = rng if rng is not None else random.Random()
    deadline = (
        clock() + policy.total_timeout_secs
        if policy.total_timeout_secs is not None
        else None
    )
    attempt = 0
    while True:
        attempt += 1
        try:
            return fn()
        except Exception as ex:  # noqa: BLE001 — gated by is_retryable
            if not is_retryable(ex):
                raise
            out_of_attempts = attempt >= policy.max_attempts
            out_of_time = deadline is not None and clock() >= deadline
            if out_of_attempts or out_of_time:
                raise
            if on_retry is not None:
                try:
                    on_retry(attempt, ex)
                except Exception:  # noqa: BLE001 — a broken hook (e.g.
                    # a re-resolve probe dying) must not end the retry
                    # loop: the loop IS the outage survival path
                    logger.exception("Retry hook failed; continuing")
            delay = rng.uniform(0.0, policy.delay_cap(attempt))
            if deadline is not None:
                # the wall budget clamps the FINAL backoff sleep too: a
                # full jitter draw near max_delay must not overshoot the
                # deadline and bill the caller for time the budget
                # already spent
                delay = min(delay, max(0.0, deadline - clock()))
            sleep(delay)
