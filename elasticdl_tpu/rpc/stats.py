"""Process-local RPC outcome counters (client side).

The worker is where retries, deadline expiries and injected
UNAVAILABLEs actually happen, but the ``/metrics`` endpoint lives on
the master — so each worker accumulates monotone totals here and ships
a snapshot with every heartbeat (``HeartbeatRequest.rpc``).  The
heartbeat is deliberately the carrier: it is the one RPC that keeps
flowing when task reports stall, which is exactly when these counters
spike.  The master max-merges per worker and sums across workers onto
``elasticdl_rpc_*_total`` (telemetry/master_hooks.py).

Counted here (all services riding :class:`~elasticdl_tpu.rpc.service.
RpcClient`, the replication clients included):

- ``deadline_exceeded`` / ``unavailable`` — outage-class failures per
  attempt (retried or not);
- ``retries`` — backoff re-sends of the retry loop.

Zero-dependency and lock-tiny: the happy path never touches this
module; only failures do.
"""

from __future__ import annotations

import threading

_lock = threading.Lock()
_counts: dict[str, int] = {}

# the status-code names worth tracking (grpc.StatusCode.name.lower());
# anything else is a bug-class failure the caller will surface loudly
_TRACKED_CODES = frozenset({"deadline_exceeded", "unavailable"})


def note_failure(code_name: str):
    """Record one failed attempt by lowercase status-code name."""
    if code_name not in _TRACKED_CODES:
        return
    with _lock:
        _counts[code_name] = _counts.get(code_name, 0) + 1


def note_retry():
    """Record one backoff re-send (retry loop's ``on_retry``)."""
    with _lock:
        _counts["retries"] = _counts.get("retries", 0) + 1


def snapshot() -> dict[str, int]:
    """Monotone totals since process start (empty when clean)."""
    with _lock:
        return dict(_counts)


def reset_for_tests():
    with _lock:
        _counts.clear()
