"""lock-discipline: guarded attributes are only touched under their lock.

The bug class that produced the ``_rehome_pending`` gRPC-thread vs
run-loop race and the ``RpcClient`` call-table snapshot race: a field
documented as lock-guarded, with one access site that predates (or
forgot) the lock.  The discipline is opt-in per attribute via a comment
annotation at the attribute's ``__init__`` assignment:

    self._heartbeats = {}        # guarded-by: _lock
    self._version = 0            # guarded-by: _lock (writes)

``guarded-by: <lock>`` requires EVERY lexical read/write of
``self.<attr>`` outside ``__init__`` to sit inside ``with
self.<lock>:``.  The ``(writes)`` variant guards mutations only —
the repo's documented pattern for GIL-atomic int/bool reads (e.g. the
``cluster_version`` property, the ``_reform_requested`` unlocked peek
whose locked swap re-checks).

Method-level escape hatches, annotated on (or directly above) ``def``:

- ``# lock-holding: <lock>[, <lock2>]`` — the method documents that its
  CALLER holds the lock (the ``_locked()``-suffix convention); its body
  is analyzed as if the listed locks were held.
- ``# single-threaded`` — a known init/teardown window (e.g. journal
  replay before the RPC server starts); the body is exempt.

``__init__`` is always a single-threaded window.  Mutating an attribute
through an alias (``x = self._attr; x.append(...)``) is invisible to
this lexical analysis — the annotation is a contract the checker
enforces at direct-access sites, not an alias-tracking race prover.
Nested functions (closures often run on OTHER threads) deliberately do
NOT inherit the enclosing ``with`` stack: a guarded access inside a
closure must take the lock itself or be waived.
"""

from __future__ import annotations

import ast
import re

from elasticdl_tpu.analysis.core import Finding, register

CHECKER = "lock-discipline"

_GUARDED_BY = re.compile(r"guarded-by:\s*(\w+)\s*(\(writes\))?")
# method escapes must START their comment line: prose like "callers:
# __init__ (single-threaded construction)" inside another annotation's
# explanation must never silently exempt a method
_LOCK_HOLDING = re.compile(r"^lock-holding:\s*([\w,\s]+)")
_SINGLE_THREADED = re.compile(r"^single-threaded\b")


def _self_attr(node: ast.expr) -> str | None:
    """``self.<name>`` -> name, else None."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _def_annotation_lines(source, node: ast.FunctionDef) -> list[str]:
    """Annotation candidates for a method, one comment line per entry:
    trailing comments on the def line AND on the first decorator line,
    plus the contiguous block of comment-ONLY lines directly above the
    decorator stack.  Line-granular so the escape-hatch regexes can
    anchor to line start (prose inside one annotation's explanation must
    never activate another)."""
    first = node.decorator_list[0].lineno if node.decorator_list else node.lineno
    parts = list(source.comments.get(node.lineno, ()))
    if first != node.lineno:
        parts.extend(source.comments.get(first, ()))
    lines = source.text.splitlines()
    line = first - 1
    while 1 <= line <= len(lines) and lines[line - 1].strip().startswith("#"):
        parts.append(lines[line - 1].strip().lstrip("#").strip())
        line -= 1
    return parts


class _MethodVisitor(ast.NodeVisitor):
    """Walk one method body tracking which locks are lexically held."""

    def __init__(self, source, class_name, method_name, guarded, findings):
        self.source = source
        self.class_name = class_name
        self.method_name = method_name
        self.guarded = guarded  # attr -> (lock, writes_only)
        self.findings = findings
        self.held: list[str] = []

    def visit_With(self, node: ast.With):
        acquired = []
        for item in node.items:
            lock = _self_attr(item.context_expr)
            if lock is not None:
                acquired.append(lock)
            else:
                # a non-lock context expr can itself touch guarded state
                # (``with self._calls[...]``) — check it outside the lock
                self.visit(item.context_expr)
            if item.optional_vars is not None:
                self.visit(item.optional_vars)
        self.held.extend(acquired)
        for stmt in node.body:
            self.visit(stmt)
        if acquired:
            del self.held[-len(acquired):]

    def _enter_closure(self, node):
        # closures execute later, possibly on another thread: analyze
        # their bodies with NO inherited locks
        saved, self.held = self.held, []
        for stmt in ast.iter_child_nodes(node):
            self.visit(stmt)
        self.held = saved

    def visit_FunctionDef(self, node):
        self._enter_closure(node)

    def visit_AsyncFunctionDef(self, node):
        self._enter_closure(node)

    def visit_Lambda(self, node):
        self._enter_closure(node)

    def visit_Attribute(self, node: ast.Attribute):
        attr = _self_attr(node)
        if attr is not None and attr in self.guarded:
            lock, writes_only = self.guarded[attr]
            is_write = not isinstance(node.ctx, ast.Load)
            if lock not in self.held and (is_write or not writes_only):
                access = "write" if is_write else "read"
                self.findings.append(
                    Finding(
                        CHECKER,
                        self.source.path,
                        f"{self.class_name}.{self.method_name}:{attr}",
                        f"{access} of self.{attr} (guarded-by: {lock}"
                        f"{' (writes)' if writes_only else ''}) outside "
                        f"'with self.{lock}:' — take the lock, mark the "
                        f"method '# lock-holding: {lock}', or waive with "
                        "a justification",
                        line=node.lineno,
                    )
                )
        self.generic_visit(node)

def _attr_note(source, line: int):
    """guarded-by annotation for the assignment at ``line``: the
    trailing comment on the line itself, or a comment-ONLY line directly
    above — a neighboring attribute's trailing annotation never bleeds."""
    for comment in source.comments.get(line, ()):
        note = _GUARDED_BY.search(comment)
        if note is not None:
            return note
    lines = source.text.splitlines()
    above = line - 1
    while 1 <= above <= len(lines) and lines[above - 1].strip().startswith("#"):
        note = _GUARDED_BY.search(lines[above - 1].strip())
        if note is not None:
            return note
        above -= 1
    return None


def _collect_guarded(source, cls: ast.ClassDef) -> dict[str, tuple[str, bool]]:
    guarded: dict[str, tuple[str, bool]] = {}
    for method in cls.body:
        if not isinstance(method, ast.FunctionDef):
            continue
        for node in ast.walk(method):
            targets = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, ast.AnnAssign):
                targets = [node.target]
            else:
                continue
            note = _attr_note(source, node.lineno)
            if note is None:
                continue
            for target in targets:
                attr = _self_attr(target)
                if attr is not None:
                    guarded[attr] = (note.group(1), bool(note.group(2)))
    return guarded


def _method_exemptions(source, method: ast.FunctionDef) -> tuple[set[str], bool]:
    holding: set[str] = set()
    single_threaded = False
    for note in _def_annotation_lines(source, method):
        match = _LOCK_HOLDING.match(note)
        if match:
            holding.update(
                part.strip()
                for part in match.group(1).split(",")
                if part.strip()
            )
        if _SINGLE_THREADED.match(note):
            single_threaded = True
    return holding, single_threaded


@register(CHECKER)
def check(sources) -> list[Finding]:
    findings: list[Finding] = []
    for source in sources:
        if source.tree is None or "guarded-by:" not in source.text:
            continue
        for cls in ast.walk(source.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            guarded = _collect_guarded(source, cls)
            if not guarded:
                continue
            for method in cls.body:
                if not isinstance(method, ast.FunctionDef):
                    continue
                if method.name == "__init__":
                    continue  # the single-threaded construction window
                holding, exempt = _method_exemptions(source, method)
                if exempt:
                    continue
                visitor = _MethodVisitor(
                    source, cls.name, method.name, guarded, findings
                )
                visitor.held = list(holding)
                for stmt in method.body:
                    visitor.visit(stmt)
    return findings
