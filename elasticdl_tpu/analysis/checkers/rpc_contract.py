"""rpc-contract: deadlines on every client, one idempotency registry.

Two rules, both hard-won:

1. **Deadline threading.**  Every construction of an RPC client class
   (``RpcClient`` or any class inheriting from it, discovered from the
   scanned sources) must pass a ``deadlines=`` keyword — the
   :mod:`elasticdl_tpu.rpc.deadline` policy object (or an expression
   evaluating to None where the caller consciously opts out).  A
   construction site without the keyword is exactly how a blackholed
   link regains the power to hang a thread forever: the policy exists,
   but this one client never heard of it.  The framework-internal
   single resolution site (``RpcClient._call`` calling
   ``deadline_for``) is pinned too: it must exist, in exactly one
   client-side module.

2. **Idempotency classification.**  Every method string named in a
   server method table (module-level ``*_METHODS`` tuples) or in a
   retryable set (``*RETRYABLE*`` / ``*IDEMPOTENT*`` assignments) must
   be a key of ``IDEMPOTENCY`` in :mod:`elasticdl_tpu.rpc.idempotency`
   — new RPC methods fail the build until someone writes down why a
   duplicate delivery is safe.  A method classified ``not-retryable``
   must not appear in any retryable set.
"""

from __future__ import annotations

import ast

from elasticdl_tpu.analysis.core import Finding, enclosing_names, register

CHECKER = "rpc-contract"

_BASE_CLIENT = "RpcClient"


def _string_elements(
    node: ast.expr, resolved: dict[str, list[str]] | None = None
) -> list[str] | None:
    """Literal strings of a tuple/set/list/frozenset(...) display.

    ``resolved`` maps module-level names to already-collected string
    tables, so the repo's own ``MASTER_RETRYABLE_METHODS =
    frozenset(_METHODS)`` shape resolves instead of silently skipping —
    a computed set the checker can't see would make the retry-safety
    rule vacuous exactly where the master's retryable set lives.
    """
    if isinstance(node, ast.Call) and not node.keywords and len(node.args) == 1:
        func = node.func
        name = func.id if isinstance(func, ast.Name) else getattr(func, "attr", "")
        if name in ("frozenset", "set", "tuple", "list"):
            return _string_elements(node.args[0], resolved)
    if isinstance(node, ast.Name) and resolved is not None:
        return resolved.get(node.id)
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.BitOr):
        # union of tables (a | b): resolve both sides or give up
        left = _string_elements(node.left, resolved)
        right = _string_elements(node.right, resolved)
        if left is not None and right is not None:
            return left + right
        return None
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        out = []
        for element in node.elts:
            if isinstance(element, ast.Constant) and isinstance(element.value, str):
                out.append(element.value)
            else:
                return None  # computed table: not this checker's business
        return out
    return None


def _registry_keys(sources) -> tuple[dict[str, str], str | None]:
    """Parse IDEMPOTENCY from the scanned tree; (method -> class, path)."""
    for source in sources:
        if source.tree is None or "IDEMPOTENCY" not in source.text:
            continue
        for node in ast.walk(source.tree):
            targets = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets = [node.target]
            else:
                continue
            names = {t.id for t in targets if isinstance(t, ast.Name)}
            if "IDEMPOTENCY" not in names or not isinstance(
                getattr(node, "value", None), ast.Dict
            ):
                continue
            registry: dict[str, str] = {}
            for key, value in zip(node.value.keys, node.value.values):
                if not (isinstance(key, ast.Constant) and isinstance(key.value, str)):
                    continue
                klass = ""
                if isinstance(value, ast.Tuple) and value.elts:
                    first = value.elts[0]
                    if isinstance(first, ast.Constant) and isinstance(first.value, str):
                        klass = first.value
                elif isinstance(value, ast.Constant) and isinstance(value.value, str):
                    klass = value.value
                registry[key.value] = klass
            return registry, source.path
    return {}, None


@register(CHECKER)
def check(sources) -> list[Finding]:
    findings: list[Finding] = []

    # ---- discover client classes (RpcClient + subclasses, transitively)
    client_classes = {_BASE_CLIENT}
    grew = True
    while grew:
        grew = False
        for source in sources:
            if source.tree is None:
                continue
            for node in ast.walk(source.tree):
                if isinstance(node, ast.ClassDef) and node.name not in client_classes:
                    bases = {
                        b.id if isinstance(b, ast.Name) else getattr(b, "attr", "")
                        for b in node.bases
                    }
                    if bases & client_classes:
                        client_classes.add(node.name)
                        grew = True

    registry, registry_path = _registry_keys(sources)

    # ---- scan: constructions, method tables, retryable sets, deadline_for
    deadline_resolution_sites: list[tuple[str, int]] = []
    table_methods: list[tuple[str, str, int, str]] = []  # path, name, line, method
    retryable_methods: list[tuple[str, str, int, str]] = []

    for source in sources:
        if source.tree is None:
            continue
        enclosing = None
        # pre-pass: literal string tables by name, so a second pass can
        # resolve frozenset(_METHODS)-style references
        module_tables: dict[str, list[str]] = {}
        for node in ast.walk(source.tree):
            if isinstance(node, ast.Assign):
                elements = _string_elements(node.value)
                if elements is None:
                    continue
                for target in node.targets:
                    if isinstance(target, ast.Name):
                        module_tables[target.id] = elements
        for node in ast.walk(source.tree):
            # 1) client constructions must thread a deadline policy
            if isinstance(node, ast.Call):
                func = node.func
                callee = func.id if isinstance(func, ast.Name) else getattr(func, "attr", "")
                if callee in client_classes:
                    kwargs = {kw.arg for kw in node.keywords}
                    if "deadlines" not in kwargs and None not in kwargs:
                        if enclosing is None:
                            enclosing = enclosing_names(source.tree)
                        where = enclosing.get(node.lineno, "<module>")
                        findings.append(
                            Finding(
                                CHECKER,
                                source.path,
                                f"{where}:{callee}",
                                f"{callee}(...) constructed without a "
                                "deadlines= policy — this client's calls "
                                "can hang forever on a blackholed link; "
                                "pass DeadlinePolicy.from_env() (workers) "
                                "or the job policy (master), explicitly "
                                "None only with a waiver",
                                line=node.lineno,
                            )
                        )
                if (
                    isinstance(func, ast.Attribute)
                    and func.attr == "deadline_for"
                    and "deadline.py" not in source.path
                ):
                    deadline_resolution_sites.append((source.path, node.lineno))
            # 2) method tables / retryable sets
            if isinstance(node, ast.Assign):
                for target in node.targets:
                    if not isinstance(target, ast.Name):
                        continue
                    elements = _string_elements(node.value, module_tables)
                    if elements is None:
                        continue
                    upper = target.id.upper()
                    if upper.endswith("_METHODS") and "RETRYABLE" not in upper:
                        for method in elements:
                            table_methods.append(
                                (source.path, target.id, node.lineno, method)
                            )
                    if "RETRYABLE" in upper or "IDEMPOTENT" in upper:
                        for method in elements:
                            retryable_methods.append(
                                (source.path, target.id, node.lineno, method)
                            )

    # ---- registry coverage
    if registry_path is None:
        if table_methods or retryable_methods:
            findings.append(
                Finding(
                    CHECKER,
                    "elasticdl_tpu/rpc/idempotency.py",
                    "IDEMPOTENCY",
                    "no IDEMPOTENCY registry found in the scanned sources "
                    "but RPC method tables exist — the retry-safety "
                    "registry is required",
                )
            )
    else:
        for path, table, line, method in table_methods + retryable_methods:
            if method not in registry:
                findings.append(
                    Finding(
                        CHECKER,
                        path,
                        f"{table}:{method}",
                        f"RPC method {method!r} (in {table}) is not "
                        f"classified in {registry_path} — new methods "
                        "fail the build until someone writes down why a "
                        "duplicate delivery is safe",
                        line=line,
                    )
                )
        for path, table, line, method in retryable_methods:
            if registry.get(method) == "not-retryable":
                findings.append(
                    Finding(
                        CHECKER,
                        path,
                        f"{table}:{method}",
                        f"method {method!r} is classified not-retryable "
                        f"but appears in retryable set {table}",
                        line=line,
                    )
                )

    # ---- the single framework resolution site
    if any(s.path.endswith("rpc/service.py") for s in sources):
        if len(deadline_resolution_sites) != 1:
            sites = ", ".join(f"{p}:{ln}" for p, ln in deadline_resolution_sites)
            findings.append(
                Finding(
                    CHECKER,
                    "elasticdl_tpu/rpc/service.py",
                    "deadline_for",
                    "expected exactly ONE client-side deadline resolution "
                    f"site (RpcClient._call); found {len(deadline_resolution_sites)}"
                    + (f" ({sites})" if sites else "")
                    + " — per-call-site deadline math drifts; route "
                    "through the policy object",
                )
            )
    return findings
