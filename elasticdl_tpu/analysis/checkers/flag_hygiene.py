"""flag-hygiene: the worker-argv byte-identity contract, machine-checked.

The master reconstructs each worker's command line from its own parsed
namespace (``utils/args.py: build_worker_arguments``).  Byte-identity —
a feature left off must leave worker argv and the k8s golden manifests
byte-for-byte unchanged — rests on three mechanisms this checker pins:

- **FH1 master-group filtering**: every flag registered inside the
  master-only group (``_add_master_params``) must appear in
  ``_MASTER_ONLY_FLAGS`` so it is ALWAYS filtered from worker argv.  A
  new master flag missing from the filter silently leaks into every
  worker command line.
- **FH2 no stale filter entries**: every ``_MASTER_ONLY_FLAGS`` name
  must be registered by some ``add_argument`` — a stale entry means the
  filter and the parser drifted.
- **FH3 optional shared flags default to None**: a flag registered in a
  SHARED group (one used by both the master and worker parsers) with an
  explicit ``required=False`` is, by this repo's convention, a
  post-baseline feature gate: it must have ``default=None`` so an unset
  flag is DROPPED from the reconstructed argv (None values are
  skipped), keeping worker argv byte-identical with the feature off.
- **FH4 the drop mechanism exists**: ``build_arguments_from_parsed_
  result`` must still contain the ``value is None`` skip — the single
  behavior every default-None flag relies on.

The checker finds the flag module structurally (any scanned file
defining both ``_MASTER_ONLY_FLAGS`` and ``build_arguments_from_
parsed_result``), so falsification fixtures can carry a miniature one.
"""

from __future__ import annotations

import ast

from elasticdl_tpu.analysis.core import Finding, register

CHECKER = "flag-hygiene"

_MASTER_GROUP = "_add_master_params"
_FILTER_NAME = "_MASTER_ONLY_FLAGS"
_BUILDER = "build_arguments_from_parsed_result"
_MASTER_GROUPS_NAME = "_MASTER_GROUPS"
_WORKER_GROUPS_NAME = "_WORKER_GROUPS"


def _dest_of(call: ast.Call) -> str | None:
    """``add_argument("--flag", ...)`` -> ``flag`` (explicit dest= wins)."""
    for kw in call.keywords:
        if kw.arg == "dest" and isinstance(kw.value, ast.Constant):
            return str(kw.value.value)
    if call.args and isinstance(call.args[0], ast.Constant):
        raw = str(call.args[0].value)
        if raw.startswith("--"):
            return raw[2:].replace("-", "_")
    return None


def _kw(call: ast.Call, name: str) -> ast.expr | None:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _group_names(tree: ast.Module, assign_name: str) -> set[str]:
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for target in node.targets:
                if isinstance(target, ast.Name) and target.id == assign_name:
                    return {
                        e.id
                        for e in getattr(node.value, "elts", ())
                        if isinstance(e, ast.Name)
                    }
    return set()


def _filter_set(tree: ast.Module) -> set[str] | None:
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for target in node.targets:
                if isinstance(target, ast.Name) and target.id == _FILTER_NAME:
                    value = node.value
                    if isinstance(value, ast.Call) and value.args:
                        value = value.args[0]
                    elements = getattr(value, "elts", None)
                    if elements is None:
                        return None
                    return {
                        e.value
                        for e in elements
                        if isinstance(e, ast.Constant) and isinstance(e.value, str)
                    }
    return None


@register(CHECKER)
def check(sources) -> list[Finding]:
    findings: list[Finding] = []
    for source in sources:
        if source.tree is None:
            continue
        if _FILTER_NAME not in source.text or _BUILDER not in source.text:
            continue
        tree = source.tree
        # the flag module is the file that ASSIGNS the filter and DEFINES
        # the builder (not one that merely mentions their names, like
        # this checker's own source)
        assigns_filter = any(
            isinstance(n, ast.Assign)
            and any(
                isinstance(t, ast.Name) and t.id == _FILTER_NAME
                for t in n.targets
            )
            for n in ast.walk(tree)
        )
        defines_builder = any(
            isinstance(n, ast.FunctionDef) and n.name == _BUILDER
            for n in ast.walk(tree)
        )
        if not (assigns_filter and defines_builder):
            continue
        master_only = _filter_set(tree)
        if master_only is None:
            findings.append(
                Finding(
                    CHECKER,
                    source.path,
                    _FILTER_NAME,
                    f"{_FILTER_NAME} is not a literal frozenset of flag "
                    "names — the checker (and reviewers) must be able to "
                    "read the filter",
                )
            )
            continue

        master_groups = _group_names(tree, _MASTER_GROUPS_NAME)
        worker_groups = _group_names(tree, _WORKER_GROUPS_NAME)
        shared_groups = master_groups & worker_groups

        all_dests: set[str] = set()
        for func in ast.walk(tree):
            if not isinstance(func, ast.FunctionDef):
                continue
            for node in ast.walk(func):
                if not (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "add_argument"
                ):
                    continue
                dest = _dest_of(node)
                if dest is None:
                    continue
                all_dests.add(dest)
                # FH1: master-group flags must be filtered
                if func.name == _MASTER_GROUP and dest not in master_only:
                    findings.append(
                        Finding(
                            CHECKER,
                            source.path,
                            dest,
                            f"--{dest} is registered in {_MASTER_GROUP} "
                            f"but missing from {_FILTER_NAME}: it leaks "
                            "into every reconstructed worker argv",
                            line=node.lineno,
                        )
                    )
                # FH3: optional shared flags default to None
                if func.name in shared_groups:
                    required = _kw(node, "required")
                    default = _kw(node, "default")
                    explicitly_optional = (
                        isinstance(required, ast.Constant)
                        and required.value is False
                    )
                    default_is_none = (
                        isinstance(default, ast.Constant)
                        and default.value is None
                    )
                    if (
                        explicitly_optional
                        and not default_is_none
                        and dest not in master_only
                    ):
                        findings.append(
                            Finding(
                                CHECKER,
                                source.path,
                                dest,
                                f"--{dest} is an optional shared flag "
                                "(required=False in a group both parsers "
                                "use) whose default is not None: when "
                                "unset it still appears in reconstructed "
                                "worker argv, breaking the byte-identity "
                                "contract — default to None or filter it",
                                line=node.lineno,
                            )
                        )
        # FH2: stale filter entries
        for name in sorted(master_only - all_dests):
            findings.append(
                Finding(
                    CHECKER,
                    source.path,
                    name,
                    f"{_FILTER_NAME} names {name!r} but no add_argument "
                    "defines it — the filter and the parser drifted",
                )
            )
        # FH4: the None-drop mechanism
        builder = next(
            (
                n
                for n in ast.walk(tree)
                if isinstance(n, ast.FunctionDef) and n.name == _BUILDER
            ),
            None,
        )
        has_drop = False
        if builder is not None:
            for node in ast.walk(builder):
                if isinstance(node, ast.Compare) and any(
                    isinstance(op, ast.Is) for op in node.ops
                ):
                    if any(
                        isinstance(c, ast.Constant) and c.value is None
                        for c in node.comparators
                    ):
                        has_drop = True
        if not has_drop:
            findings.append(
                Finding(
                    CHECKER,
                    source.path,
                    _BUILDER,
                    f"{_BUILDER} no longer skips None values — every "
                    "default-None feature flag relies on that drop for "
                    "argv byte-identity",
                    line=getattr(builder, "lineno", 0),
                )
            )
    return findings
