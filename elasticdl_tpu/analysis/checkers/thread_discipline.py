"""thread-discipline: every thread is daemon or provably joined.

A non-daemon, never-joined thread is how a "completed" job hangs at
interpreter exit (the netchaos smoke's zero-hung-threads gate exists
because this class of bug shipped).  For every ``threading.Thread(...)``
construction the checker requires one of:

- ``daemon=True`` in the constructor keywords;
- the constructed object (``t = threading.Thread(...)`` or
  ``self._t = ...``) has ``<t>.daemon = True`` assigned, or
  ``<t>.join(`` called, somewhere in the same module — lexical
  evidence the thread cannot outlive the process silently;
- a waiver with a justification.

``daemon=<expr>`` (non-literal) counts as handled: the author made an
explicit choice the reviewer can see.  Thread SUBCLASS instantiations
are out of scope — the subclass's ``super().__init__(daemon=True)``
already names the choice at one definition site.
"""

from __future__ import annotations

import ast

from elasticdl_tpu.analysis.core import Finding, register

CHECKER = "thread-discipline"


def _is_thread_ctor(call: ast.Call) -> bool:
    func = call.func
    if isinstance(func, ast.Name) and func.id == "Thread":
        return True
    return (
        isinstance(func, ast.Attribute)
        and func.attr == "Thread"
        and isinstance(func.value, ast.Name)
        and func.value.id == "threading"
    )


def _target_name(parents: dict, call: ast.Call) -> str | None:
    """Name/attr the Thread was assigned to, if directly assigned."""
    node = parents.get(call)
    if isinstance(node, ast.Assign) and len(node.targets) == 1:
        target = node.targets[0]
        if isinstance(target, ast.Name):
            return target.id
        if isinstance(target, ast.Attribute):
            return target.attr
    return None


def _module_joins_or_daemonizes(tree: ast.Module, name: str) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            if node.func.attr == "join":
                base = node.func.value
                base_name = (
                    base.id
                    if isinstance(base, ast.Name)
                    else getattr(base, "attr", None)
                )
                if base_name == name:
                    return True
        if isinstance(node, ast.Assign):
            for target in node.targets:
                if (
                    isinstance(target, ast.Attribute)
                    and target.attr == "daemon"
                ):
                    base = target.value
                    base_name = (
                        base.id
                        if isinstance(base, ast.Name)
                        else getattr(base, "attr", None)
                    )
                    if base_name == name:
                        return True
    return False


@register(CHECKER)
def check(sources) -> list[Finding]:
    findings: list[Finding] = []
    for source in sources:
        if source.tree is None or "Thread" not in source.text:
            continue
        parents: dict = {}
        for node in ast.walk(source.tree):
            for child in ast.iter_child_nodes(node):
                parents[child] = node
        # enclosing function names for stable symbols
        enclosing: dict[int, str] = {}

        def name_spans(node, prefix=""):
            for child in ast.iter_child_nodes(node):
                if isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
                ):
                    label = f"{prefix}.{child.name}" if prefix else child.name
                    end = getattr(child, "end_lineno", child.lineno)
                    for line in range(child.lineno, end + 1):
                        enclosing[line] = label
                    name_spans(child, label)
                else:
                    name_spans(child, prefix)

        name_spans(source.tree)
        for node in ast.walk(source.tree):
            if not (isinstance(node, ast.Call) and _is_thread_ctor(node)):
                continue
            if any(kw.arg == "daemon" for kw in node.keywords) or any(
                kw.arg is None for kw in node.keywords
            ):
                continue
            target = _target_name(parents, node)
            if target and _module_joins_or_daemonizes(source.tree, target):
                continue
            where = enclosing.get(node.lineno, "<module>")
            symbol = f"{where}:{target or 'anonymous'}"
            findings.append(
                Finding(
                    CHECKER,
                    source.path,
                    symbol,
                    "threading.Thread constructed without daemon= and "
                    "never joined/daemonized in this module — a silent "
                    "non-daemon thread hangs process exit; pass "
                    "daemon=True, join it, or waive with a justification",
                    line=node.lineno,
                )
            )
    return findings
