"""hot-path: disabled-telemetry fast paths stay free; no stray print().

The telemetry spine's overhead contract (pinned by clock-poison tests,
now machine-checked): when telemetry is NOT installed, the per-step /
per-dispatch hook is ONE module-global load and a ``None`` check — no
clock read, no allocation, no attribute chase.  Functions opt in with a
comment on (or directly above) their ``def`` line:

    def record_step(step, records=0):  # elastic-lint: hot-path

The checker examines the function's **disabled prefix** — every
statement up to and including the first ``if`` whose test is an
``is None`` / ``not x`` check, plus that guard's taken suite (the code
that runs when telemetry is off).  Inside the prefix it forbids:

- calls with arguments (a zero-argument accessor like ``get_recorder()``
  is the one allowed call shape), and ANY call whose terminal name is a
  known clock (``monotonic``, ``perf_counter``, ``time`` ...);
- non-empty container displays and comprehensions (allocations);
- f-strings (allocation + formatting);
- attribute chains deeper than 3 (``a.b.c`` is the pinned shape limit);
- ``print``.

A function annotated hot-path with NO early-return guard is checked in
full (it should be a trivial accessor).

Separately — repo-wide, no annotation needed — ``print()`` calls are
forbidden outside the allowlisted CLI entry points whose stdout IS
their product: runtime output goes through the logger or the telemetry
spine, where it is structured and greppable.  (This subsumes the old
``check_telemetry_names.py`` bare-print regex, and being AST-based it
also catches indented/conditional prints the regex missed.)
"""

from __future__ import annotations

import ast

from elasticdl_tpu.analysis.core import Finding, enclosing_names, register

CHECKER = "hot-path"

_ANNOTATION = "elastic-lint: hot-path"

_CLOCK_NAMES = frozenset(
    {
        "monotonic",
        "monotonic_ns",
        "perf_counter",
        "perf_counter_ns",
        "process_time",
        "thread_time",
        "time",
        "time_ns",
        "clock_gettime",
    }
)

_MAX_ATTR_DEPTH = 3

# CLI entry points whose stdout IS their product (reports, dataset
# paths, analysis results); everything else logs
PRINT_ALLOWLIST = (
    "elasticdl_tpu/analysis/",
    "elasticdl_tpu/chaos/runner.py",
    "elasticdl_tpu/fleetsim/runner.py",
    "elasticdl_tpu/telemetry/report.py",
    "elasticdl_tpu/telemetry/trace.py",
    "elasticdl_tpu/client.py",
    "elasticdl_tpu/data/recordio/build.py",
    "elasticdl_tpu/data/recordio_gen/",
)


def _attr_depth(node: ast.Attribute) -> int:
    depth = 0
    while isinstance(node, ast.Attribute):
        depth += 1
        node = node.value
    return depth + 1  # the base name


def _is_disabled_guard(test: ast.expr) -> bool:
    """``x is None`` / ``not x`` — the disabled-telemetry check shape."""
    if isinstance(test, ast.Compare) and len(test.ops) == 1:
        if isinstance(test.ops[0], ast.Is) and isinstance(
            test.comparators[0], ast.Constant
        ) and test.comparators[0].value is None:
            return True
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        return True
    return False


def _audit_fast_node(source, func_name, node, findings):
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            callee = sub.func
            terminal = (
                callee.id
                if isinstance(callee, ast.Name)
                else getattr(callee, "attr", "")
            )
            if terminal in _CLOCK_NAMES:
                findings.append(
                    Finding(
                        CHECKER,
                        source.path,
                        f"{func_name}:clock",
                        f"clock read ({terminal}) on the disabled fast "
                        "path — the off state must cost one global load "
                        "+ None check",
                        line=sub.lineno,
                    )
                )
            elif sub.args or sub.keywords:
                findings.append(
                    Finding(
                        CHECKER,
                        source.path,
                        f"{func_name}:call",
                        f"call with arguments ({ast.unparse(callee)}) on "
                        "the disabled fast path — only a zero-arg gate "
                        "accessor is allowed before the None check",
                        line=sub.lineno,
                    )
                )
        elif isinstance(
            sub, (ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp)
        ):
            findings.append(
                Finding(
                    CHECKER,
                    source.path,
                    f"{func_name}:alloc",
                    "comprehension on the disabled fast path (allocation)",
                    line=sub.lineno,
                )
            )
        elif isinstance(sub, (ast.List, ast.Set, ast.Dict, ast.Tuple)):
            if getattr(sub, "elts", None) or getattr(sub, "keys", None):
                findings.append(
                    Finding(
                        CHECKER,
                        source.path,
                        f"{func_name}:alloc",
                        "non-empty container literal on the disabled "
                        "fast path (allocation)",
                        line=sub.lineno,
                    )
                )
        elif isinstance(sub, ast.JoinedStr):
            findings.append(
                Finding(
                    CHECKER,
                    source.path,
                    f"{func_name}:alloc",
                    "f-string on the disabled fast path (allocation)",
                    line=sub.lineno,
                )
            )
    _audit_attr_chains(source, func_name, node, findings)


def _audit_attr_chains(source, func_name, node, findings):
    class V(ast.NodeVisitor):
        def visit_Attribute(self, attr_node: ast.Attribute):
            depth = _attr_depth(attr_node)
            if depth > _MAX_ATTR_DEPTH:
                findings.append(
                    Finding(
                        CHECKER,
                        source.path,
                        f"{func_name}:attr-chain",
                        f"attribute chain of depth {depth} on the "
                        f"disabled fast path (pinned shape is "
                        f"<= {_MAX_ATTR_DEPTH})",
                        line=attr_node.lineno,
                    )
                )
            # do not descend: inner attributes are part of this chain

    V().visit(node)


def _check_hot_function(source, func: ast.FunctionDef, findings):
    name = func.name
    prefix: list[ast.stmt] = []
    for stmt in func.body:
        if (
            isinstance(stmt, ast.Expr)
            and isinstance(stmt.value, ast.Constant)
            and isinstance(stmt.value.value, str)
        ):
            continue  # docstring
        if isinstance(stmt, ast.If) and _is_disabled_guard(stmt.test):
            prefix.append(stmt.test)
            prefix.extend(stmt.body)  # the disabled suite
            break
        prefix.append(stmt)
    else:
        # no guard: the whole body is the fast path (trivial accessor)
        pass
    for node in prefix:
        _audit_fast_node(source, name, node, findings)


@register(CHECKER)
def check(sources) -> list[Finding]:
    findings: list[Finding] = []
    for source in sources:
        if source.tree is None:
            continue
        allowlisted = any(
            source.path.startswith(prefix) or f"/{prefix}" in source.path
            for prefix in PRINT_ALLOWLIST
        )
        enclosing = None
        for node in ast.walk(source.tree):
            if (
                not allowlisted
                and isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "print"
            ):
                if enclosing is None:
                    enclosing = enclosing_names(source.tree)
                # symbol anchored to the enclosing def, not the line —
                # a waived intentional print must survive edits above it
                findings.append(
                    Finding(
                        CHECKER,
                        source.path,
                        f"print:{enclosing.get(node.lineno, '<module>')}",
                        "print() outside the CLI allowlist — use the "
                        "logger or the telemetry event log",
                        line=node.lineno,
                    )
                )
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                first = (
                    node.decorator_list[0].lineno
                    if node.decorator_list
                    else node.lineno
                )
                # look at BOTH the def line and the decorator-stack top:
                # a decorated function's trailing annotation sits on the
                # def line, which is not `first`
                note = source.comment_on(first)
                if first != node.lineno:
                    note += " " + source.comment_on(node.lineno)
                if _ANNOTATION in note:
                    _check_hot_function(source, node, findings)
    return findings
