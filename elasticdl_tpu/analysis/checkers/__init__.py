"""Checker modules; importing this package registers every checker."""

from elasticdl_tpu.analysis.checkers import (  # noqa: F401
    flag_hygiene,
    hot_path,
    lock_discipline,
    rpc_contract,
    telemetry_names,
    thread_discipline,
)
