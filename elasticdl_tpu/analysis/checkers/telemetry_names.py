"""telemetry-names: the naming lint, absorbed from scripts/.

Same contracts ``scripts/check_telemetry_names.py`` enforced since PR 2
(that script is now a thin shim over this checker):

1. every metric/event/span name passed literally to a registration call
   is snake_case;
2. each such name has exactly ONE registration site (multi-module names
   live in a shared constant: the ``EVENT_*`` vocabulary in
   ``telemetry/events.py``, ``SPAN_*`` in ``telemetry/tracing.py``,
   ``PHASE_*`` in ``telemetry/anatomy.py``);
3. the constant vocabularies are snake_case, defined once, and contain
   the REQUIRED names downstream tooling scrapes (smokes, report
   sections, /metrics gates);
4. required metric families are registered somewhere.

The bare-print rule the script also carried lives in the ``hot-path``
checker now (AST-based, so it catches indented prints too).

Regex-over-text like the original — registration calls wrap across
lines, and names are string literals, so regex is the right tool; the
required-vocabulary rules only engage when the canonical telemetry
modules are in the scanned set (fixture trees can carry miniatures).
"""

from __future__ import annotations

import re

from elasticdl_tpu.analysis.core import Finding, register

CHECKER = "telemetry-names"

SNAKE_CASE = re.compile(r"^[a-z][a-z0-9_]*$")
METRIC_CALL = re.compile(
    r"\.(?:counter|gauge|histogram)\(\s*[\"']([^\"']+)[\"']", re.S
)
EMIT_CALL = re.compile(r"(?:\.emit|emit_event)\(\s*[\"']([^\"']+)[\"']", re.S)
SPAN_CALL = re.compile(
    r"(?:\.start_span|\.record_span|trace_span)\(\s*[\"']([^\"']+)[\"']",
    re.S,
)
EVENT_CONST = re.compile(r"^EVENT_\w+\s*=\s*[\"']([^\"']+)[\"']", re.M)
SPAN_CONST = re.compile(r"^SPAN_\w+\s*=\s*[\"']([^\"']+)[\"']", re.M)
PHASE_CONST = re.compile(r"^PHASE_\w+\s*=\s*[\"']([^\"']+)[\"']", re.M)

REQUIRED_EVENT_NAMES = frozenset(
    {
        "replica_push",
        "replica_restore",
        "replica_harvest",
        "master_restart",
        "journal_replay",
        "worker_rehome",
        "slice_loss",
        "mesh_resize",
        "autoscale_decision",
        "rpc_fault_injected",
        "step_anatomy",
        "serving_request",
        "model_swap",
        "fleet_fault",
        # memory observability plane (telemetry/memory.py) + the
        # on-demand profiler round trip (utils/profiling.py)
        "memory_sample",
        "memory_pressure",
        "profile_window_open",
        "profile_window_close",
        # sharded embedding subsystem (elasticdl_tpu/embeddings): the
        # host-tier pull into the device minitable and the admission
        # fault when neither tier has headroom
        "embedding_gather",
        "embedding_spill_fault",
        # SLO watchdog plane (telemetry/slo.py + telemetry/incident.py):
        # detector fire/clear transitions and the incident lifecycle
        "slo_violation",
        "slo_recovered",
        "incident_open",
        "incident_close",
        # streaming subsystem (elasticdl_tpu/streaming): the watermark/
        # lag tick pair and the live train->serve push (freshness ledger)
        "stream_watermark",
        "stream_lag",
        "live_push",
    }
)
REQUIRED_SPAN_NAMES = frozenset(
    {
        "replica_push",
        "replica_restore",
        "replica_harvest",
        "compile",
        "master_restart",
        "journal_replay",
        "worker_rehome",
        "slice_loss",
        "mesh_resize",
        "autoscale_decision",
        "rpc_degraded",
        "step_anatomy",
        "serving_request",
        "model_swap",
        "fleet_fault",
        # the XLA profiler capture window (flag-armed or on-demand)
        "profile_window",
        # the SLO watchdog burn window: first bad evaluation -> fire
        "slo_watch",
        # serving fleet tracing: one trace per request — client root,
        # router (re)route children, replica queue/engine split, and
        # the batched dispatch group LINKED to its member traces
        "predict_request",
        "route",
        "reroute",
        "queue",
        "engine",
        "serving_dispatch",
        # streaming: one span per live train->serve push (harvest ->
        # swap accepted)
        "live_push",
    }
)
REQUIRED_PHASE_NAMES = frozenset(
    {
        "host_fetch",
        "assemble",
        "h2d_transfer",
        "device_compute",
        "step_bookkeeping",
        "untracked",
        "queue_wait",
        "d2h_transfer",
        "boundary_stall",
    }
)
REQUIRED_METRIC_NAMES = frozenset(
    {
        "elasticdl_compile_total",
        "elasticdl_rpc_deadline_exceeded_total",
        "elasticdl_rpc_latency_seconds",
        "elasticdl_step_phase_ms_total",
        "elasticdl_step_phase_seconds",
        "elasticdl_device_prefetch_groups_total",
        "elasticdl_device_prefetch_stall_ms_total",
        "elasticdl_device_prefetch_stage_ms_total",
        "elasticdl_boundary_stall_ms_total",
        "elasticdl_serving_latency_seconds",
        "elasticdl_serving_requests_total",
        "elasticdl_serving_swaps_total",
        # thousand-worker control plane (coalesced heartbeat fan-in,
        # incremental dead-worker sweep, cardinality-bounded per-worker
        # series) — the fleetsim scale budgets scrape these
        "elasticdl_heartbeats_total",
        "elasticdl_heartbeat_batches_total",
        "elasticdl_dead_worker_sweep_ms_total",
        "elasticdl_worker_heartbeat_age_secs",
        # memory observability plane: the component-level byte ledger
        # (component= / kind=current|peak gauge family)
        "elasticdl_memory_bytes",
        # sharded embedding subsystem: per-table resident bytes by tier
        # (table= / tier=device|spill)
        "elasticdl_embedding_bytes",
        # SLO watchdog plane: per-objective detector state (objective= /
        # window=fast|slow) and the incident counter — registered at one
        # site each inside SLOEngine.mirror_metrics
        "elasticdl_slo_violations_total",
        "elasticdl_slo_objective_ok",
        "elasticdl_slo_burn_rate",
        "elasticdl_slo_incidents_total",
        # serving fleet fan-in: router-side per-replica families over
        # the probe-beat merge (replica= label under the PR-13
        # cardinality cap) — registered at one site each inside
        # serving/metrics.py FleetMetrics._collect
        "elasticdl_serving_replica_queue_rows",
        "elasticdl_serving_replica_outstanding",
        "elasticdl_serving_replica_probe_age_secs",
        "elasticdl_serving_replica_shed_total",
        "elasticdl_serving_replica_errors_total",
        "elasticdl_serving_replica_phase_ms_total",
        # streaming subsystem: the backlog signal pair (lag in records,
        # source/trained watermark by role=) and the live-push counter —
        # registered at one site each inside MasterTelemetry's collect
        "elasticdl_stream_lag_records",
        "elasticdl_stream_watermark",
        "elasticdl_stream_live_push_total",
    }
)

# (path suffix of the canonical vocabulary module, const pattern, label,
# required set)
_VOCABULARIES = (
    ("telemetry/events.py", EVENT_CONST, "event", REQUIRED_EVENT_NAMES),
    ("telemetry/tracing.py", SPAN_CONST, "span", REQUIRED_SPAN_NAMES),
    ("telemetry/anatomy.py", PHASE_CONST, "phase", REQUIRED_PHASE_NAMES),
)


def _line_of(text: str, offset: int) -> int:
    return text.count("\n", 0, offset) + 1


@register(CHECKER)
def check(sources) -> list[Finding]:
    findings: list[Finding] = []
    metric_sites: dict[str, list[tuple[str, int]]] = {}
    event_sites: dict[str, list[tuple[str, int]]] = {}
    span_sites: dict[str, list[tuple[str, int]]] = {}

    scanned = [s for s in sources if "/analysis/" not in f"/{s.path}"]
    for source in scanned:
        for pattern, sites in (
            (METRIC_CALL, metric_sites),
            (EMIT_CALL, event_sites),
            (SPAN_CALL, span_sites),
        ):
            for match in pattern.finditer(source.text):
                sites.setdefault(match.group(1), []).append(
                    (source.path, _line_of(source.text, match.start()))
                )

    for kind, sites in (
        ("metric", metric_sites),
        ("event", event_sites),
        ("span", span_sites),
    ):
        for name, where in sorted(sites.items()):
            path, line = where[0]
            if not SNAKE_CASE.match(name):
                findings.append(
                    Finding(
                        CHECKER,
                        path,
                        f"{kind}:{name}",
                        f"{kind} name {name!r} is not snake_case",
                        line=line,
                    )
                )
            if len(where) > 1:
                rendered = ", ".join(f"{p}:{ln}" for p, ln in where)
                findings.append(
                    Finding(
                        CHECKER,
                        path,
                        f"multisite:{kind}:{name}",
                        f"{kind} name {name!r} registered at "
                        f"{len(where)} sites ({rendered}); hoist it into "
                        "a shared constant with one definition site",
                        line=line,
                    )
                )

    have_canonical = any(
        s.path.endswith(_VOCABULARIES[0][0]) for s in scanned
    )
    if have_canonical:
        for name in sorted(REQUIRED_METRIC_NAMES - set(metric_sites)):
            findings.append(
                Finding(
                    CHECKER,
                    "elasticdl_tpu/telemetry",
                    f"required:metric:{name}",
                    f"required metric {name!r} is not registered anywhere "
                    "(smoke/report scrape contract)",
                )
            )

    for suffix, pattern, label, required in _VOCABULARIES:
        source = next((s for s in scanned if s.path.endswith(suffix)), None)
        if source is None:
            continue
        values = pattern.findall(source.text)
        for value in values:
            if not SNAKE_CASE.match(value):
                findings.append(
                    Finding(
                        CHECKER,
                        source.path,
                        f"const:{label}:{value}",
                        f"{label} constant value {value!r} is not "
                        "snake_case",
                    )
                )
        for value in sorted({v for v in values if values.count(v) > 1}):
            findings.append(
                Finding(
                    CHECKER,
                    source.path,
                    f"const:{label}:{value}",
                    f"{label} name {value!r} defined more than once",
                )
            )
        for value in sorted(required - set(values)):
            findings.append(
                Finding(
                    CHECKER,
                    source.path,
                    f"required:{label}:{value}",
                    f"required {label} name {value!r} missing from the "
                    "shared vocabulary (downstream tooling contract)",
                )
            )
    return findings
