"""CLI: ``python -m elasticdl_tpu.analysis [--json] [paths...]``.

Exit codes: 0 = clean (waived findings allowed), 1 = unwaived findings,
2 = usage error.  ``--json`` prints the machine-readable result to
stdout (the human rendering moves to stderr); ``--output PATH``
additionally writes the JSON artifact (what ``scripts/run_tier1.sh``
collects as ``analysis_result.json``).
"""

from __future__ import annotations

import argparse
import json
import sys

from elasticdl_tpu.analysis.core import checker_ids, run_analysis


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m elasticdl_tpu.analysis",
        description="elastic-lint: static contract analysis for this repo",
    )
    parser.add_argument(
        "paths",
        nargs="*",
        help="Files/directories to analyze (default: the elasticdl_tpu package)",
    )
    parser.add_argument(
        "--json", action="store_true", help="JSON result on stdout"
    )
    parser.add_argument(
        "--output", default="", help="Also write the JSON result to this file"
    )
    parser.add_argument(
        "--checkers",
        default="",
        help="Comma-separated checker subset (default: all). "
        f"Available: {', '.join(checker_ids())}",
    )
    parser.add_argument(
        "--waivers",
        default="",
        help="Waivers file (default: elasticdl_tpu/analysis/waivers.toml)",
    )
    parser.add_argument(
        "--root",
        default="",
        help="Root for repo-relative finding paths (default: the repo root; "
        "fixture tests point this at the fixture tree)",
    )
    args = parser.parse_args(argv)

    result = run_analysis(
        paths=args.paths or None,
        root=args.root or None,
        only=(
            [c.strip() for c in args.checkers.split(",") if c.strip()]
            if args.checkers
            else None
        ),
        waivers_path=args.waivers or None,
    )
    unwaived = result.pop("_unwaived_findings")

    human = sys.stderr if args.json else sys.stdout
    for finding in unwaived:
        print(f"elastic-lint: {finding.render()}", file=human)
    verdict = (
        "OK" if result["ok"] else f"FAIL ({result['unwaived']} unwaived finding(s))"
    )
    print(
        f"elastic-lint: {verdict} — {result['files_scanned']} files, "
        f"{len(result['checkers'])} checkers, {result['waived']} waived",
        file=human,
    )
    if args.json:
        json.dump(result, sys.stdout, indent=2, sort_keys=True)
        sys.stdout.write("\n")
    if args.output:
        with open(args.output, "w", encoding="utf-8") as f:
            json.dump(result, f, indent=2, sort_keys=True)
            f.write("\n")
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
