"""elastic-lint: whole-repo static analysis for this repo's contracts.

Four of the last five PRs found latent races and contract violations
only by hand or by chaos luck: the ``_rehome_pending`` gRPC-thread vs
run-loop race, the ``RpcClient`` call-table snapshot race under
re-resolve, the non-idempotent ``report_evaluation_metrics``
double-accumulation, the double-banked compile delta.  The codebase
already encodes its safety rules — lock-guarded fields,
deadline-on-every-RPC, idempotent-only retry, flags-default-None argv
byte-identity, one-registration-site telemetry names — but only as
prose in design docs and as hand-written pins in tests.  This package
makes the machine check them on every tier-1 run:

    python -m elasticdl_tpu.analysis [--json] [--output PATH] [paths...]

Zero dependencies (stdlib ``ast`` + ``tokenize``), pluggable checkers
(:mod:`.checkers`), and a waivers file
(``elasticdl_tpu/analysis/waivers.toml``) where every intentional
exception carries a mandatory one-line justification.  Checkers:

- ``lock-discipline``  — attributes annotated ``# guarded-by: <lock>``
  are only touched inside ``with self.<lock>:`` or methods documented
  ``# lock-holding: <lock>`` (the ``_rehome_pending`` bug class);
- ``rpc-contract``     — every RPC client construction threads a
  deadline policy, and every method named in a retryable set is
  classified in :mod:`elasticdl_tpu.rpc.idempotency` (new methods fail
  the build until classified);
- ``flag-hygiene``     — master-group flags are filtered from worker
  argv and optional shared flags default to ``None`` (the argv
  byte-identity contract);
- ``hot-path``         — disabled-telemetry fast paths stay one global
  load + ``None`` check (no clock reads, no allocations), and no
  ``print()`` outside CLI modules;
- ``thread-discipline``— every ``threading.Thread`` is daemon or
  provably joined;
- ``telemetry-names``  — the naming lint absorbed from
  ``scripts/check_telemetry_names.py`` (snake_case, one registration
  site, required vocabulary).

See docs/designs/static_analysis.md for the checker taxonomy, the
annotation grammar, and the waiver policy.
"""

from elasticdl_tpu.analysis.core import (  # noqa: F401
    Finding,
    load_sources,
    run_analysis,
)
