"""Framework: sources, findings, checker registry, the analysis run.

A checker is a function ``check(sources) -> list[Finding]`` registered
under a stable id.  Findings carry a ``(checker, path, symbol)``
identity triple — line numbers are display-only, so a waiver written
against a finding survives unrelated edits above it.

Sources are parsed ONCE (ast + a line->comments map from tokenize) and
shared by every checker; a file that does not parse is itself a finding
(``parse-error``), never a crash.
"""

from __future__ import annotations

import ast
import io
import os
import tokenize
from dataclasses import dataclass, field


@dataclass(frozen=True)
class Finding:
    """One contract violation.

    ``symbol`` is the stable within-file identity a waiver matches on
    (e.g. ``MasterServicer.heartbeat:_worker_rpc_stats``); ``line`` is
    for humans and editors only.
    """

    checker: str
    path: str  # repo-relative, forward slashes
    symbol: str
    message: str
    line: int = 0

    @property
    def key(self) -> tuple[str, str, str]:
        return (self.checker, self.path, self.symbol)

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.checker}] {self.symbol}: {self.message}"


@dataclass
class SourceFile:
    """One parsed Python file shared by all checkers."""

    path: str  # repo-relative, forward slashes
    abspath: str
    text: str
    tree: ast.Module | None
    # line number -> list of comment strings on that line (text after
    # '#', stripped); the annotation grammar reads these
    comments: dict[int, list[str]] = field(default_factory=dict)

    def comment_on(self, line: int) -> str:
        """Comments attached to ``line``: the line itself plus the line
        directly above (annotations may trail the code or precede it)."""
        parts = []
        for candidate in (line - 1, line):
            parts.extend(self.comments.get(candidate, ()))
        return " ".join(parts)


def enclosing_names(tree: ast.Module) -> dict[int, str]:
    """line -> dotted enclosing function/class name (innermost wins).

    The shared symbol-stability helper: checkers anchor finding symbols
    to the enclosing def/class, never to line numbers, so waivers
    survive edits elsewhere in the file.
    """
    spans: list[tuple[int, int, str]] = []

    def walk(node, prefix):
        for child in ast.iter_child_nodes(node):
            if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                name = f"{prefix}.{child.name}" if prefix else child.name
                end = getattr(child, "end_lineno", child.lineno)
                spans.append((child.lineno, end, name))
                walk(child, name)
            else:
                walk(child, prefix)

    walk(tree, "")
    index: dict[int, str] = {}
    for start, end, name in sorted(spans):
        for line in range(start, end + 1):
            index[line] = name  # innermost wins (nested spans sort later)
    return index


def _extract_comments(text: str) -> dict[int, list[str]]:
    comments: dict[int, list[str]] = {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(text).readline):
            if tok.type == tokenize.COMMENT:
                comments.setdefault(tok.start[0], []).append(
                    tok.string.lstrip("#").strip()
                )
    except (tokenize.TokenError, IndentationError, SyntaxError):
        pass  # the ast parse reports the real error as a finding
    return comments


def repo_root() -> str:
    """The directory holding the ``elasticdl_tpu`` package."""
    return os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )


def default_paths() -> list[str]:
    return [os.path.join(repo_root(), "elasticdl_tpu")]


def _iter_py_files(paths: list[str]):
    for path in paths:
        if os.path.isfile(path):
            yield path
            continue
        for root, dirs, files in os.walk(path):
            dirs[:] = sorted(d for d in dirs if d != "__pycache__")
            for name in sorted(files):
                if name.endswith(".py"):
                    yield os.path.join(root, name)


def load_sources(
    paths: list[str] | None = None, root: str | None = None
) -> tuple[list[SourceFile], list[Finding]]:
    """Parse every .py under ``paths``; returns (sources, parse findings)."""
    root = root or repo_root()
    paths = paths or default_paths()
    files: list[SourceFile] = []
    findings: list[Finding] = []
    seen: set[str] = set()
    for abspath in _iter_py_files([os.path.abspath(p) for p in paths]):
        if abspath in seen:
            continue
        seen.add(abspath)
        rel = os.path.relpath(abspath, root).replace(os.sep, "/")
        try:
            with open(abspath, encoding="utf-8") as f:
                text = f.read()
        except OSError as ex:
            findings.append(
                Finding("parse-error", rel, "io", f"unreadable: {ex}")
            )
            continue
        try:
            tree = ast.parse(text, filename=abspath)
        except SyntaxError as ex:
            findings.append(
                Finding(
                    "parse-error",
                    rel,
                    "syntax",
                    f"does not parse: {ex.msg}",
                    line=ex.lineno or 0,
                )
            )
            tree = None
        files.append(
            SourceFile(
                path=rel,
                abspath=abspath,
                text=text,
                tree=tree,
                comments=_extract_comments(text),
            )
        )
    return files, findings


# ---- checker registry -------------------------------------------------------

_CHECKERS: dict[str, object] = {}


def register(checker_id: str):
    def wrap(fn):
        _CHECKERS[checker_id] = fn
        return fn

    return wrap


def checker_ids() -> list[str]:
    _ensure_loaded()
    return sorted(_CHECKERS)


def _ensure_loaded():
    if not _CHECKERS:
        from elasticdl_tpu.analysis import checkers  # noqa: F401 — registers


def run_analysis(
    paths: list[str] | None = None,
    root: str | None = None,
    only: list[str] | None = None,
    waivers_path: str | None = None,
) -> dict:
    """Run the suite; returns the result dict the CLI renders.

    ``only`` restricts to the named checkers (waiver hygiene then only
    audits waivers belonging to them).  Waived findings are carried in
    the result (marked) but do not affect the verdict; unknown/unused/
    unjustified waivers are findings in their own right.
    """
    from elasticdl_tpu.analysis import waivers as waivers_mod

    _ensure_loaded()
    sources, findings = load_sources(paths, root=root)
    selected = (
        {c: _CHECKERS[c] for c in only if c in _CHECKERS}
        if only is not None
        else dict(_CHECKERS)
    )
    unknown = [] if only is None else [c for c in only if c not in _CHECKERS]
    for name in unknown:
        findings.append(
            Finding(
                "usage",
                "elasticdl_tpu/analysis",
                name,
                f"unknown checker {name!r} (have: {', '.join(sorted(_CHECKERS))})",
            )
        )
    for checker_id in sorted(selected):
        findings.extend(selected[checker_id](sources))

    waiver_set, waiver_findings = waivers_mod.load(waivers_path)
    if only is not None:
        waiver_set = [w for w in waiver_set if w.checker in selected]
    findings.extend(waiver_findings)
    matched: set[int] = set()
    waived_keys: set[tuple[str, str, str]] = set()
    for finding in findings:
        for i, waiver in enumerate(waiver_set):
            if waiver.matches(finding):
                matched.add(i)
                waived_keys.add(finding.key)
                break
    for i, waiver in enumerate(waiver_set):
        if i not in matched:
            findings.append(
                Finding(
                    "waiver-hygiene",
                    waiver.origin,
                    f"{waiver.checker}:{waiver.path}:{waiver.symbol}",
                    "stale waiver: no current finding matches it — delete "
                    "it (waivers must not outlive the exception they "
                    "justify)",
                )
            )
    unwaived = [f for f in findings if f.key not in waived_keys]
    waived = [f for f in findings if f.key in waived_keys]
    return {
        "checkers": sorted(selected) + (["waiver-hygiene"]),
        "files_scanned": len(sources),
        "waivers": len(waiver_set),
        "findings": [
            {
                "checker": f.checker,
                "path": f.path,
                "line": f.line,
                "symbol": f.symbol,
                "message": f.message,
                "waived": f.key in waived_keys,
            }
            for f in findings
        ],
        "unwaived": len(unwaived),
        "waived": len(waived),
        "ok": not unwaived,
        "_unwaived_findings": unwaived,  # object form for callers; CLI strips
    }
