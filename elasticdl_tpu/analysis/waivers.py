"""Waivers: intentional exceptions, each with a mandatory justification.

``elasticdl_tpu/analysis/waivers.toml`` holds ``[[waiver]]`` tables:

    [[waiver]]
    checker = "flag-hygiene"
    path = "elasticdl_tpu/utils/args.py"
    symbol = "model_zoo"
    reason = "baseline flag predating the default-None convention"

A waiver matches a finding when ``checker``, ``path`` and ``symbol``
are all equal — line numbers never participate, so waivers survive
edits elsewhere in the file.  ``reason`` is REQUIRED and must be
non-empty: a waiver without a justification is itself a finding, and so
is a stale waiver that no longer matches anything (core.run_analysis).

Python 3.10 has no ``tomllib``, and this package is zero-dep by
contract, so the loader is a minimal parser for exactly the subset the
file uses: ``[[waiver]]`` table headers, ``key = "basic string"``
pairs, comments, blank lines.  Anything else is a loud finding, not a
silent skip.
"""

from __future__ import annotations

import os
import re
from dataclasses import dataclass

from elasticdl_tpu.analysis.core import Finding

WAIVERS_FILENAME = "waivers.toml"

_HEADER = re.compile(r"^\[\[\s*waiver\s*\]\]$")
_PAIR = re.compile(r'^(\w+)\s*=\s*"((?:[^"\\]|\\.)*)"\s*(?:#.*)?$')
_REQUIRED_KEYS = ("checker", "path", "symbol", "reason")


@dataclass(frozen=True)
class Waiver:
    checker: str
    path: str
    symbol: str
    reason: str
    origin: str  # waivers file (repo-relative) for hygiene findings

    def matches(self, finding: Finding) -> bool:
        return (
            finding.checker == self.checker
            and finding.path == self.path
            and finding.symbol == self.symbol
        )


def default_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)), WAIVERS_FILENAME)


def load(path: str | None = None) -> tuple[list[Waiver], list[Finding]]:
    """Parse the waivers file; malformed entries become findings."""
    path = path or default_path()
    origin = "elasticdl_tpu/analysis/" + os.path.basename(path)
    waivers: list[Waiver] = []
    findings: list[Finding] = []
    if not os.path.exists(path):
        return waivers, findings
    with open(path, encoding="utf-8") as f:
        lines = f.read().splitlines()

    entries: list[tuple[int, dict[str, str]]] = []
    current: dict[str, str] | None = None
    for lineno, raw in enumerate(lines, 1):
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        if _HEADER.match(line):
            current = {}
            entries.append((lineno, current))
            continue
        pair = _PAIR.match(line)
        if pair and current is not None:
            current[pair.group(1)] = (
                pair.group(2).replace('\\"', '"').replace("\\\\", "\\")
            )
            continue
        findings.append(
            Finding(
                "waiver-hygiene",
                origin,
                f"line-{lineno}",
                f"unparseable waivers line {lineno}: {line!r} (only "
                '[[waiver]] tables of key = "value" pairs are allowed)',
                line=lineno,
            )
        )
    for lineno, entry in entries:
        missing = [k for k in _REQUIRED_KEYS if not entry.get(k, "").strip()]
        if missing:
            findings.append(
                Finding(
                    "waiver-hygiene",
                    origin,
                    f"line-{lineno}",
                    f"waiver at line {lineno} missing required "
                    f"non-empty {', '.join(missing)} — every waiver "
                    "carries a justification",
                    line=lineno,
                )
            )
            continue
        waivers.append(
            Waiver(
                checker=entry["checker"],
                path=entry["path"],
                symbol=entry["symbol"],
                reason=entry["reason"],
                origin=origin,
            )
        )
    return waivers, findings
