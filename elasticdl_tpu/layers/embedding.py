"""Distributed embedding layers — the TPU redesign of the EDL sparse path.

The reference implements large embeddings as PS-side hash-sharded python
dicts (``ps/embedding_table.py``) looked up via a ``pull_embedding_vector``
RPC issued *in the middle of the forward pass*
(``elasticdl/embedding_delegate.py:64-96``), with gradients routed back to
the PS by id-hash scatter (``worker/worker.py:499-511``).

None of that survives contact with XLA: a jit-traced step cannot call out
over RPC, and per-id dict ops are exactly what kills TPU throughput.  The
TPU-native design instead:

- the table is ONE array ``(vocab, dim)`` laid out over the mesh with a
  ``PartitionSpec`` on the vocab dim (the analogue of id-hash sharding —
  contiguous range sharding instead of mod-N, which is what XLA can tile);
- lookup is ``jnp.take`` *inside* the jitted step: GSPMD lowers a gather
  from a vocab-sharded operand to the same all-to-all/allgather exchange
  the reference does by hand over gRPC, but fused, on ICI, and overlapped
  with compute;
- gradients flow to the table like any other parameter (an
  ``IndexedSlices``-style scatter-add XLA emits natively), so the
  ``OptimizerWrapper`` slot-injection machinery (ps/optimizer_wrapper.py)
  collapses into ordinary optax state, sharded identically to the table via
  the same PartitionSpec rules.

Sparse (ragged) inputs are represented jit-compatibly as a fixed-width
``(batch, max_ids)`` int array padded with ``-1`` plus optional weights —
the static-shape analogue of tf.SparseTensor that keeps the MXU fed.
``Dataset.padded_sparse`` (data layer) produces this layout.
"""

from __future__ import annotations

import re
from typing import Any, Callable, Optional, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from elasticdl_tpu.utils.constants import (
    EMBEDDING_AUTO_DISTRIBUTE_BYTES,
    Initializer,
    MeshAxis,
)

PAD_ID = -1

Combiner = ("sum", "mean", "sqrtn")


def resolve_initializer(name_or_fn) -> Callable:
    """Map the reference's initializer-name strings (layers/embedding.py
    accepts Keras initializer names) to jax.nn.initializers."""
    if callable(name_or_fn):
        return name_or_fn
    name = str(name_or_fn).lower()
    if name in (Initializer.UNIFORM, "uniform", "random_uniform"):
        return nn.initializers.uniform(scale=0.05)
    if name in (Initializer.NORMAL, "random_normal"):
        return nn.initializers.normal(stddev=0.05)
    if name == Initializer.ZEROS:
        return nn.initializers.zeros
    if name == Initializer.ONES:
        return nn.initializers.ones
    if name == "glorot_uniform":
        return nn.initializers.glorot_uniform()
    raise ValueError(f"unknown embedding initializer: {name_or_fn!r}")


def embedding_lookup(table: jax.Array, ids: jax.Array) -> jax.Array:
    """Dense lookup: ``ids`` of any shape -> ``ids.shape + (dim,)``.

    Out-of-range ids — padded (< 0) OR past the table (>= rows) — return
    zero vectors and contribute exactly zero gradient.  The upper bound
    matters: under jit ``jnp.take`` CLIPS out-of-bounds indices, so an
    out-of-vocab id would silently read (and backprop into) the LAST
    table row — a corrupt-data bug that trains the wrong embedding
    instead of failing or masking.  Same mask contract as the
    shape-canonical batching weights (zero weight => zero gradient).
    """
    mask = (ids >= 0) & (ids < table.shape[0])
    safe = jnp.where(mask, ids, 0)
    out = jnp.take(table, safe, axis=0)
    return out * mask[..., None].astype(out.dtype)


def safe_embedding_lookup_sparse(
    table: jax.Array,
    ids: jax.Array,
    weights: Optional[jax.Array] = None,
    combiner: str = "mean",
) -> jax.Array:
    """Combined lookup over padded-sparse ids — the in-jit equivalent of the
    delegate's ``safe_embedding_lookup_sparse`` reimplementation
    (embedding_delegate.py:98-221): combiners sum/mean/sqrtn, empty rows
    yield zeros.

    ids: ``(batch, max_ids)`` int, padded with ``PAD_ID``.
    weights: optional ``(batch, max_ids)`` float; pads are ignored either way.
    Returns ``(batch, dim)``.

    Out-of-range handling is deterministic in BOTH directions: ids < 0
    (the pad) and ids >= the table's rows are masked out of the combine
    and contribute exactly zero gradient.  Without the upper bound,
    jit-mode ``jnp.take`` clips an out-of-vocab id onto the last row —
    it would join the combine AND receive gradient, silently corrupting
    that row (pinned by test_out_of_vocab_id_zero_gradient).
    """
    if combiner not in Combiner:
        raise ValueError(f"combiner must be one of {Combiner}, got {combiner}")
    in_range = (ids >= 0) & (ids < table.shape[0])
    mask = in_range.astype(table.dtype)
    safe = jnp.where(in_range, ids, 0)
    emb = jnp.take(table, safe, axis=0)  # (b, k, d)
    w = mask if weights is None else weights.astype(table.dtype) * mask
    summed = jnp.einsum("bk,bkd->bd", w, emb)
    if combiner == "sum":
        return summed
    if combiner == "mean":
        denom = jnp.sum(w, axis=-1)
    else:  # sqrtn
        denom = jnp.sqrt(jnp.sum(w * w, axis=-1))
    return summed / jnp.maximum(denom, 1e-12)[:, None]


class Embedding(nn.Module):
    """The ``elasticdl.layers.Embedding`` equivalent.

    Reference (`elasticdl/python/elasticdl/layers/embedding.py:7-148`):
    dense-id input -> per-id vectors; sparse input + combiner -> combined
    row per example.  Here both paths are pure jit-traceable array ops on a
    mesh-sharded table; distribution is decided by sharding rules (see
    :func:`auto_partition_rules`), not by a different layer class.
    """

    input_dim: int
    output_dim: int
    embeddings_initializer: Any = Initializer.UNIFORM
    combiner: Optional[str] = None  # None => dense lookup
    dtype: Any = jnp.float32
    # Table rows are padded up to a multiple of this so odd vocab sizes
    # (e.g. frappe's 5383) still divide evenly over mesh axes; padded rows
    # are never looked up, so their gradients stay zero.  1 = no padding.
    vocab_pad_multiple: int = 1

    @property
    def padded_input_dim(self) -> int:
        m = max(1, self.vocab_pad_multiple)
        return ((self.input_dim + m - 1) // m) * m

    @nn.compact
    def __call__(self, ids, weights=None):
        table = self.param(
            "embedding",
            resolve_initializer(self.embeddings_initializer),
            (self.padded_input_dim, self.output_dim),
            self.dtype,
        )
        ids = jnp.asarray(ids)
        if ids.dtype not in (jnp.int32, jnp.int64):
            ids = ids.astype(jnp.int32)
        if self.combiner is not None:
            if ids.ndim != 2:
                raise ValueError(
                    "combiner lookup expects (batch, max_ids) padded ids, "
                    f"got shape {ids.shape}"
                )
            return safe_embedding_lookup_sparse(
                table, ids, weights, self.combiner
            )
        return embedding_lookup(table, ids)


class SparseEmbedding(nn.Module):
    """Combiner embedding whose table is DECLARED shard-eligible — the
    recommender-scale counterpart (reference
    keras/layers/sparse_embedding.py:7, the layer that always lived on
    the parameter servers regardless of size).

    Same math as :class:`Embedding` with a combiner; kept as a distinct
    class so policy can tell "always distribute" from "distribute when
    large" the way the reference distinguishes SparseEmbedding from
    rewritten Keras Embedding (model_handler.py:199-241).  The sharded
    embedding subsystem (:mod:`elasticdl_tpu.embeddings`) treats every
    ``SparseEmbedding`` table as row-partitionable: models export
    ``sharding_rules(mesh)`` built from
    :func:`elasticdl_tpu.embeddings.sharded_table_rules`, which
    range-shards the ``embedding`` param over the mesh's embedding axis
    (ep > tp > fsdp, falling back to dp on pure-data-parallel worlds).
    ``vocab_pad_multiple`` keeps odd vocabs divisible over any such axis.
    """

    input_dim: int
    output_dim: int
    combiner: str = "sum"
    embeddings_initializer: Any = Initializer.UNIFORM
    dtype: Any = jnp.float32
    vocab_pad_multiple: int = 1

    @property
    def padded_input_dim(self) -> int:
        m = max(1, self.vocab_pad_multiple)
        return ((self.input_dim + m - 1) // m) * m

    @nn.compact
    def __call__(self, ids, weights=None):
        table = self.param(
            "embedding",
            resolve_initializer(self.embeddings_initializer),
            (self.padded_input_dim, self.output_dim),
            self.dtype,
        )
        ids = jnp.asarray(ids)
        if ids.dtype not in (jnp.int32, jnp.int64):
            ids = ids.astype(jnp.int32)
        return safe_embedding_lookup_sparse(table, ids, weights, self.combiner)


# ---- distribution policy ---------------------------------------------------


def _preferred_axes(mesh) -> list[str]:
    """Vocab-sharding axis preference: ep (dedicated embedding axis) first,
    then tp, then fsdp — never bare dp (batch sharding stays on dp)."""
    return [
        a
        for a in (MeshAxis.EP, MeshAxis.TP, MeshAxis.FSDP)
        if a in mesh.axis_names and mesh.shape[a] > 1
    ]


def auto_partition_rules(
    params_or_shapes,
    mesh,
    threshold_bytes: int = EMBEDDING_AUTO_DISTRIBUTE_BYTES,
) -> list:
    """Sharding rules distributing every embedding table bigger than the
    reference's 2MB policy threshold (model_handler.py:47-55).

    Scans parameter paths ending in ``embedding`` with 2-D shape; tables
    over the threshold get ``P(axis, None)`` (vocab-range sharding — the
    contiguous analogue of the reference's id-mod-N hash sharding,
    hash_utils.py:9) on the best-fitting mesh axis, falling back to the
    output dim if the vocab doesn't divide.  Returns first-match-wins rules
    for :func:`elasticdl_tpu.parallel.sharding.infer_param_specs`.
    """
    from elasticdl_tpu.parallel.sharding import Rule
    from elasticdl_tpu.utils.tree_utils import _key_str

    axes = _preferred_axes(mesh)
    if not axes:
        return []
    rules = []
    flat, _ = jax.tree_util.tree_flatten_with_path(params_or_shapes)
    for path_entries, leaf in flat:
        path = "/".join(_key_str(k) for k in path_entries)
        if not path.split("/")[-1].startswith("embedding"):
            continue
        shape = tuple(getattr(leaf, "shape", np.shape(leaf)))
        if len(shape) != 2:
            continue
        dtype = getattr(leaf, "dtype", np.dtype(np.float32))
        nbytes = int(np.prod(shape)) * np.dtype(dtype).itemsize
        if nbytes <= threshold_bytes:
            continue
        # anchor at a path boundary so "emb/embedding" can't also claim
        # "big_emb/embedding"
        pattern = r"(^|/)" + re.escape(path) + "$"
        for axis in axes:
            size = mesh.shape[axis]
            if shape[0] % size == 0:
                rules.append(Rule(pattern, P(axis, None)))
                break
            if shape[1] % size == 0:
                rules.append(Rule(pattern, P(None, axis)))
                break
    return rules
