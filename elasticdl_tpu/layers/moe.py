"""Mixture-of-experts MLP with expert parallelism over the ``ep`` axis.

GShard/Switch-style top-1 routing, expressed as dense dispatch/combine
einsums so GSPMD derives the expert all-to-all from the shardings: expert
weight tensors carry a leading ``num_experts`` dimension sharded over
``ep`` (``moe_sharding_rules``), tokens arrive sharded over ``dp``/``sp``,
and XLA inserts the token all-to-all where the two layouts meet — the
TPU-native counterpart of the reference's only sharded-parameter feature
(id-hash embedding sharding, ``hash_utils.py``), generalized to compute.

No reference counterpart otherwise; listed in DEVIATIONS.md additions.
"""

from __future__ import annotations

import math

import flax.linen as nn
import jax
import jax.numpy as jnp


def _pick_group_size(n_tokens: int, target: int) -> int:
    """Largest divisor of ``n_tokens`` that is <= target."""
    g = min(target, n_tokens)
    while n_tokens % g:
        g -= 1
    return max(g, 1)


# fan_in must count only the per-expert receptive field: axis 0 is the
# expert "batch" dimension, not part of any one expert's fan
_expert_init = nn.initializers.variance_scaling(
    1.0, "fan_in", "truncated_normal", batch_axis=(0,)
)


class MoEMLP(nn.Module):
    """Drop-in MLP replacement: top-1 routed experts with capacity.

    Routing is GROUPED (GShard's ``gsec`` formulation): tokens dispatch
    within fixed-size groups of ~``group_size``, so the dispatch/combine
    tensors are O(n_tokens * group_capacity), not O(n_tokens^2) — the
    difference between a long-context batch fitting in HBM or not.

    Tokens over an expert's per-group capacity are dropped (contribute
    zero here; the surrounding residual connection carries them through
    unchanged) — the standard Switch trade that keeps every shape static
    for XLA.
    """

    num_experts: int
    hidden_mult: int = 4
    capacity_factor: float = 1.25
    aux_loss_weight: float = 0.01
    group_size: int = 1024

    @nn.compact
    def __call__(self, x, training: bool = False):
        batch, seq, embed = x.shape
        hidden = embed * self.hidden_mult
        n_tokens = batch * seq
        g_size = _pick_group_size(n_tokens, self.group_size)
        groups = n_tokens // g_size
        tokens = x.reshape(groups, g_size, embed)  # (G, g, d)
        capacity = max(
            1,
            int(
                math.ceil(
                    g_size / self.num_experts * self.capacity_factor
                )
            ),
        )

        logits = nn.Dense(self.num_experts, name="router")(
            tokens.astype(jnp.float32)
        )
        probs = jax.nn.softmax(logits, axis=-1)  # (G, g, e)
        expert_index = jnp.argmax(probs, axis=-1)
        expert_onehot = jax.nn.one_hot(
            expert_index, self.num_experts, dtype=jnp.float32
        )  # (G, g, e)
        gate = jnp.max(probs * expert_onehot, axis=-1)  # (G, g)

        # position of each token within its expert's per-group queue;
        # tokens past capacity get dropped by the one_hot below
        position = (
            jnp.cumsum(expert_onehot, axis=1) - expert_onehot
        ) * expert_onehot
        keep = expert_onehot * (position < capacity)
        dispatch = keep[..., None] * jax.nn.one_hot(
            position.astype(jnp.int32), capacity
        )  # (G, g, e, c)
        combine = dispatch * gate[..., None, None]

        # load-balance loss (Switch eq. 4): pushes the router toward
        # uniform expert utilization; joins the training loss via the
        # "losses" collection (trainer/step.py forward_loss)
        fraction = expert_onehot.mean(axis=(0, 1))
        router_prob = probs.mean(axis=(0, 1))
        aux = (
            self.num_experts
            * jnp.sum(fraction * router_prob)
            * self.aux_loss_weight
        )
        self.sow(
            "losses",
            "moe_load_balance",
            aux,
            init_fn=lambda: jnp.zeros((), jnp.float32),
            reduce_fn=lambda _prev, new: new,
        )

        w_in = self.param(
            "w_in", _expert_init, (self.num_experts, embed, hidden)
        )
        w_out = self.param(
            "w_out", _expert_init, (self.num_experts, hidden, embed)
        )
        # all-to-all happens here: tokens (dp/sp-sharded) meet expert
        # weights (ep-sharded)
        expert_in = jnp.einsum(
            "Ggec,Ggd->Gecd", dispatch.astype(x.dtype), tokens
        )
        h = jax.nn.gelu(jnp.einsum("Gecd,edh->Gech", expert_in, w_in))
        expert_out = jnp.einsum("Gech,ehd->Gecd", h, w_out)
        y = jnp.einsum(
            "Ggec,Gecd->Ggd", combine.astype(x.dtype), expert_out
        )
        return y.reshape(batch, seq, embed)


def moe_sharding_rules():
    """Expert-parallel rules: the leading expert dimension of every MoE
    weight shards over ``ep``; composes with default_tp_rules (distinct
    path patterns)."""
    from jax.sharding import PartitionSpec as P

    from elasticdl_tpu.parallel.sharding import Rule

    return [
        Rule(r"(w_in|w_out)$", P("ep", None, None)),
    ]
