from elasticdl_tpu.layers.embedding import (  # noqa: F401
    Embedding,
    SparseEmbedding,
    embedding_lookup,
    safe_embedding_lookup_sparse,
    auto_partition_rules,
)
