"""Flax attention layer over the framework kernels.

``MultiHeadSelfAttention`` projects QKV and dispatches through
:func:`elasticdl_tpu.ops.attention`: ring attention when the trainer's
mesh has an ``sp`` axis > 1 (sequence sharded across devices), else the
pallas flash kernel.  The layer itself is sharding-agnostic — GSPMD lays
out the projections; only the attention inner product needs the explicit
ring schedule.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

import elasticdl_tpu.ops.attention as attention_ops


class MultiHeadSelfAttention(nn.Module):
    num_heads: int
    causal: bool = False
    # grouped-query attention: fewer K/V heads than Q heads (0 = equal);
    # shrinks the KV projection + cache by num_heads/num_kv_heads
    num_kv_heads: int = 0

    @nn.compact
    def __call__(self, x):
        """x: (batch, seq, embed) -> (batch, seq, embed)."""
        embed = x.shape[-1]
        if embed % self.num_heads:
            raise ValueError(
                f"embed dim {embed} not divisible by {self.num_heads} heads"
            )
        head_dim = embed // self.num_heads
        kv_heads = self.num_kv_heads or self.num_heads

        def _proj(name, heads):
            return nn.DenseGeneral(
                features=(heads, head_dim), name=name
            )(x)

        q = _proj("query", self.num_heads)
        k = _proj("key", kv_heads)
        v = _proj("value", kv_heads)
        out = attention_ops.attention(q, k, v, causal=self.causal)
        return nn.DenseGeneral(
            features=embed, axis=(-2, -1), name="out"
        )(out.astype(x.dtype))


class TransformerBlock(nn.Module):
    num_heads: int
    mlp_ratio: int = 4
    causal: bool = False
    dropout_rate: float = 0.0
    # > 0 replaces the dense MLP with a routed expert MLP (layers.moe);
    # shard experts over ep via moe_sharding_rules
    num_experts: int = 0
    num_kv_heads: int = 0  # > 0: grouped-query attention

    @nn.compact
    def __call__(self, x, training: bool = False):
        y = nn.LayerNorm()(x)
        y = MultiHeadSelfAttention(
            num_heads=self.num_heads,
            causal=self.causal,
            num_kv_heads=self.num_kv_heads,
            name="attn",
        )(y)
        if self.dropout_rate:
            y = nn.Dropout(self.dropout_rate, deterministic=not training)(y)
        x = x + y
        y = nn.LayerNorm()(x)
        if self.num_experts > 0:
            from elasticdl_tpu.layers.moe import MoEMLP

            y = MoEMLP(
                num_experts=self.num_experts,
                hidden_mult=self.mlp_ratio,
                name="moe",
            )(y, training=training)
        else:
            # named for the shared megatron tp rules (default_tp_rules)
            y = nn.Dense(x.shape[-1] * self.mlp_ratio, name="mlp_up")(y)
            y = nn.gelu(y)
            y = nn.Dense(x.shape[-1], name="mlp_down")(y)
        if self.dropout_rate:
            y = nn.Dropout(self.dropout_rate, deterministic=not training)(y)
        return x + y


def sinusoidal_positions(seq_len: int, dim: int) -> jnp.ndarray:
    """Fixed sinusoidal position encoding (seq, dim) — parameter-free, so
    a sequence-sharded activation needs no position-table gather."""
    pos = jnp.arange(seq_len)[:, None].astype(jnp.float32)
    div = jnp.exp(
        jnp.arange(0, dim, 2).astype(jnp.float32)
        * (-jnp.log(10000.0) / dim)
    )
    enc = jnp.zeros((seq_len, dim), jnp.float32)
    enc = enc.at[:, 0::2].set(jnp.sin(pos * div))
    enc = enc.at[:, 1::2].set(jnp.cos(pos * div))
    return enc
