"""Flax attention layer over the framework kernels.

``MultiHeadSelfAttention`` projects QKV and dispatches through
:func:`elasticdl_tpu.ops.attention`: ring attention when the trainer's
mesh has an ``sp`` axis > 1 (sequence sharded across devices), else the
pallas flash kernel.  The layer itself is sharding-agnostic — GSPMD lays
out the projections; only the attention inner product needs the explicit
ring schedule.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

import elasticdl_tpu.ops.attention as attention_ops


class MultiHeadSelfAttention(nn.Module):
    num_heads: int
    causal: bool = False
    # grouped-query attention: fewer K/V heads than Q heads (0 = equal);
    # shrinks the KV projection + cache by num_heads/num_kv_heads
    num_kv_heads: int = 0
    # autoregressive decoding: keep a KV cache in the "cache" variable
    # collection (apply with mutable=["cache"]); each call appends one
    # step's K/V and attends over the filled prefix
    decode: bool = False
    max_decode_len: int = 0
    # compute dtype (e.g. bf16): projections and the attention kernel run
    # in it; parameters stay in param_dtype (f32) — mixed precision
    dtype: Any = None

    @nn.compact
    def __call__(self, x, decode_pos=None):
        """x: (batch, seq, embed) -> (batch, seq, embed).

        ``decode_pos``: the model's single decode cursor (traced scalar),
        required in decode mode — there is ONE position source of truth,
        not one per layer."""
        embed = x.shape[-1]
        if embed % self.num_heads:
            raise ValueError(
                f"embed dim {embed} not divisible by {self.num_heads} heads"
            )
        head_dim = embed // self.num_heads
        kv_heads = self.num_kv_heads or self.num_heads

        def _proj(name, heads):
            return nn.DenseGeneral(
                features=(heads, head_dim), dtype=self.dtype, name=name
            )(x)

        q = _proj("query", self.num_heads)
        k = _proj("key", kv_heads)
        v = _proj("value", kv_heads)
        if self.decode:
            if decode_pos is None:
                raise ValueError("decode mode needs decode_pos")
            out = self._decode_attend(q, k, v, decode_pos)
        else:
            out = attention_ops.attention(q, k, v, causal=self.causal)
        return nn.DenseGeneral(
            features=embed, axis=(-2, -1), dtype=self.dtype, name="out"
        )(out.astype(x.dtype))

    def _decode_attend(self, q, k, v, pos):
        """One decode step: append this step's K/V to the cache at
        ``pos``, attend the single query over the filled prefix
        (positions beyond the cursor are masked)."""
        if not self.max_decode_len:
            raise ValueError("decode=True needs max_decode_len")
        if q.shape[1] != 1:
            raise ValueError(
                f"decode mode consumes one token per call, got seq "
                f"{q.shape[1]}"
            )
        batch, _, kv_heads, head_dim = k.shape
        cache_shape = (batch, self.max_decode_len, kv_heads, head_dim)
        ck = self.variable(
            "cache", "k", lambda: jnp.zeros(cache_shape, k.dtype)
        )
        cv = self.variable(
            "cache", "v", lambda: jnp.zeros(cache_shape, v.dtype)
        )
        if not self.is_initializing():
            # init() runs this call once to create the variables; it must
            # NOT consume cache slot 0
            ck.value = jax.lax.dynamic_update_slice(
                ck.value, k, (0, pos, 0, 0)
            )
            cv.value = jax.lax.dynamic_update_slice(
                cv.value, v, (0, pos, 0, 0)
            )

        kf, vf = attention_ops.repeat_kv_heads(q, ck.value, cv.value)
        scale = 1.0 / jnp.sqrt(jnp.asarray(head_dim, jnp.float32))
        scores = (
            jnp.einsum(
                "bqhd,bkhd->bhqk",
                q.astype(jnp.float32),
                kf.astype(jnp.float32),
            )
            * scale
        )
        valid = (
            jnp.arange(self.max_decode_len) <= pos
        )  # filled prefix incl. this step
        scores = jnp.where(
            valid[None, None, None, :], scores, attention_ops._NEG_INF
        )
        probs = jax.nn.softmax(scores, axis=-1)
        out = jnp.einsum(
            "bhqk,bkhd->bqhd", probs, vf.astype(jnp.float32)
        )
        return out.astype(q.dtype)


class TransformerBlock(nn.Module):
    num_heads: int
    mlp_ratio: int = 4
    causal: bool = False
    dropout_rate: float = 0.0
    # > 0 replaces the dense MLP with a routed expert MLP (layers.moe);
    # shard experts over ep via moe_sharding_rules
    num_experts: int = 0
    num_kv_heads: int = 0  # > 0: grouped-query attention
    decode: bool = False  # autoregressive decoding with a KV cache
    max_decode_len: int = 0
    dtype: Any = None  # compute dtype; params stay f32

    @nn.compact
    def __call__(self, x, training: bool = False, decode_pos=None):
        y = nn.LayerNorm(dtype=self.dtype)(x)
        y = MultiHeadSelfAttention(
            num_heads=self.num_heads,
            causal=self.causal,
            num_kv_heads=self.num_kv_heads,
            decode=self.decode,
            max_decode_len=self.max_decode_len,
            dtype=self.dtype,
            name="attn",
        )(y, decode_pos=decode_pos)
        if self.dropout_rate:
            y = nn.Dropout(self.dropout_rate, deterministic=not training)(y)
        x = x + y
        y = nn.LayerNorm(dtype=self.dtype)(x)
        if self.num_experts > 0:
            from elasticdl_tpu.layers.moe import MoEMLP

            y = MoEMLP(
                num_experts=self.num_experts,
                hidden_mult=self.mlp_ratio,
                name="moe",
            )(y, training=training)
        else:
            # named for the shared megatron tp rules (default_tp_rules)
            y = nn.Dense(x.shape[-1] * self.mlp_ratio, dtype=self.dtype,
                         name="mlp_up")(y)
            y = nn.gelu(y)
            y = nn.Dense(x.shape[-1], dtype=self.dtype,
                         name="mlp_down")(y)
        if self.dropout_rate:
            y = nn.Dropout(self.dropout_rate, deterministic=not training)(y)
        return x + y


def sinusoidal_positions(seq_len: int, dim: int) -> jnp.ndarray:
    """Fixed sinusoidal position encoding (seq, dim) — parameter-free, so
    a sequence-sharded activation needs no position-table gather."""
    pos = jnp.arange(seq_len)[:, None].astype(jnp.float32)
    div = jnp.exp(
        jnp.arange(0, dim, 2).astype(jnp.float32)
        * (-jnp.log(10000.0) / dim)
    )
    enc = jnp.zeros((seq_len, dim), jnp.float32)
    enc = enc.at[:, 0::2].set(jnp.sin(pos * div))
    enc = enc.at[:, 1::2].set(jnp.cos(pos * div))
    return enc
