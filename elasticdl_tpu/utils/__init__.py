"""Common substrate: flags, constants, logging, hashing, serde, timing.

Reference: ``elasticdl/python/common/`` (SURVEY.md §2.7).
"""
