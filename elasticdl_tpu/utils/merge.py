"""Monotone max-merge — THE merge rule for worker-shipped totals.

Workers ship process-lifetime monotone counters on the heartbeat (PR-8
RPC outcome totals, PR-9 step-anatomy phase totals).  Beats can be
reordered, duplicated, or replayed after a master restart, so the
server-side merge must be ``max``, never ``sum`` or overwrite: a stale
beat can then never walk an exposed total backward, and a duplicate is
absorbed.  That rule used to live as two hand-rolled loops inside
``MasterServicer.heartbeat`` — one flat, one nested — which is one more
copy than a correctness rule should have.  This module is the single
definition site; the unit test pins the monotonicity and
malformed-input tolerance both call sites rely on.
"""

from __future__ import annotations


def max_merge_counters(
    merged: dict[str, int],
    update: dict,
    watch: frozenset[str] | set[str] = frozenset(),
) -> bool:
    """Max-merge ``update`` into ``merged`` in place.

    Non-int values are skipped (wire payloads are untrusted).  Returns
    True when any ``watch`` key ROSE above its merged value — the
    "an outage-class counter moved since the last beat" signal the
    /healthz degraded-network flag keys off.
    """
    rose = False
    for key, value in update.items():
        try:
            value = int(value)
        except (TypeError, ValueError):
            continue
        if key in watch and value > merged.get(key, 0):
            rose = True
        merged[key] = max(merged.get(key, 0), value)
    return rose


def max_merge_phase_stats(merged: dict[str, dict], update: dict) -> None:
    """Max-merge step-anatomy phase totals in place.

    Shape: ``{phase: {"ms": float, "count": int, "buckets": {str(bound):
    int}}}`` — ms, count and every log bucket are each monotone per
    worker, so each merges independently by max.  A malformed phase
    entry is skipped whole; a malformed bucket value skips the rest of
    that phase's entry (same tolerance the servicer always had).
    """
    for phase, stats in update.items():
        if not isinstance(stats, dict):
            continue
        slot = merged.setdefault(
            phase, {"ms": 0.0, "count": 0, "buckets": {}}
        )
        try:
            slot["ms"] = max(slot["ms"], float(stats.get("ms", 0.0)))
            slot["count"] = max(slot["count"], int(stats.get("count", 0)))
            for bound, n in (stats.get("buckets") or {}).items():
                slot["buckets"][bound] = max(
                    slot["buckets"].get(bound, 0), int(n)
                )
        except (TypeError, ValueError):
            continue
