"""Heartbeat merge rules: monotone max-merge and timestamped last-writer-wins.

Workers ship process-lifetime monotone counters on the heartbeat (PR-8
RPC outcome totals, PR-9 step-anatomy phase totals).  Beats can be
reordered, duplicated, batched by the servicer's coalesced fan-in, or
replayed after a master restart, so the server-side merge must be
``max``, never ``sum`` or overwrite: a stale beat can then never walk
an exposed total backward, and a duplicate is absorbed.  That rule used
to live as two hand-rolled loops inside ``MasterServicer.heartbeat`` —
one flat, one nested — which is one more copy than a correctness rule
should have.  This module is the single definition site; the unit test
pins the monotonicity and malformed-input tolerance both call sites
rely on.

The max rule assumes the shipped value only goes UP.  Memory gauges
(telemetry/memory.py) break that assumption: a model swap releases its
old leaves, a drained queue empties, RSS shrinks — so a max-merged
"current bytes" would be a ratchet that can only report the high-water
mark, never the release.  :func:`last_merge_counters` is the
non-monotone counterpart: every sample carries the SENDER's timestamp,
and the newest-stamped sample wins per key.  Reordering, duplication
and batch-then-replay all converge to the same merged state because
"newest stamp" is order-independent (ties break toward the larger
value, so even same-stamp duplicates are deterministic).  Peak
watermarks stay on :func:`max_merge_counters` — a peak IS monotone.

Both max functions optionally maintain a fleet-wide AGGREGATE alongside
the per-worker maxima: pass ``totals`` and every rise of a per-worker
counter adds its delta there.  That is what lets the servicer answer
"sum of per-worker maxima across the fleet" in O(keys) at scrape time
instead of an O(world_size) walk under its lock — the 1000-worker
scrape path.  The aggregate is exactly ``sum over workers of max over
beats``; the order or batching of beats cannot change it (pinned by
tests/test_fleetsim.py).  ``last_merge_counters`` maintains the same
aggregate shape with signed deltas (values go down too), so the
fleet-wide sum tracks the newest-stamped per-worker values exactly.
"""

from __future__ import annotations


def max_merge_counters(
    merged: dict[str, int],
    update: dict,
    watch: frozenset[str] | set[str] = frozenset(),
    totals: dict[str, int] | None = None,
) -> bool:
    """Max-merge ``update`` into ``merged`` in place.

    Non-int values are skipped (wire payloads are untrusted).  Returns
    True when any ``watch`` key ROSE above its merged value — the
    "an outage-class counter moved since the last beat" signal the
    /healthz degraded-network flag keys off.  ``totals``, when given,
    accumulates each rise's delta (the fleet-wide aggregate).
    """
    rose = False
    for key, value in update.items():
        try:
            value = int(value)
        except (TypeError, ValueError):
            continue
        old = merged.get(key, 0)
        if value > old:
            if key in watch:
                rose = True
            if totals is not None:
                totals[key] = totals.get(key, 0) + (value - old)
            merged[key] = value
    return rose


# reserved key in a last-merge ``stamps`` dict holding the newest
# COMPLETE-snapshot stamp for that worker (no component may be named
# this; component names are snake_case identifiers)
SNAPSHOT_STAMP_KEY = "\x00snapshot"


def last_merge_counters(
    merged: dict[str, int],
    update: dict,
    at: float,
    stamps: dict[str, float],
    totals: dict[str, int] | None = None,
    complete: bool = False,
) -> bool:
    """Timestamped last-writer-wins merge for NON-MONOTONE gauges.

    ``merged[key]`` becomes the value of the newest-stamped sample seen
    for that key; ``stamps[key]`` records that stamp (the caller keeps
    both dicts together, per worker).  A sample older than the stored
    stamp is dropped — a reordered or duplicated beat can never roll a
    gauge back to a stale reading — and equal stamps break toward the
    larger value so any delivery order converges to the same state.
    Non-numeric values are skipped (wire payloads are untrusted).

    ``complete=True`` declares ``update`` a WHOLE snapshot, not a
    per-key patch: a key the snapshot no longer carries was released at
    the source (its owner unregistered — a drained queue, a closed
    stager), so the newest snapshot's key SET wins too.  The newest
    complete stamp seen is kept in ``stamps`` under
    :data:`SNAPSHOT_STAMP_KEY`: a snapshot older than that floor is
    dropped WHOLESALE (its keys are known-superseded — without the
    floor, a reordered stale beat could re-add a key a newer snapshot
    deleted), a newer one applies its keys then deletes older-stamped
    keys it no longer carries, and an equal-stamped duplicate keeps the
    per-key larger-value tie rule (absence keeps the key), so any
    delivery order converges to one state.  The heartbeat's memory
    field is a complete snapshot; without deletion, the last nonzero
    reading of a retired component would ratchet in the fleet gauge
    forever — exactly the failure last-writer-wins exists to prevent.

    ``totals``, when given, is adjusted by each applied change's SIGNED
    delta: the aggregate is exactly "sum over workers of the
    newest-stamped value", and unlike the max rule it goes down when
    memory is released.  Returns True when anything changed.
    """
    floor = stamps.get(SNAPSHOT_STAMP_KEY)
    if complete:
        if floor is not None and at < floor:
            return False  # superseded snapshot: every key is stale
        stamps[SNAPSHOT_STAMP_KEY] = at
    changed = False
    for key, value in update.items():
        try:
            value = int(value)
        except (TypeError, ValueError):
            continue
        stamp = stamps.get(key)
        if stamp is not None and (
            at < stamp or (at == stamp and value <= merged.get(key, 0))
        ):
            continue
        old = merged.get(key, 0)
        if totals is not None:
            totals[key] = totals.get(key, 0) + (value - old)
        merged[key] = value
        stamps[key] = at
        changed = changed or value != old or stamp is None
    if complete and (floor is None or at > floor):
        for key in [
            k
            for k, stamp in stamps.items()
            if k != SNAPSHOT_STAMP_KEY and stamp < at and k not in update
        ]:
            old = merged.pop(key, 0)
            del stamps[key]
            if totals is not None and old:
                remaining = totals.get(key, 0) - old
                if remaining:
                    totals[key] = remaining
                else:
                    totals.pop(key, None)
            changed = True
    return changed


def max_merge_phase_stats(
    merged: dict[str, dict],
    update: dict,
    totals: dict[str, dict] | None = None,
) -> None:
    """Max-merge step-anatomy phase totals in place.

    Shape: ``{phase: {"ms": float, "count": int, "buckets": {str(bound):
    int}}}`` — ms, count and every log bucket are each monotone per
    worker, so each merges independently by max.  A malformed phase
    entry is skipped whole; a malformed bucket value skips the rest of
    that phase's entry (same tolerance the servicer always had).
    ``totals``, when given, accumulates every slot's rise delta — the
    fleet-wide aggregate mirrored onto the elasticdl_step_phase_*
    families without a per-worker walk at scrape time.
    """
    for phase, stats in update.items():
        if not isinstance(stats, dict):
            continue
        slot = merged.setdefault(
            phase, {"ms": 0.0, "count": 0, "buckets": {}}
        )
        agg = (
            None
            if totals is None
            else totals.setdefault(
                phase, {"ms": 0.0, "count": 0, "buckets": {}}
            )
        )
        try:
            ms = float(stats.get("ms", 0.0))
            if ms > slot["ms"]:
                if agg is not None:
                    agg["ms"] += ms - slot["ms"]
                slot["ms"] = ms
            count = int(stats.get("count", 0))
            if count > slot["count"]:
                if agg is not None:
                    agg["count"] += count - slot["count"]
                slot["count"] = count
            for bound, n in (stats.get("buckets") or {}).items():
                n = int(n)
                old = slot["buckets"].get(bound, 0)
                if n > old:
                    if agg is not None:
                        agg["buckets"][bound] = (
                            agg["buckets"].get(bound, 0) + (n - old)
                        )
                    slot["buckets"][bound] = n
        except (TypeError, ValueError):
            continue
