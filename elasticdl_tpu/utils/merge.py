"""Monotone max-merge — THE merge rule for worker-shipped totals.

Workers ship process-lifetime monotone counters on the heartbeat (PR-8
RPC outcome totals, PR-9 step-anatomy phase totals).  Beats can be
reordered, duplicated, batched by the servicer's coalesced fan-in, or
replayed after a master restart, so the server-side merge must be
``max``, never ``sum`` or overwrite: a stale beat can then never walk
an exposed total backward, and a duplicate is absorbed.  That rule used
to live as two hand-rolled loops inside ``MasterServicer.heartbeat`` —
one flat, one nested — which is one more copy than a correctness rule
should have.  This module is the single definition site; the unit test
pins the monotonicity and malformed-input tolerance both call sites
rely on.

Both functions optionally maintain a fleet-wide AGGREGATE alongside the
per-worker maxima: pass ``totals`` and every rise of a per-worker
counter adds its delta there.  That is what lets the servicer answer
"sum of per-worker maxima across the fleet" in O(keys) at scrape time
instead of an O(world_size) walk under its lock — the 1000-worker
scrape path.  The aggregate is exactly ``sum over workers of max over
beats``; the order or batching of beats cannot change it (pinned by
tests/test_fleetsim.py).
"""

from __future__ import annotations


def max_merge_counters(
    merged: dict[str, int],
    update: dict,
    watch: frozenset[str] | set[str] = frozenset(),
    totals: dict[str, int] | None = None,
) -> bool:
    """Max-merge ``update`` into ``merged`` in place.

    Non-int values are skipped (wire payloads are untrusted).  Returns
    True when any ``watch`` key ROSE above its merged value — the
    "an outage-class counter moved since the last beat" signal the
    /healthz degraded-network flag keys off.  ``totals``, when given,
    accumulates each rise's delta (the fleet-wide aggregate).
    """
    rose = False
    for key, value in update.items():
        try:
            value = int(value)
        except (TypeError, ValueError):
            continue
        old = merged.get(key, 0)
        if value > old:
            if key in watch:
                rose = True
            if totals is not None:
                totals[key] = totals.get(key, 0) + (value - old)
            merged[key] = value
    return rose


def max_merge_phase_stats(
    merged: dict[str, dict],
    update: dict,
    totals: dict[str, dict] | None = None,
) -> None:
    """Max-merge step-anatomy phase totals in place.

    Shape: ``{phase: {"ms": float, "count": int, "buckets": {str(bound):
    int}}}`` — ms, count and every log bucket are each monotone per
    worker, so each merges independently by max.  A malformed phase
    entry is skipped whole; a malformed bucket value skips the rest of
    that phase's entry (same tolerance the servicer always had).
    ``totals``, when given, accumulates every slot's rise delta — the
    fleet-wide aggregate mirrored onto the elasticdl_step_phase_*
    families without a per-worker walk at scrape time.
    """
    for phase, stats in update.items():
        if not isinstance(stats, dict):
            continue
        slot = merged.setdefault(
            phase, {"ms": 0.0, "count": 0, "buckets": {}}
        )
        agg = (
            None
            if totals is None
            else totals.setdefault(
                phase, {"ms": 0.0, "count": 0, "buckets": {}}
            )
        )
        try:
            ms = float(stats.get("ms", 0.0))
            if ms > slot["ms"]:
                if agg is not None:
                    agg["ms"] += ms - slot["ms"]
                slot["ms"] = ms
            count = int(stats.get("count", 0))
            if count > slot["count"]:
                if agg is not None:
                    agg["count"] += count - slot["count"]
                slot["count"] = count
            for bound, n in (stats.get("buckets") or {}).items():
                n = int(n)
                old = slot["buckets"].get(bound, 0)
                if n > old:
                    if agg is not None:
                        agg["buckets"][bound] = (
                            agg["buckets"].get(bound, 0) + (n - old)
                        )
                    slot["buckets"][bound] = n
        except (TypeError, ValueError):
            continue
