"""Wall-clock timing buckets for the worker hot loop.

Reference: ``elasticdl/python/common/timing_utils.py`` — named wall-clock
buckets (task_process / batch_process / get_model / report_gradient),
reported per task at DEBUG level.  The TPU build keeps the same shape and
adds a ``device_step`` bucket for the jitted step (host-side wall clock
including dispatch; per-op detail belongs to the JAX profiler, see
``elasticdl_tpu.utils.profiler``).
"""

from __future__ import annotations

import contextlib
import logging
import time
from collections import defaultdict


class Timing:
    def __init__(self, enabled: bool = False, logger: logging.Logger | None = None):
        self._enabled = enabled
        self._logger = logger
        self.reset()

    def reset(self):
        self._totals: dict[str, float] = defaultdict(float)
        self._counts: dict[str, int] = defaultdict(int)
        self._starts: dict[str, float] = {}
        self._reported_ms: dict[str, int] = defaultdict(int)

    def start_record_time(self, name: str):
        if self._enabled:
            self._starts[name] = time.monotonic()

    def end_record_time(self, name: str):
        if self._enabled and name in self._starts:
            self._totals[name] += time.monotonic() - self._starts.pop(name)
            self._counts[name] += 1

    @contextlib.contextmanager
    def record(self, name: str):
        self.start_record_time(name)
        try:
            yield
        finally:
            self.end_record_time(name)

    def summary(self) -> dict[str, dict[str, float]]:
        return {
            name: {"total_secs": total, "count": self._counts[name]}
            for name, total in sorted(self._totals.items())
        }

    def exec_counters(self) -> dict[str, int]:
        """Bucket time accrued SINCE THE LAST CALL, as task-report
        counters (``time_<bucket>_ms``) — delta semantics so a batch that
        completes several tasks attributes its time once, not once per
        report, and the master's per-job sum stays exact.  Zero deltas
        are omitted; the cumulative-ms bookkeeping keeps rounding from
        drifting across reports."""
        if not self._enabled:
            return {}
        out = {}
        for name, total in self._totals.items():
            cum_ms = round(total * 1000)
            delta = cum_ms - self._reported_ms[name]
            if delta:
                out[f"time_{name}_ms"] = delta
                self._reported_ms[name] = cum_ms
        return out

    def totals_ms(self) -> dict[str, int]:
        """Cumulative bucket totals as ``time_<bucket>_ms`` keys — the
        ABSOLUTE counterpart of :meth:`exec_counters` deltas, for
        telemetry consumers (event log, registry mirror) that want the
        run total in one read.  Does not advance the delta bookkeeping."""
        if not self._enabled:
            return {}
        return {
            f"time_{name}_ms": round(total * 1000)
            for name, total in self._totals.items()
            if round(total * 1000)
        }

    def report_timing(self, reset: bool = False):
        if self._enabled and self._logger is not None:
            for name, stats in self.summary().items():
                self._logger.debug(
                    "Timing %s: %.6fs over %d calls",
                    name,
                    stats["total_secs"],
                    stats["count"],
                )
        if reset:
            self.reset()
