"""Flag system: shared argparse groups for master / worker / client roles.

Reference: ``elasticdl/python/common/args.py`` (685 LoC) — three argparse
trees built from shared flag groups, strategy validation/coercions, and the
**argv round-trip**: the master reconstructs the exact command line for the
worker processes it launches from its own parsed namespace
(``build_arguments_from_parsed_result``, reference args.py:664-685, used at
master.py:340).

The TPU build keeps the same model-spec / data / train flags so reference
job specs keep working, drops the PS-pod resource flags (no parameter
servers exist — dense sync is psum over ICI), and adds the mesh flags that
describe the SPMD layout (``--mesh_shape``, per-axis parallel degrees,
``--compute_dtype``).
"""

from __future__ import annotations

import argparse

from elasticdl_tpu.utils.constants import (
    MASTER_DEFAULT_PORT,
    DistributionStrategy,
)
from elasticdl_tpu.utils.log_utils import default_logger as logger


def pos_int(arg: str) -> int:
    value = int(arg)
    if value <= 0:
        raise argparse.ArgumentTypeError(f"must be a positive integer: {arg}")
    return value


def non_neg_int(arg: str) -> int:
    value = int(arg)
    if value < 0:
        raise argparse.ArgumentTypeError(f"must be >= 0: {arg}")
    return value


def non_neg_float(arg: str) -> float:
    value = float(arg)
    if value < 0:
        raise argparse.ArgumentTypeError(
            f"must be a non-negative float: {arg}"
        )
    return value


def pos_float(arg: str) -> float:
    value = float(arg)
    if value <= 0:
        raise argparse.ArgumentTypeError(f"must be a positive float: {arg}")
    return value


def parse_bool(arg) -> bool:
    if isinstance(arg, bool):
        return arg
    lowered = str(arg).lower()
    if lowered in ("true", "1", "yes"):
        return True
    if lowered in ("false", "0", "no"):
        return False
    raise argparse.ArgumentTypeError(f"not a boolean: {arg}")


def parse_envs(arg: str | None) -> dict[str, str]:
    """Parse ``--envs k1=v1,k2=v2`` (reference args.py:62-87)."""
    envs: dict[str, str] = {}
    if not arg:
        return envs
    for kv in arg.split(","):
        if not kv:
            continue
        k, _, v = kv.partition("=")
        envs[k.strip()] = v.strip()
    return envs


def parse_params_dict(arg: str | None) -> dict:
    """Parse the ``k=v;k=v`` mini-DSL used by ``--model_params`` /
    ``--data_reader_params`` (reference common/model_utils.py:34-50).

    Values are parsed with ``ast.literal_eval`` when possible, else kept as
    strings (the reference falls back to ``eval``; we deliberately do not).
    """
    import ast

    params: dict = {}
    if not arg:
        return params
    for kv in arg.split(";"):
        if not kv.strip():
            continue
        k, sep, v = kv.partition("=")
        if not sep:
            raise ValueError(f"malformed params entry (need k=v): {kv!r}")
        k, v = k.strip(), v.strip()
        try:
            params[k] = ast.literal_eval(v)
        except (ValueError, SyntaxError):
            params[k] = v
    return params


def _add_job_params(parser: argparse.ArgumentParser):
    parser.add_argument("--job_name", default="elasticdl-job", help="Job name")
    parser.add_argument(
        "--log_level",
        default="INFO",
        choices=["DEBUG", "INFO", "WARNING", "ERROR"],
        help="Logging level",
    )
    parser.add_argument(
        "--envs",
        type=str,
        default="",
        help="Extra environment variables, comma separated k=v pairs",
    )


def _add_model_spec_params(parser: argparse.ArgumentParser):
    # reference args.py:448-486 — the model-zoo spec contract
    parser.add_argument(
        "--model_zoo",
        required=False,
        default="",
        help=(
            "Directory that contains user-defined model modules; empty "
            "means the built-in elasticdl_tpu.models zoo"
        ),
    )
    parser.add_argument(
        "--model_def",
        required=True,
        help=(
            "Model definition in module path form, e.g. "
            "mnist_functional_api.mnist_functional_api.custom_model"
        ),
    )
    parser.add_argument(
        "--model_params",
        default="",
        help="Keyword args for custom_model(), 'k=v;k=v' form",
    )
    parser.add_argument("--dataset_fn", default="dataset_fn")
    parser.add_argument("--loss", default="loss")
    parser.add_argument("--optimizer", default="optimizer")
    parser.add_argument("--eval_metrics_fn", default="eval_metrics_fn")
    parser.add_argument(
        "--custom_data_reader", default="custom_data_reader"
    )
    parser.add_argument(
        "--prediction_outputs_processor",
        default="PredictionOutputsProcessor",
        help="Class in the model module that processes prediction outputs",
    )


def _add_data_params(parser: argparse.ArgumentParser):
    parser.add_argument("--training_data", default="")
    parser.add_argument("--validation_data", default="")
    parser.add_argument("--prediction_data", default="")
    parser.add_argument(
        "--records_per_task",
        type=pos_int,
        default=4096,
        help="Records per dynamic-sharding task (the elasticity unit)",
    )
    parser.add_argument("--minibatch_size", type=pos_int, default=64)
    parser.add_argument(
        "--steps_per_dispatch",
        type=lambda v: v if v == "auto" else pos_int(v),
        default=1,
        help=(
            "Optimizer steps fused into one device dispatch (stacked "
            "batches + lax.scan, semantically identical to sequential "
            "steps). >1 amortizes per-dispatch overhead — decisive on "
            "high-latency host-device links. 'auto' derives it at "
            "startup from the measured per-dispatch overhead and the "
            "batch's transfer size (trainer/stacking.py sizing rule)"
        ),
    )
    parser.add_argument("--num_epochs", type=pos_int, default=1)
    parser.add_argument(
        "--data_reader_params",
        default="",
        help="Keyword args for the data reader, 'k=v;k=v' form",
    )
    parser.add_argument(
        "--shuffle_seed",
        type=int,
        default=None,
        required=False,
        help=(
            "Seed for training-task shuffling; unset = nondeterministic "
            "order (set it for reproducible runs and A/B comparisons)"
        ),
    )
    parser.add_argument(
        "--num_minibatches_per_task",
        type=pos_int,
        default=None,
        required=False,
        help=(
            "If set, records_per_task = minibatch_size * this "
            "(convenience; reference derives similarly)"
        ),
    )
    parser.add_argument(
        "--serving_addr",
        default=None,
        required=False,
        help=(
            "predict only: target a RUNNING serving endpoint "
            "(elasticdl_tpu.serving.main router or replica, host:port) "
            "instead of loading the model in-process — prediction "
            "shards are decoded locally, batches predict remotely.  "
            "Unset keeps the offline batch path (and, per the "
            "flag-hygiene contract, is dropped from any reconstructed "
            "argv)"
        ),
    )


def _add_train_params(parser: argparse.ArgumentParser):
    parser.add_argument("--evaluation_steps", type=non_neg_int, default=0)
    parser.add_argument(
        "--evaluation_start_delay_secs", type=non_neg_int, default=100
    )
    parser.add_argument(
        "--evaluation_throttle_secs", type=non_neg_int, default=0
    )
    parser.add_argument("--checkpoint_steps", type=non_neg_int, default=0)
    parser.add_argument("--checkpoint_dir", default="")
    parser.add_argument(
        "--checkpoint_dir_for_init",
        default="",
        help="Restore initial model state from this checkpoint directory",
    )
    parser.add_argument("--keep_checkpoint_max", type=non_neg_int, default=3)
    # defaults are None (not False/0) so an unset flag is absent from the
    # reconstructed argv: with replication off, worker command lines and
    # the k8s golden manifests stay byte-identical to a build without it
    parser.add_argument(
        "--replication",
        type=parse_bool,
        default=None,
        required=False,
        help=(
            "Replicate trainer state into peer host RAM (ring push at "
            "task boundaries) so a re-formed world hot-restores from "
            "peers instead of disk; lockstep jobs (num_workers > 1) "
            "only.  Disk checkpoints remain the durable fallback"
        ),
    )
    parser.add_argument(
        "--replication_steps",
        type=non_neg_int,
        default=None,
        required=False,
        help=(
            "Replicate every N steps (milestone-crossing, like "
            "--checkpoint_steps); 0 or unset = every task boundary"
        ),
    )
    parser.add_argument(
        "--output", default="", help="Directory for the exported model"
    )
    parser.add_argument("--tensorboard_log_dir", default="")
    parser.add_argument(
        "--telemetry_dir",
        default="",
        help=(
            "Write the structured elastic event log (events.jsonl) here; "
            "workers inherit it via the environment.  Summarize with "
            "python -m elasticdl_tpu.telemetry.report"
        ),
    )
    parser.add_argument(
        "--metrics_port",
        type=int,
        default=0,
        help=(
            "Port for the master's /metrics (Prometheus) + /healthz "
            "endpoint; 0 picks a free port, negative disables the server"
        ),
    )
    parser.add_argument(
        "--metrics_host",
        default="127.0.0.1",
        help=(
            "Bind address for /metrics + /healthz.  Loopback by default "
            "(the endpoint is unauthenticated); set 0.0.0.0 to let a "
            "scraper reach it from off the machine"
        ),
    )
    parser.add_argument(
        "--trace_sample_rate",
        type=float,
        default=None,
        required=False,
        help=(
            "Keep this fraction of hot-path spans (train_step, "
            "heartbeat) in the distributed trace; lifecycle/reform "
            "spans are always recorded.  Default 0.05 (1-in-20) keeps "
            "steady-state overhead under the telemetry budget; 1.0 "
            "traces every step.  Requires --telemetry_dir"
        ),
    )
    parser.add_argument(
        "--step_anatomy",
        type=parse_bool,
        default=None,
        required=False,
        help=(
            "Continuous per-dispatch time anatomy: decompose every "
            "dispatch group's wall time into host_fetch / assemble / "
            "h2d_transfer / device_compute / step_bookkeeping phases "
            "(sum-exact; residual tracked as 'untracked').  Feeds the "
            "elasticdl_step_phase_* metric families, the report's "
            "goodput section and sampled step_anatomy spans.  Workers "
            "inherit it via ELASTICDL_TPU_STEP_ANATOMY (never argv).  "
            "Measuring blocks each dispatch on its outputs, trading a "
            "little async-dispatch overlap for exact attribution; "
            "default off"
        ),
    )
    parser.add_argument(
        "--device_prefetch",
        type=parse_bool,
        default=None,
        required=False,
        help=(
            "Device-path pipelining: stage the NEXT canonical batch "
            "onto the device on a background thread while the current "
            "dispatch group computes, donate batch/mask buffers to the "
            "jitted step (steady-state dispatches allocate no fresh "
            "device buffers), and retire dispatch outputs one group "
            "behind in a bounded in-flight window (--pipeline_depth, "
            "default 2) — with the drain kept at task boundaries "
            "(fusable via --boundary_fusion) and under "
            "--step_anatomy.  "
            "Workers inherit it via ELASTICDL_TPU_DEVICE_PREFETCH "
            "(never argv); default off"
        ),
    )
    parser.add_argument(
        "--boundary_fusion",
        type=parse_bool,
        default=None,
        required=False,
        help=(
            "Cross-task staging (requires --device_prefetch): keep the "
            "device pipeline alive across TASK boundaries — the stager "
            "pre-stages the next task's dispatch groups while the "
            "current task's last groups compute, and the boundary "
            "barrier shrinks to retiring the previous task's in-flight "
            "window (exactly-once preserved: a task reports only after "
            "its own groups retired).  Workers inherit it via "
            "ELASTICDL_TPU_BOUNDARY_FUSION (never argv); default off"
        ),
    )
    parser.add_argument(
        "--pipeline_depth",
        type=pos_int,
        default=None,
        required=False,
        help=(
            "Device-pipeline depth (requires --device_prefetch): the "
            "retire-behind window and staging-queue bound, in dispatch "
            "groups.  The memory ledger's device_stager component "
            "bounds how deep staging actually runs (admission against "
            "live device headroom / ELASTICDL_TPU_STAGING_BUDGET_BYTES "
            "with a loud degrade to 1 on pressure).  Workers inherit "
            "it via ELASTICDL_TPU_PIPELINE_DEPTH (never argv); "
            "default 2 — today's proven window"
        ),
    )
    parser.add_argument(
        "--profile_dir",
        default="",
        help=(
            "Capture an XLA profiler trace of a few training steps into "
            "this directory (TensorBoard 'profile' plugin format)"
        ),
    )
    parser.add_argument(
        "--profile_steps",
        type=pos_int,
        default=5,
        help="How many steps the profiler window covers",
    )
    parser.add_argument(
        "--get_model_steps",
        type=pos_int,
        default=1,
        help=(
            "Accepted for compatibility with the reference's local-SGD "
            "mode (pull model from PS every N minibatches, reference "
            "worker.py:179-182); the TPU build syncs every step — see "
            "the deviation warning when set >1"
        ),
    )
    parser.add_argument(
        "--use_async",
        type=parse_bool,
        default=False,
        help=(
            "Accepted for compatibility with the reference's async-SGD PS "
            "mode; the TPU build trains synchronously (ICI makes sync "
            "cheap) and logs a deviation warning when set"
        ),
    )
    parser.add_argument(
        "--grads_to_wait",
        type=pos_int,
        default=1,
        help="Compatibility flag from the sync-PS mode; unused on TPU",
    )
    parser.add_argument("--learning_rate", type=pos_float, default=None,
                        required=False,
                        help="Override the model module's learning rate")


def _add_mesh_params(parser: argparse.ArgumentParser):
    parser.add_argument(
        "--distribution_strategy",
        default=DistributionStrategy.LOCAL,
        choices=list(DistributionStrategy.ALL),
    )
    parser.add_argument(
        "--num_workers",
        # 0 = control plane only (workers launched externally, e.g. by the
        # TPU pod runtime)
        type=non_neg_int,
        default=1,
        help="Number of worker processes (TPU hosts)",
    )
    parser.add_argument(
        "--mesh_shape",
        default="",
        help=(
            "Logical device mesh, e.g. 'dp=8' or 'dp=4,tp=2' or "
            "'dp=2,sp=4'; empty = all devices on dp"
        ),
    )
    parser.add_argument(
        "--dcn_mesh_shape",
        default="",
        help=(
            "Which part of which axis spans TPU slices on a multi-slice "
            "job (collectives there ride DCN), e.g. 'dp=2'; empty = "
            "auto (all slices on dp)"
        ),
    )
    parser.add_argument(
        "--compute_dtype",
        default="bfloat16",
        choices=["bfloat16", "float32"],
        help="Activation/matmul dtype (params stay float32)",
    )
    parser.add_argument(
        "--remat",
        type=parse_bool,
        default=False,
        help="Rematerialize activations (jax.checkpoint) to save HBM",
    )
    parser.add_argument(
        "--donate_state",
        type=parse_bool,
        default=True,
        help="Donate train-state buffers to the jitted step",
    )
    parser.add_argument(
        "--jax_platform",
        default="",
        help=(
            "Pin the JAX platform (e.g. 'cpu' for tests/virtual meshes, "
            "'tpu'); empty = JAX default.  Forwarded to workers."
        ),
    )
    parser.add_argument(
        "--compilation_cache_dir",
        default="",
        help=(
            "Persistent XLA compilation cache directory (forwarded to "
            "workers): repeated jobs and re-formed worlds reuse compiled "
            "executables instead of recompiling; empty disables"
        ),
    )


def _add_master_params(parser: argparse.ArgumentParser):
    parser.add_argument(
        # 0 = ephemeral (the OS picks; used by tests and local runs)
        "--port", type=non_neg_int, default=MASTER_DEFAULT_PORT
    )
    parser.add_argument(
        "--instance_backend",
        default="local",
        choices=["local", "k8s", "none"],
        help=(
            "How workers are launched/monitored: local subprocesses, "
            "Kubernetes pods, or externally managed ('none')"
        ),
    )
    parser.add_argument("--namespace", default="default")
    parser.add_argument(
        "--docker_image",
        default="",
        help="Prebuilt job image; empty = build one (--docker_image_repository)",
    )
    parser.add_argument(
        "--docker_image_repository",
        default="",
        help="Registry/repository the built job image is pushed to",
    )
    parser.add_argument(
        "--docker_base_image",
        default="",
        help="Base image for the synthesized job Dockerfile",
    )
    parser.add_argument(
        "--worker_resource_request", default="cpu=1,memory=4096Mi"
    )
    parser.add_argument("--worker_resource_limit", default="")
    parser.add_argument("--worker_pod_priority", default="")
    parser.add_argument(
        "--master_resource_request", default="cpu=1,memory=4096Mi"
    )
    parser.add_argument("--master_resource_limit", default="")
    parser.add_argument("--master_pod_priority", default="")
    parser.add_argument(
        "--volume",
        default="",
        help=(
            "Pod volumes, e.g. 'host_path=/data,mount_path=/data;"
            "claim_name=c1,mount_path=/ckpt'"
        ),
    )
    parser.add_argument(
        "--image_pull_policy",
        default="Always",
        choices=["Always", "IfNotPresent", "Never"],
    )
    parser.add_argument(
        "--relaunch_on_worker_failure",
        type=non_neg_int,
        default=3,
        help="Max relaunches per failed worker",
    )
    parser.add_argument(
        "--heartbeat_timeout_secs",
        # 0 disables heartbeat-timeout failure detection
        type=non_neg_float,
        default=30.0,
        help="Declare a worker dead after this long without a heartbeat",
    )
    parser.add_argument(
        "--task_timeout_secs",
        # 0 disables lease-timeout reclaim
        type=non_neg_float,
        default=0.0,
        help="Re-queue a task held longer than this (0 = never)",
    )
    parser.add_argument(
        "--cluster_spec",
        default="",
        help=(
            "Python module exporting `cluster` with with_pod/with_service "
            "hooks applied to every pod/service manifest (cluster-specific "
            "tolerations, labels); copied into the job image on submit"
        ),
    )
    parser.add_argument(
        "--yaml",
        default="",
        help=(
            "Dump the master pod+service manifests to this file instead "
            "of submitting the job (k8s backend only)"
        ),
    )
    # master high availability.  Defaults are None (not "") so an unset
    # flag is absent from any reconstructed argv: with HA off, worker
    # command lines and the k8s golden manifests stay byte-identical to
    # a journal-less build (same rule as the replication flags)
    parser.add_argument(
        "--master_journal_dir",
        default=None,
        required=False,
        help=(
            "Write-ahead journal of the master's control-plane state "
            "(dispatcher transitions, generation fences, lockstep "
            "stream).  A master relaunched with the same directory "
            "replays it, workers re-home onto the restarted master, and "
            "the job survives master death.  Unset disables HA"
        ),
    )
    parser.add_argument(
        "--rpc_retry_secs",
        type=non_neg_float,
        default=None,
        required=False,
        help=(
            "Worker RPC retry budget (full-jitter backoff) carried "
            "across a master outage; forwarded to workers by env.  "
            "Default 60 when --master_journal_dir is set, else retries "
            "are off"
        ),
    )
    parser.add_argument(
        "--rpc_deadline_secs",
        type=pos_float,
        default=None,
        required=False,
        help=(
            "Per-call deadline for worker control RPCs (state-transfer "
            "methods like get_restore_state and the replication "
            "push/fetch get a proportionally longer tier; see "
            "rpc/deadline.py).  Makes a blackholed master link degrade "
            "to DEADLINE_EXCEEDED — which feeds the retry loop — "
            "instead of hanging the worker forever.  Forwarded to "
            "workers by env; unset = no deadlines (historical behavior)"
        ),
    )
    parser.add_argument(
        "--rehome_grace_secs",
        type=non_neg_float,
        default=None,
        required=False,
        help=(
            "How long a journal-restored master waits for the previous "
            "world's workers to re-home before declaring the silent "
            "ones dead; default max(10, 3x heartbeat timeout)"
        ),
    )
    # slice-granular elasticity.  Defaults are None (not 1/0) so unset
    # flags are absent from any reconstructed argv: with multislice and
    # autoscaling off, worker command lines and the k8s golden manifests
    # stay byte-identical to a slice-blind build (same rule as the
    # replication and HA flags)
    parser.add_argument(
        "--num_slices",
        type=pos_int,
        default=None,
        required=False,
        help=(
            "Split the worker fleet into this many TPU slices for the "
            "hybrid ICI/DCN mesh (the dp axis spans slices over DCN).  "
            "On backends without a device slice_index (CPU dryruns) the "
            "layout is forced via the canonical process->slice map.  "
            "Reform is then slice-granular: a whole-slice loss shrinks "
            "the world to the surviving slices, a capacity grant grows "
            "it back.  Unset = single slice (classic reform)"
        ),
    )
    parser.add_argument(
        "--min_slices",
        type=pos_int,
        default=None,
        required=False,
        help=(
            "Graceful degradation floor: a slice loss that would shrink "
            "the world below this parks the job quiesced (tasks "
            "re-queued, no world running) instead of crashing; the next "
            "capacity grant or autoscale grow resumes it.  Unset = 1"
        ),
    )
    parser.add_argument(
        "--autoscale_p95_step_ms",
        type=pos_float,
        default=None,
        required=False,
        help=(
            "Autoscaler SLO: grow the world by one slice when the p95 "
            "step time (master-observed from version reports) exceeds "
            "this many milliseconds.  Unset disables the step-time "
            "trigger"
        ),
    )
    parser.add_argument(
        "--autoscale_backlog_tasks",
        type=pos_int,
        default=None,
        required=False,
        help=(
            "Autoscaler SLO: grow the world by one slice when the "
            "pending (unleased) task backlog reaches this count.  "
            "Unset disables the backlog trigger"
        ),
    )
    parser.add_argument(
        "--autoscale_cooldown_secs",
        type=non_neg_float,
        default=None,
        required=False,
        help=(
            "Minimum seconds between autoscale decisions (and after any "
            "re-formation) before the next decision may fire; default 30"
        ),
    )
    parser.add_argument(
        "--autoscale_shrink",
        type=parse_bool,
        default=None,
        required=False,
        help=(
            "Let the autoscaler also SHRINK by one slice when the "
            "MEASURED p95 step time sits under a quarter of "
            "--autoscale_p95_step_ms with no backlog pending (down to "
            "--min_slices).  Requires the p95 SLO: an empty backlog "
            "alone is not over-provisioning evidence (it reads zero "
            "while every worker is busy mid-lease).  Off unless set"
        ),
    )
    parser.add_argument(
        "--slo_config",
        default=None,
        required=False,
        help=(
            "Arm the SLO watchdog plane: 'default' for the built-in "
            "objectives, a path to a JSON objective file, or inline "
            "JSON.  The master evaluates multi-window burn-rate "
            "detectors over its telemetry each poll tick, emits "
            "slo_violation events + elasticdl_slo_* metrics, flips the "
            "/healthz slo block, auto-arms an on-demand profiler "
            "window, and writes incidents/incident_<n>.json "
            "postmortems under --telemetry_dir.  Unset (the default) "
            "constructs nothing: worker argv and behavior are "
            "byte-identical to a watchdog-less build"
        ),
    )
    # streaming subsystem (continuous training).  Defaults None for the
    # same byte-identical-argv rule: with streaming off, nothing about
    # these flags reaches a worker or a golden manifest
    parser.add_argument(
        "--streaming",
        type=parse_bool,
        default=None,
        required=False,
        help=(
            "Watermark-lease mode: --training_data names a stream:// "
            "origin, the dispatcher mints [offset, offset+n) window "
            "tasks up to the source watermark instead of slicing "
            "epochs, finished() holds off until the source closes and "
            "the backlog drains, and lag = source_watermark - "
            "trained_watermark becomes the autoscaler's backlog "
            "signal.  Unset = epoch mode (workers see the same argv "
            "either way — the stream:// origin rides --training_data)"
        ),
    )
    parser.add_argument(
        "--stream_lag_tasks",
        type=pos_int,
        default=None,
        required=False,
        help=(
            "Streaming autoscaler trigger: grow the world by one slice "
            "when the stream lag reaches this many windows "
            "(lag_records / records_per_task).  Unset falls back to "
            "--autoscale_backlog_tasks over the same window-denominated "
            "backlog"
        ),
    )
    parser.add_argument(
        "--live_push_addr",
        default=None,
        required=False,
        help=(
            "Close the train->serve loop: after each replica-ring "
            "commit at a new model version, harvest the freshest "
            "complete replica set and push its flat state dict into "
            "the serving replica at this address (swap_model with an "
            "inline payload -> engine.swap_state_dicts; zero failed "
            "in-flight requests).  Each push lands a live_push event "
            "stamping trained-vs-source watermark — the freshness "
            "ledger.  Unset constructs nothing"
        ),
    )
    parser.add_argument(
        "--standby_workers",
        type=int,
        default=-1,
        help=(
            "Hot-standby workers kept warm (imports done, waiting on a "
            "world assignment) so re-formation skips the cold start; "
            "-1 = num_workers, 0 disables. Lockstep jobs only; local "
            "standbys wait on stdin, k8s standby pods poll the master's "
            "assignment mailbox"
        ),
    )


def _add_worker_params(parser: argparse.ArgumentParser):
    parser.add_argument("--worker_id", type=non_neg_int, required=True)
    parser.add_argument("--master_addr", required=True)
    parser.add_argument(
        "--coordinator_addr",
        default="",
        help=(
            "jax.distributed coordinator address; non-empty selects the "
            "multi-process lockstep runtime (one model over all workers)"
        ),
    )
    parser.add_argument(
        "--num_processes",
        type=pos_int,
        default=1,
        help="Processes in the distributed world this worker joins",
    )
    parser.add_argument(
        "--process_id",
        type=non_neg_int,
        default=0,
        help="This worker's process index in the distributed world",
    )
    parser.add_argument(
        "--cluster_version",
        type=non_neg_int,
        default=0,
        help=(
            "World generation assigned by the master; fences stale "
            "workers after a mesh re-formation"
        ),
    )
    # slice coordinates of a multi-slice lockstep world; assigned by the
    # instance manager per process / per generation (like process_id),
    # and ONLY when the world spans >1 slice — single-slice worker argv
    # stays byte-identical to a slice-blind build
    parser.add_argument(
        "--slice_id",
        type=non_neg_int,
        default=0,
        help="This worker's TPU slice index in the multi-slice world",
    )
    parser.add_argument(
        "--num_slices",
        type=pos_int,
        default=1,
        help=(
            "Slices in the distributed world this worker joins; >1 "
            "builds the hybrid ICI/DCN mesh (forced via the canonical "
            "process->slice map on backends without a device "
            "slice_index)"
        ),
    )
    parser.add_argument(
        "--standby",
        type=non_neg_int,
        default=0,
        help=(
            "1 = hot-standby mode: warm every import, then block until "
            "the master writes a world assignment (JSON line) on stdin; "
            "re-formation then skips the cold start"
        ),
    )


_MASTER_GROUPS = (
    _add_job_params,
    _add_model_spec_params,
    _add_data_params,
    _add_train_params,
    _add_mesh_params,
    _add_master_params,
)

_WORKER_GROUPS = (
    _add_job_params,
    _add_model_spec_params,
    _add_data_params,
    _add_train_params,
    _add_mesh_params,
    _add_worker_params,
)


def _finalize(args: argparse.Namespace) -> argparse.Namespace:
    """Validation + coercions (reference args.py:595-604)."""
    if getattr(args, "num_minibatches_per_task", None):
        args.records_per_task = (
            args.minibatch_size * args.num_minibatches_per_task
        )
    if getattr(args, "use_async", False):
        # reference coerces async => grads_to_wait=1; we additionally pin the
        # TPU build to synchronous updates (documented deviation, SURVEY §7).
        args.grads_to_wait = 1
        logger.warning(
            "--use_async is accepted for compatibility but the TPU build "
            "trains synchronously (gradient psum over ICI); async staleness "
            "semantics do not apply"
        )
    if getattr(args, "get_model_steps", 1) > 1:
        # Documented deviation: the reference's local-SGD exists to
        # amortize PS pull/push round-trips over slow pod networks
        # (worker.py:179-182,274-282); here gradient sync is the psum
        # GSPMD derives from shardings, riding ICI — per-step sync is
        # already cheaper than the divergent-replica bookkeeping
        # local-SGD would need (params stacked over dp inside the step).
        logger.warning(
            "--get_model_steps=%d is accepted for compatibility but the "
            "TPU build synchronizes gradients every step over ICI; "
            "local-SGD does not apply (coerced to 1)",
            args.get_model_steps,
        )
        args.get_model_steps = 1
    if args.model_params:
        args.model_params_dict = parse_params_dict(args.model_params)
    else:
        args.model_params_dict = {}
    if args.data_reader_params:
        args.data_reader_params_dict = parse_params_dict(
            args.data_reader_params
        )
    else:
        args.data_reader_params_dict = {}
    args.envs_dict = parse_envs(args.envs)
    return args


def _parse_known(parser: argparse.ArgumentParser, argv):
    args, unknown = parser.parse_known_args(argv)
    if unknown:
        # reference args.py:569-572 — surface, don't swallow, typos
        logger.warning("Unknown arguments: %s", unknown)
    return _finalize(args)


def parse_master_args(argv=None) -> argparse.Namespace:
    parser = argparse.ArgumentParser(description="ElasticDL-TPU master")
    for group in _MASTER_GROUPS:
        group(parser)
    return _parse_known(parser, argv)


def parse_worker_args(argv=None) -> argparse.Namespace:
    parser = argparse.ArgumentParser(description="ElasticDL-TPU worker")
    for group in _WORKER_GROUPS:
        group(parser)
    return _parse_known(parser, argv)


# Flags that exist only on the master and must not be forwarded to workers.
_MASTER_ONLY_FLAGS = frozenset(
    {
        "port",
        "instance_backend",
        "namespace",
        "docker_image",
        "docker_image_repository",
        "docker_base_image",
        "worker_resource_request",
        "worker_resource_limit",
        "worker_pod_priority",
        "master_resource_request",
        "master_resource_limit",
        "master_pod_priority",
        "volume",
        "image_pull_policy",
        "relaunch_on_worker_failure",
        "heartbeat_timeout_secs",
        "task_timeout_secs",
        "standby_workers",
        # slice-granular elasticity is the master's business: workers
        # receive their slice coordinates (--slice_id/--num_slices) from
        # the instance manager per generation, never from this flag, and
        # the autoscaler runs only in the master's run loop
        "num_slices",
        "min_slices",
        "autoscale_p95_step_ms",
        "autoscale_backlog_tasks",
        "autoscale_cooldown_secs",
        "autoscale_shrink",
        "yaml",
        "cluster_spec",
        # master HA is the master's business: workers receive the addr
        # file, retry budget and RPC deadline policy via env
        # (master/main.py), never argv
        "master_journal_dir",
        "rpc_retry_secs",
        "rpc_deadline_secs",
        "rehome_grace_secs",
        # workers receive the telemetry dir via ELASTICDL_TPU_TELEMETRY_DIR
        # and the span sample rate via ELASTICDL_TPU_TRACE_SAMPLE_RATE
        # (master/main.py); they never serve /metrics themselves
        "telemetry_dir",
        "metrics_port",
        "metrics_host",
        "trace_sample_rate",
        # step anatomy travels by ELASTICDL_TPU_STEP_ANATOMY (never
        # argv) so worker command lines stay byte-identical when off
        "step_anatomy",
        # device-path pipelining travels by
        # ELASTICDL_TPU_DEVICE_PREFETCH, same contract; cross-task
        # staging and the pipeline window ride the same contract via
        # ELASTICDL_TPU_BOUNDARY_FUSION / ELASTICDL_TPU_PIPELINE_DEPTH
        "device_prefetch",
        "boundary_fusion",
        "pipeline_depth",
        # the SLO watchdog runs only in the master's run loop; the
        # config travels by ELASTICDL_TPU_SLO_CONFIG (never argv) so
        # worker command lines stay byte-identical when off
        "slo_config",
        # the streaming subsystem is master business end to end: the
        # dispatcher mints windows, the run loop pushes live swaps —
        # workers only ever see the stream:// origin via --training_data
        "streaming",
        "stream_lag_tasks",
        "live_push_addr",
    }
)

# Derived (non-flag) namespace entries produced by _finalize.
_DERIVED_KEYS = frozenset(
    {"model_params_dict", "data_reader_params_dict", "envs_dict"}
)


def derive_job_type(args):
    """JobType from which data args are set (reference master.py:233-262).
    Shared by master and worker so they can never disagree."""
    from elasticdl_tpu.utils.constants import JobType

    training = bool(getattr(args, "training_data", ""))
    evaluation = bool(getattr(args, "validation_data", ""))
    prediction = bool(getattr(args, "prediction_data", ""))
    if prediction and not training:
        return JobType.PREDICTION_ONLY
    if evaluation and not training:
        return JobType.EVALUATION_ONLY
    if training and evaluation:
        return JobType.TRAINING_WITH_EVALUATION
    return JobType.TRAINING_ONLY


def build_arguments_from_parsed_result(
    args: argparse.Namespace,
    filter_args: frozenset[str] | set[str] = frozenset(),
) -> list[str]:
    """Reconstruct an argv list from a parsed namespace.

    The master uses this to synthesize each worker's command line from its
    own flags (reference args.py:664-685 + master.py:331-384).  Booleans are
    rendered as ``true``/``false`` (parse_bool round-trips them); ``None``
    values are dropped.
    """
    argv: list[str] = []
    skip = set(filter_args) | _DERIVED_KEYS
    for key, value in sorted(vars(args).items()):
        if key in skip or value is None:
            continue
        if isinstance(value, bool):
            value = "true" if value else "false"
        argv.extend([f"--{key}", str(value)])
    return argv


def build_worker_arguments(
    master_args: argparse.Namespace, worker_id: int, master_addr: str
) -> list[str]:
    """The master→worker argv round-trip."""
    argv = build_arguments_from_parsed_result(
        master_args, filter_args=_MASTER_ONLY_FLAGS
    )
    argv.extend(["--worker_id", str(worker_id), "--master_addr", master_addr])
    return argv
