"""Deterministic hashing for shard assignment.

Reference: ``elasticdl/python/common/hash_utils.py`` — sha256-based
string→shard mapping for dense variables and id-mod mapping for embedding
rows.  The TPU build uses the same functions to assign embedding-table rows
to mesh shards (the in-step all-to-all routes ids by ``int_to_id``) and to
re-shard checkpoints across different mesh sizes.
"""

from __future__ import annotations

import hashlib

import numpy as np


def string_to_id(name: str, num_shards: int) -> int:
    """Stable shard index for a named parameter (sha256 mod N)."""
    if num_shards <= 0:
        raise ValueError("num_shards must be positive, got %d" % num_shards)
    digest = hashlib.sha256(name.encode("utf-8")).hexdigest()
    return int(digest, 16) % num_shards


def int_to_id(value: int, num_shards: int) -> int:
    """Shard index for an embedding row id (id mod N)."""
    return int(value) % num_shards


def scatter_ids(ids: np.ndarray, num_shards: int) -> list[np.ndarray]:
    """Group a 1-D id array by owning shard; returns per-shard id arrays.

    Vectorized counterpart of the reference's per-id Python loop
    (``hash_utils.py:13`` scatter_embedding_vector).
    """
    ids = np.asarray(ids)
    shard = ids % num_shards
    return [ids[shard == i] for i in range(num_shards)]


def scatter_with_positions(
    ids: np.ndarray, num_shards: int
) -> tuple[list[np.ndarray], list[np.ndarray]]:
    """Group ids by shard, also returning original positions for re-gather."""
    ids = np.asarray(ids)
    shard = ids % num_shards
    grouped, positions = [], []
    for i in range(num_shards):
        mask = shard == i
        grouped.append(ids[mask])
        positions.append(np.nonzero(mask)[0])
    return grouped, positions
