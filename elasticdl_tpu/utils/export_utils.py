"""Model export / import for serving.

Reference: the SAVE_MODEL flow (``model_handler.py:155-197``) rebuilds a
pure-Keras model, injects checkpoint weights, and writes a TF SavedModel.
The TPU-native equivalent is a self-describing export directory:

    {output}/
      manifest.json   (model_def, model_params, framework version)
      params.npz      (name-keyed parameters)
      model_state.npz (batch_stats etc., if any)

``load_exported_model`` rebuilds the flax module from the manifest and
returns ``(model, params, model_state)`` ready for ``model.apply`` — no
training framework state required, which is the same property a SavedModel
gives TF serving.
"""

from __future__ import annotations

import json
import os

import numpy as np

import elasticdl_tpu
from elasticdl_tpu.utils import tree_utils
from elasticdl_tpu.utils.log_utils import default_logger as logger
from elasticdl_tpu.utils.model_utils import get_model_spec

_MANIFEST = "manifest.json"


def export_model(output_dir: str, state, spec, args) -> str:
    os.makedirs(output_dir, exist_ok=True)
    np.savez(
        os.path.join(output_dir, "params.npz"),
        **tree_utils.tree_to_dict(state.params),
    )
    if state.model_state:
        np.savez(
            os.path.join(output_dir, "model_state.npz"),
            **tree_utils.tree_to_dict(state.model_state),
        )
    manifest = {
        "framework": "elasticdl_tpu",
        "version": elasticdl_tpu.__version__,
        "model_zoo": getattr(args, "model_zoo", ""),
        "model_def": args.model_def,
        "model_params": getattr(args, "model_params_dict", {}),
        "model_version": int(state.step),
    }
    with open(os.path.join(output_dir, _MANIFEST), "w") as f:
        json.dump(manifest, f)
    logger.info("Exported model (version %d) to %s", int(state.step), output_dir)
    return output_dir


def read_manifest(output_dir: str) -> dict:
    """The export's manifest dict (cheap: no npz load) — the serving
    plane polls this to learn a directory grew a newer
    ``model_version`` before paying for the parameter bytes."""
    with open(os.path.join(output_dir, _MANIFEST)) as f:
        return json.load(f)


def load_exported_model(output_dir: str):
    manifest = read_manifest(output_dir)
    spec = get_model_spec(
        manifest.get("model_zoo", ""),
        manifest["model_def"],
        model_params=manifest.get("model_params", {}),
    )
    model = spec.build_model()
    with np.load(os.path.join(output_dir, "params.npz")) as z:
        flat_params = {k: z[k] for k in z.files}
    model_state_path = os.path.join(output_dir, "model_state.npz")
    flat_state = {}
    if os.path.exists(model_state_path):
        with np.load(model_state_path) as z:
            flat_state = {k: z[k] for k in z.files}
    return model, flat_params, flat_state


def rebuild_variables(model, sample_features, flat_params, flat_state):
    """Shape the flat dicts into the module's variable pytrees."""
    from elasticdl_tpu.trainer.state import init_model

    params, model_state = init_model(model, sample_features)
    params = tree_utils.dict_to_tree(flat_params, params)
    if flat_state:
        model_state = tree_utils.dict_to_tree(flat_state, model_state)
    return params, model_state
