"""Pytree <-> flat name-keyed dict conversion for checkpointing.

Parameter names are '/'-joined pytree paths (e.g. ``Dense_0/kernel``),
the stable naming checkpoints are keyed by — the analogue of the
reference's Keras variable names in its pb checkpoints.
"""

from __future__ import annotations

import jax
import numpy as np


def _key_str(entry) -> str:
    if isinstance(entry, jax.tree_util.DictKey):
        return str(entry.key)
    if isinstance(entry, jax.tree_util.SequenceKey):
        return str(entry.idx)
    if isinstance(entry, jax.tree_util.GetAttrKey):
        return str(entry.name)
    return str(entry)


def tree_to_dict(tree) -> dict[str, np.ndarray]:
    """Flatten a pytree of arrays into {path: numpy array}."""
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return {
        "/".join(_key_str(k) for k in path): np.asarray(leaf)
        for path, leaf in flat
    }


def dict_to_tree(values: dict[str, np.ndarray], like):
    """Rebuild a pytree structured like ``like`` from a flat dict.

    Missing keys raise; extra keys are ignored (they may belong to other
    subsystems, e.g. embedding tables restored separately).
    """
    flat, treedef = jax.tree_util.tree_flatten_with_path(like)
    leaves = []
    for path, leaf in flat:
        key = "/".join(_key_str(k) for k in path)
        if key not in values:
            raise KeyError(f"checkpoint missing parameter {key!r}")
        arr = values[key]
        if tuple(arr.shape) != tuple(np.shape(leaf)):
            raise ValueError(
                f"shape mismatch for {key!r}: checkpoint {arr.shape} vs "
                f"model {np.shape(leaf)}"
            )
        # read dtype without np.asarray(leaf): a multi-process-sharded
        # model leaf is not fully addressable and would raise
        dtype = getattr(leaf, "dtype", None)
        if dtype is None:
            dtype = np.asarray(leaf).dtype
        leaves.append(arr.astype(dtype))
    return jax.tree_util.tree_unflatten(treedef, leaves)
