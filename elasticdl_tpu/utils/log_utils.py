"""Cached structured stderr loggers.

Reference: ``elasticdl/python/common/log_utils.py`` (cached per-name loggers
with a uniform format written to stderr).
"""

from __future__ import annotations

import logging
import sys
import threading

_FORMAT = (
    "[%(asctime)s] [%(levelname)s] "
    "[%(name)s:%(filename)s:%(lineno)d] %(message)s"
)

_lock = threading.Lock()
_loggers: dict[str, logging.Logger] = {}


def get_logger(name: str = "elasticdl_tpu", level: str | int | None = None):
    """Return a cached logger writing the framework format to stderr.

    ``level`` only takes effect when explicitly passed, so a later
    ``get_logger()`` call cannot clobber a configured ``--log_level``.
    """
    with _lock:
        logger = _loggers.get(name)
        if logger is None:
            logger = logging.getLogger(name)
            logger.propagate = False
            handler = logging.StreamHandler(sys.stderr)
            handler.setFormatter(logging.Formatter(_FORMAT))
            logger.addHandler(handler)
            logger.setLevel("INFO")
            _loggers[name] = logger
        if level is not None:
            logger.setLevel(level)
        return logger


default_logger = get_logger()
