"""Tensor wire format for the control plane.

Reference: ``elasticdl/python/common/tensor.py`` + the ``Tensor`` protobuf
message (``elasticdl/proto/elasticdl.proto:52-70``) — the reference ships
*all* parameters and gradients through this format.  In the TPU build dense
parameters and gradients never leave the device mesh (psum over ICI), so this
format only carries low-rate control traffic: evaluation outputs/labels,
model export payloads, and debugging tensors.  It therefore favors
simplicity: a self-describing binary frame of

    [u32 header_len][header json][raw data bytes][raw indices bytes?]

A ``Tensor`` is dense (``indices is None``) or sparse row-slices
(``indices`` holds row ids — the IndexedSlices analogue used for embedding
gradients, reference tensor.py:25-60).
"""

from __future__ import annotations

import json
import struct
from dataclasses import dataclass

import numpy as np

_HEADER_STRUCT = struct.Struct("<I")

_SUPPORTED_DTYPES = frozenset(
    {
        "bool",
        "int8",
        "int16",
        "int32",
        "int64",
        "uint8",
        "uint16",
        "uint32",
        "uint64",
        "float16",
        "float32",
        "float64",
        "bfloat16",
    }
)


def _dtype_name(dtype) -> str:
    name = np.dtype(dtype).name if dtype != "bfloat16" else "bfloat16"
    # ml_dtypes registers bfloat16 with numpy under this name
    if name not in _SUPPORTED_DTYPES:
        raise ValueError(f"unsupported tensor dtype: {name}")
    return name


def _np_dtype(name: str):
    if name == "bfloat16":
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(name)


@dataclass
class Tensor:
    """A named dense or row-sparse tensor (reference tensor.py:25).

    values: ndarray of the dense values, or the gathered rows for sparse.
    indices: None for dense; 1-D int64 row ids for sparse row-slices.
    """

    name: str
    values: np.ndarray
    indices: np.ndarray | None = None

    def __post_init__(self):
        self.values = np.asarray(self.values)
        if self.indices is not None:
            self.indices = np.asarray(self.indices, dtype=np.int64)
            if self.indices.ndim != 1:
                raise ValueError("indices must be 1-D row ids")
            if self.values.shape[0] != self.indices.shape[0]:
                raise ValueError(
                    "row count mismatch: values %s vs indices %s"
                    % (self.values.shape, self.indices.shape)
                )

    @property
    def is_sparse(self) -> bool:
        return self.indices is not None

    def __add__(self, other: "Tensor") -> "Tensor":
        """Dense+dense adds; sparse+sparse concatenates rows
        (reference tensor.py:92-104)."""
        if self.is_sparse != other.is_sparse:
            raise ValueError("cannot add dense and sparse tensors")
        if self.is_sparse:
            return Tensor(
                self.name,
                np.concatenate([self.values, other.values], axis=0),
                np.concatenate([self.indices, other.indices], axis=0),
            )
        return Tensor(self.name, self.values + other.values)

    def to_bytes(self) -> bytes:
        values = self.values
        if not values.flags["C_CONTIGUOUS"]:
            # note: np.ascontiguousarray would promote 0-d arrays to 1-d,
            # so only call it when actually needed
            values = np.ascontiguousarray(values)
        header = {
            "name": self.name,
            "dtype": _dtype_name(values.dtype),
            "shape": list(values.shape),
            "sparse": self.is_sparse,
        }
        parts = []
        if self.is_sparse:
            idx = self.indices
            if not idx.flags["C_CONTIGUOUS"]:
                idx = np.ascontiguousarray(idx)
            header["num_indices"] = int(idx.shape[0])
            parts.append(idx.tobytes())
        hdr = json.dumps(header).encode("utf-8")
        return b"".join(
            [_HEADER_STRUCT.pack(len(hdr)), hdr, values.tobytes()] + parts
        )

    @classmethod
    def from_bytes(cls, buf: bytes | memoryview) -> "Tensor":
        buf = memoryview(buf)
        (hdr_len,) = _HEADER_STRUCT.unpack_from(buf, 0)
        header = json.loads(bytes(buf[4 : 4 + hdr_len]).decode("utf-8"))
        dtype = _np_dtype(header["dtype"])
        shape = tuple(header["shape"])
        nbytes = int(np.prod(shape)) * dtype.itemsize if shape else dtype.itemsize
        start = 4 + hdr_len
        values = np.frombuffer(
            buf[start : start + nbytes], dtype=dtype
        ).reshape(shape)
        indices = None
        if header.get("sparse"):
            n = header["num_indices"]
            indices = np.frombuffer(
                buf[start + nbytes : start + nbytes + 8 * n], dtype=np.int64
            )
        return cls(header["name"], values.copy(), None if indices is None else indices.copy())


def serialize_tensors(tensors: dict[str, Tensor] | list[Tensor]) -> bytes:
    """Frame a collection of tensors: [u32 count] then length-prefixed frames."""
    if isinstance(tensors, dict):
        tensors = list(tensors.values())
    frames = [t.to_bytes() for t in tensors]
    out = [_HEADER_STRUCT.pack(len(frames))]
    for f in frames:
        out.append(_HEADER_STRUCT.pack(len(f)))
        out.append(f)
    return b"".join(out)


def deserialize_tensors(buf: bytes | memoryview) -> dict[str, Tensor]:
    buf = memoryview(buf)
    (count,) = _HEADER_STRUCT.unpack_from(buf, 0)
    offset = 4
    out: dict[str, Tensor] = {}
    for _ in range(count):
        (flen,) = _HEADER_STRUCT.unpack_from(buf, offset)
        offset += 4
        t = Tensor.from_bytes(buf[offset : offset + flen])
        offset += flen
        out[t.name] = t
    return out


def ndarray_to_tensor(name: str, array, indices=None) -> Tensor:
    return Tensor(name, np.asarray(array), indices)
