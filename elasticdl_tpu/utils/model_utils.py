"""Model-zoo module loading and spec resolution.

Reference: ``elasticdl/python/common/model_utils.py`` — imports the user's
model module by file path and resolves the spec contract
(``custom_model``/``loss``/``optimizer``/``dataset_fn``/``eval_metrics_fn``
and optional ``learning_rate_scheduler``/``PredictionOutputsProcessor``/
``custom_data_reader``, reference model_utils.py:94-150).

The TPU build resolves the same names; ``custom_model`` returns an
:class:`elasticdl_tpu.trainer.spec.ModelSpec`-compatible flax module and
``optimizer`` returns an optax ``GradientTransformation`` (or a factory
taking ``learning_rate``).  When ``--model_zoo`` is empty the module is
imported from the built-in ``elasticdl_tpu.models`` zoo, so reference-style
``--model_def=mnist_functional_api.mnist_functional_api.custom_model``
invocations work out of the box.
"""

from __future__ import annotations

import importlib
import importlib.util
import os
import sys
from dataclasses import dataclass, field
from typing import Any, Callable

from elasticdl_tpu.utils.log_utils import default_logger as logger


def load_module_from_path(module_file: str):
    """Import a python module from an absolute file path
    (reference model_utils.py:11-16)."""
    spec = importlib.util.spec_from_file_location(module_file, module_file)
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


def _split_model_def(model_def: str) -> tuple[str, str]:
    """``pkg.module.func`` -> (``pkg/module.py`` relpath, ``func``)."""
    parts = model_def.split(".")
    if len(parts) < 2:
        raise ValueError(
            "model_def must be 'module_path.function_name', got %r"
            % model_def
        )
    return os.path.join(*parts[:-1]) + ".py", parts[-1]


def load_model_module(model_zoo: str, model_def: str):
    """Load the model module named by ``model_def``.

    With a ``model_zoo`` directory: treat ``model_def`` as
    ``relative.module.path.entry_fn`` rooted at that directory (reference
    model_utils.py:52-58).  Without one: import from the built-in
    ``elasticdl_tpu.models`` package.
    """
    rel_path, func_name = _split_model_def(model_def)
    if model_zoo:
        module_file = os.path.join(model_zoo, rel_path)
        if not os.path.exists(module_file):
            raise FileNotFoundError(module_file)
        module = load_module_from_path(module_file)
    else:
        dotted = "elasticdl_tpu.models." + model_def.rsplit(".", 1)[0]
        # tolerate the reference's dir/file repetition
        # (mnist_functional_api.mnist_functional_api) by trying the full
        # dotted path first, then the last component alone.
        try:
            module = importlib.import_module(dotted)
        except ModuleNotFoundError as e:
            # only fall back when the *named module itself* is missing, not
            # when a dependency imported inside it is
            if e.name is None or not dotted.startswith(e.name):
                raise
            last = dotted.rsplit(".", 1)[-1]
            module = importlib.import_module("elasticdl_tpu.models." + last)
    return module, func_name


@dataclass
class ModelSpec:
    """The resolved model-zoo contract (reference layer 9, SURVEY §1)."""

    model_fn: Callable[..., Any]
    loss: Callable
    optimizer: Callable
    dataset_fn: Callable | None = None
    # optional vectorized alternative to dataset_fn:
    # ``batch_parse(example_batch: dict[str, ndarray], mode)`` receives a
    # WHOLE decoded minibatch (native fused decode+batch path,
    # data/dataset.py batched_model_pipeline) and returns the same
    # element dataset_fn's mapped elements would after batching
    batch_parse: Callable | None = None
    # optional DEVICE-side half of the parse, applied INSIDE the jitted
    # step (train/eval/predict) before the model: lets batch_parse ship
    # compact wire dtypes (e.g. uint8 images) and move elementwise
    # normalization onto the chip — the role tf.data's device-side
    # transforms play for the reference.  Signature: features -> features.
    device_parse: Callable | None = None
    eval_metrics_fn: Callable | None = None
    learning_rate_scheduler: Any | None = None
    prediction_outputs_processor: Any | None = None
    custom_data_reader: Callable | None = None
    # optional module hook ``sharding_rules(mesh) -> [Rule]``: model-forced
    # layout (e.g. deepfm_edl_embedding distributes its tables regardless
    # of size); merged ahead of the auto policy by the SPMD trainer callers
    sharding_rules: Callable | None = None
    model_params: dict = field(default_factory=dict)
    module: Any = None

    def build_model(self):
        return self.model_fn(**self.model_params)


def resolve_model_spec(
    module,
    entry_fn_name: str,
    dataset_fn: str = "dataset_fn",
    loss: str = "loss",
    optimizer: str = "optimizer",
    eval_metrics_fn: str = "eval_metrics_fn",
    custom_data_reader: str = "custom_data_reader",
    prediction_outputs_processor: str = "PredictionOutputsProcessor",
) -> ModelSpec:
    """Resolve the spec functions from a loaded model module, honoring
    user-renamed spec functions (reference model_utils.py:94-150 +
    args.py:448-486)."""

    def _get(name, required=False):
        obj = getattr(module, name, None)
        if obj is None and required:
            raise AttributeError(
                f"model module {module.__name__!r} must define {name!r}"
            )
        return obj

    model_fn = _get(entry_fn_name)
    if model_fn is None:
        # subclass style: entry name is a class (reference CustomModel)
        raise AttributeError(
            f"model module {module.__name__!r} has no entry {entry_fn_name!r}"
        )

    processor_cls = _get(prediction_outputs_processor)
    processor = processor_cls() if processor_cls is not None else None
    if processor is None:
        logger.debug(
            "PredictionOutputsProcessor not defined in the model module; "
            "prediction outputs will not be processed"
        )

    return ModelSpec(
        model_fn=model_fn,
        loss=_get(loss, required=True),
        optimizer=_get(optimizer, required=True),
        dataset_fn=_get(dataset_fn),
        # the vectorized fast path pairs with the DEFAULT dataset_fn; a
        # user-renamed --dataset_fn selects a different parse, which
        # batch_parse must not silently bypass
        batch_parse=(
            _get("batch_parse") if dataset_fn == "dataset_fn" else None
        ),
        device_parse=(
            _get("device_parse") if dataset_fn == "dataset_fn" else None
        ),
        eval_metrics_fn=_get(eval_metrics_fn),
        learning_rate_scheduler=_get("learning_rate_scheduler"),
        prediction_outputs_processor=processor,
        custom_data_reader=_get(custom_data_reader),
        sharding_rules=_get("sharding_rules"),
        module=module,
    )


def get_model_spec(
    model_zoo: str,
    model_def: str,
    model_params: dict | None = None,
    dataset_fn: str = "dataset_fn",
    loss: str = "loss",
    optimizer: str = "optimizer",
    eval_metrics_fn: str = "eval_metrics_fn",
    custom_data_reader: str = "custom_data_reader",
    prediction_outputs_processor: str = "PredictionOutputsProcessor",
) -> ModelSpec:
    """One-call loader used by master/worker/local executor."""
    module, entry = load_model_module(model_zoo, model_def)
    spec = resolve_model_spec(
        module,
        entry,
        dataset_fn=dataset_fn,
        loss=loss,
        optimizer=optimizer,
        eval_metrics_fn=eval_metrics_fn,
        custom_data_reader=custom_data_reader,
        prediction_outputs_processor=prediction_outputs_processor,
    )
    spec.model_params = dict(model_params or {})
    return spec
