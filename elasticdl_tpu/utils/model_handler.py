"""Strategy-dependent model distribution policy.

Reference: ``elasticdl/python/common/model_handler.py`` — for the PS
strategy it *clones the Keras model*, swapping ``tf.keras.layers.Embedding``
for the RPC-backed EDL layer iff the table exceeds 2MB (:47-55,199-241),
and reverses the rewrite (plus checkpoint-weight injection) at export time
(:155-197).

In the TPU build a model never needs rewriting: distribution is a *layout*
decision, not a *layer* decision.  The handler therefore emits sharding
rules (consumed by ``SPMDTrainer``) instead of cloned models, and export is
a host-gather of the (possibly sharded) state — the same user-visible
contract (small tables stay local, big tables get distributed, exports are
always dense) with none of the clone/rewrite machinery.
"""

from __future__ import annotations

from typing import Sequence

import jax

from elasticdl_tpu.utils.constants import (
    DistributionStrategy,
    EMBEDDING_AUTO_DISTRIBUTE_BYTES,
)


class ModelHandler:
    """Base handler: no distribution (Local strategy)."""

    def __init__(self, threshold_bytes: int = EMBEDDING_AUTO_DISTRIBUTE_BYTES):
        self.threshold_bytes = threshold_bytes

    @classmethod
    def get_model_handler(
        cls, distribution_strategy=None, checkpoint_dir=None
    ) -> "ModelHandler":
        """Factory mirroring model_handler.py:89-111."""
        if distribution_strategy in (
            DistributionStrategy.PARAMETER_SERVER,
            DistributionStrategy.ALLREDUCE,
        ):
            return DistributedModelHandler(checkpoint_dir=checkpoint_dir)
        return ModelHandler()

    def get_model_to_train(self, model):
        """Models run unchanged; kept for reference-API compatibility."""
        return model

    def sharding_rules(self, params_shapes, mesh) -> Sequence:
        return ()

    def get_model_to_export(self, state) -> dict:
        """Dense, host-resident name->ndarray dict of the full model —
        always un-sharded regardless of training layout (the analogue of
        the reverse rewrite at model_handler.py:155-197)."""
        from elasticdl_tpu.trainer.state import state_to_checkpoint

        return {
            k: jax.device_get(v)
            for k, v in state_to_checkpoint(state).items()
        }


class DistributedModelHandler(ModelHandler):
    """PS/AllReduce-strategy handler: distribute big embedding tables.

    Same policy knob as the reference (tables > ``threshold_bytes`` get
    distributed), realized as vocab-dim sharding rules instead of layer
    swaps."""

    def __init__(self, checkpoint_dir=None, **kwargs):
        super().__init__(**kwargs)
        self.checkpoint_dir = checkpoint_dir

    def sharding_rules(self, params_shapes, mesh) -> Sequence:
        from elasticdl_tpu.layers.embedding import auto_partition_rules

        return auto_partition_rules(
            params_shapes, mesh, self.threshold_bytes
        )
