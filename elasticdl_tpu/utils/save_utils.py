"""Checkpointing: versioned, re-shardable, name-keyed.

Reference: ``elasticdl/python/common/save_utils.py`` — protobuf Model
checkpoints ``{dir}/version-{v}/variables-{i}-of-{N}.ckpt`` with retention
(``keep_checkpoint_max``), validity = all N parts present, and a
**resharding restore** that re-hashes every variable/embedding row when the
PS count changes (save_utils.py:208-261).

The TPU build keeps the same directory scheme and the same key property —
a checkpoint written by an N-host mesh restores onto an M-host mesh — but
stores name-keyed numpy arrays (npz) plus a JSON manifest instead of
protobufs.  Dense parameters are saved whole (host 0 owns them; they are
replicated across the dp axis).  Sharded embedding tables are saved as
``(ids, rows)`` pairs per part; restore concatenates and re-partitions by
``int_to_id`` hashing for the new shard count, exactly like the reference.
"""

from __future__ import annotations

import json
import os
import shutil

import numpy as np

from elasticdl_tpu.utils import hash_utils
from elasticdl_tpu.utils.log_utils import default_logger as logger

_MANIFEST = "manifest.json"


def _version_dir(checkpoint_dir: str, version: int) -> str:
    return os.path.join(checkpoint_dir, f"version-{version}")


def _part_file(i: int, n: int) -> str:
    return f"variables-{i}-of-{n}.npz"


class CheckpointSaver:
    """Writes checkpoints; enforces retention."""

    def __init__(
        self,
        checkpoint_dir: str,
        keep_checkpoint_max: int = 3,
        include_evaluation: bool = False,
    ):
        if not checkpoint_dir:
            raise ValueError("checkpoint_dir must be set")
        self._dir = checkpoint_dir
        self._keep_max = keep_checkpoint_max
        os.makedirs(checkpoint_dir, exist_ok=True)

    @property
    def directory(self) -> str:
        return self._dir

    def save(
        self,
        version: int,
        dense: dict[str, np.ndarray],
        embeddings: dict[str, tuple[np.ndarray, np.ndarray]] | None = None,
        part: int = 0,
        num_parts: int = 1,
        extra: dict | None = None,
    ):
        """Save one part of checkpoint ``version``.

        dense: name -> array (only part 0 should carry dense params).
        embeddings: table_name -> (ids [n], rows [n, dim]) owned by this part.
        """
        vdir = _version_dir(self._dir, version)
        os.makedirs(vdir, exist_ok=True)
        payload: dict[str, np.ndarray] = {}
        names = {"dense": sorted(dense), "embeddings": []}
        for name, arr in dense.items():
            payload[f"dense/{name}"] = np.asarray(arr)
        for name, (ids, rows) in (embeddings or {}).items():
            names["embeddings"].append(name)
            payload[f"emb_ids/{name}"] = np.asarray(ids, dtype=np.int64)
            payload[f"emb_rows/{name}"] = np.asarray(rows)
        np.savez(os.path.join(vdir, _part_file(part, num_parts)), **payload)
        if part == 0:
            manifest = {
                "version": version,
                "num_parts": num_parts,
                "names": names,
                "extra": extra or {},
            }
            with open(os.path.join(vdir, _MANIFEST), "w") as f:
                json.dump(manifest, f)
        self._enforce_retention()
        logger.info(
            "Saved checkpoint version %d part %d/%d to %s",
            version,
            part,
            num_parts,
            vdir,
        )

    def _versions(self) -> list[int]:
        out = []
        if not os.path.isdir(self._dir):
            return out
        for name in os.listdir(self._dir):
            if name.startswith("version-"):
                try:
                    out.append(int(name.split("-", 1)[1]))
                except ValueError:
                    continue
        return sorted(out)

    def _enforce_retention(self):
        if self._keep_max <= 0:
            return
        versions = self._versions()
        while len(versions) > self._keep_max:
            victim = versions.pop(0)
            shutil.rmtree(_version_dir(self._dir, victim), ignore_errors=True)
            logger.info("Evicted checkpoint version %d", victim)


def checkpoint_is_valid(checkpoint_dir: str, version: int) -> bool:
    """All parts present (reference save_utils.py:190-206)."""
    vdir = _version_dir(checkpoint_dir, version)
    manifest_path = os.path.join(vdir, _MANIFEST)
    if not os.path.exists(manifest_path):
        return False
    with open(manifest_path) as f:
        manifest = json.load(f)
    n = manifest["num_parts"]
    return all(
        os.path.exists(os.path.join(vdir, _part_file(i, n)))
        for i in range(n)
    )


def latest_version(checkpoint_dir: str) -> int | None:
    saver_versions = []
    if not os.path.isdir(checkpoint_dir):
        return None
    for name in os.listdir(checkpoint_dir):
        if name.startswith("version-"):
            try:
                v = int(name.split("-", 1)[1])
            except ValueError:
                continue
            if checkpoint_is_valid(checkpoint_dir, v):
                saver_versions.append(v)
    return max(saver_versions) if saver_versions else None


def restore_checkpoint(
    checkpoint_dir: str,
    version: int | None = None,
    num_shards: int = 1,
    shard_id: int = 0,
) -> tuple[dict[str, np.ndarray], dict[str, tuple[np.ndarray, np.ndarray]], dict]:
    """Restore (dense, embeddings, extra) for ``shard_id`` of ``num_shards``.

    Works across a *different* part count than the checkpoint was written
    with: embedding rows from all parts are concatenated and re-partitioned
    by ``int_to_id(id, num_shards)`` — the reference's resharding property
    (save_utils.py:208-261).  Dense params are returned whole to every
    shard (they are replicated on the mesh).
    """
    # accept a direct version dir ({root}/version-N) like the reference's
    # --checkpoint_dir_for_init usage (tests point at version-100 dirs)
    base = os.path.basename(os.path.normpath(checkpoint_dir))
    if version is None and base.startswith("version-"):
        try:
            version = int(base.split("-", 1)[1])
            checkpoint_dir = os.path.dirname(os.path.normpath(checkpoint_dir))
        except ValueError:
            pass
    if version is None:
        version = latest_version(checkpoint_dir)
        if version is None:
            raise FileNotFoundError(
                f"no valid checkpoint under {checkpoint_dir}"
            )
    if not checkpoint_is_valid(checkpoint_dir, version):
        raise FileNotFoundError(
            f"checkpoint version {version} under {checkpoint_dir} is invalid"
        )
    vdir = _version_dir(checkpoint_dir, version)
    with open(os.path.join(vdir, _MANIFEST)) as f:
        manifest = json.load(f)
    n = manifest["num_parts"]

    dense: dict[str, np.ndarray] = {}
    emb_ids: dict[str, list[np.ndarray]] = {}
    emb_rows: dict[str, list[np.ndarray]] = {}
    for i in range(n):
        with np.load(os.path.join(vdir, _part_file(i, n))) as z:
            for key in z.files:
                kind, name = key.split("/", 1)
                if kind == "dense":
                    dense[name] = z[key]
                elif kind == "emb_ids":
                    emb_ids.setdefault(name, []).append(z[key])
                elif kind == "emb_rows":
                    emb_rows.setdefault(name, []).append(z[key])

    embeddings: dict[str, tuple[np.ndarray, np.ndarray]] = {}
    for name in emb_ids:
        ids = np.concatenate(emb_ids[name])
        rows = np.concatenate(emb_rows[name], axis=0)
        if num_shards > 1 or n > 1:
            mask = np.asarray(
                [hash_utils.int_to_id(i, num_shards) == shard_id for i in ids]
            )
            ids, rows = ids[mask], rows[mask]
        embeddings[name] = (ids, rows)
    return dense, embeddings, manifest.get("extra", {})
