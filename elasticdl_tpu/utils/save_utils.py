"""Checkpointing: versioned, re-shardable, name-keyed.

Reference: ``elasticdl/python/common/save_utils.py`` — protobuf Model
checkpoints ``{dir}/version-{v}/variables-{i}-of-{N}.ckpt`` with retention
(``keep_checkpoint_max``), validity = all N parts present, and a
**resharding restore** that re-hashes every variable/embedding row when the
PS count changes (save_utils.py:208-261).

The TPU build keeps the same directory scheme and the same key property —
a checkpoint written by an N-host mesh restores onto an M-host mesh — but
stores name-keyed numpy arrays (npz) plus a JSON manifest instead of
protobufs.  Dense parameters are saved whole (host 0 owns them; they are
replicated across the dp axis).  Sharded embedding tables are saved as
``(ids, rows)`` pairs per part; restore concatenates and re-partitions by
``int_to_id`` hashing for the new shard count, exactly like the reference.
"""

from __future__ import annotations

import json
import os
import shutil

import numpy as np

from elasticdl_tpu.utils.log_utils import default_logger as logger

_MANIFEST = "manifest.json"


def _version_dir(checkpoint_dir: str, version: int) -> str:
    return os.path.join(checkpoint_dir, f"version-{version}")


def _part_file(i: int, n: int) -> str:
    return f"variables-{i}-of-{n}.npz"


class CheckpointSaver:
    """Writes checkpoints; enforces retention."""

    def __init__(
        self,
        checkpoint_dir: str,
        keep_checkpoint_max: int = 3,
        include_evaluation: bool = False,
    ):
        if not checkpoint_dir:
            raise ValueError("checkpoint_dir must be set")
        self._dir = checkpoint_dir
        self._keep_max = keep_checkpoint_max
        os.makedirs(checkpoint_dir, exist_ok=True)

    @property
    def directory(self) -> str:
        return self._dir

    def save(
        self,
        version: int,
        dense: dict[str, np.ndarray],
        embeddings: dict[str, tuple[np.ndarray, np.ndarray]] | None = None,
        part: int = 0,
        num_parts: int = 1,
        extra: dict | None = None,
        enforce_retention: bool = True,
    ):
        """Save one part of checkpoint ``version``.

        dense: name -> array (only part 0 should carry dense params).
        embeddings: table_name -> (ids [n], rows [n, dim]) owned by this part.
        enforce_retention: pass False on parts written concurrently with
        part 0 (exactly one writer should delete old versions).
        """
        vdir = _version_dir(self._dir, version)
        os.makedirs(vdir, exist_ok=True)
        payload: dict[str, np.ndarray] = {}
        names = {"dense": sorted(dense), "embeddings": []}
        for name, arr in dense.items():
            payload[f"dense/{name}"] = np.asarray(arr)
        for name, (ids, rows) in (embeddings or {}).items():
            names["embeddings"].append(name)
            payload[f"emb_ids/{name}"] = np.asarray(ids, dtype=np.int64)
            payload[f"emb_rows/{name}"] = np.asarray(rows)
        # atomic publish: a SIGKILL mid-save (mesh re-formation kills
        # workers) must never leave a torn npz behind a complete-looking
        # file set — write to a temp name, then rename
        final = os.path.join(vdir, _part_file(part, num_parts))
        # keep the .npz suffix so np.savez doesn't append another one
        tmp = os.path.join(
            vdir, f".tmp-{os.getpid()}-{_part_file(part, num_parts)}"
        )
        np.savez(tmp, **payload)
        os.replace(tmp, final)
        if part == 0:
            manifest = {
                "version": version,
                "num_parts": num_parts,
                "names": names,
                "extra": extra or {},
            }
            with open(os.path.join(vdir, _MANIFEST), "w") as f:
                json.dump(manifest, f)
        if enforce_retention:
            self._enforce_retention()
        logger.info(
            "Saved checkpoint version %d part %d/%d to %s",
            version,
            part,
            num_parts,
            vdir,
        )

    def _versions(self) -> list[int]:
        return _list_versions(self._dir)

    def _enforce_retention(self):
        if self._keep_max <= 0:
            return
        versions = self._versions()
        while len(versions) > self._keep_max:
            victim = versions.pop(0)
            shutil.rmtree(_version_dir(self._dir, victim), ignore_errors=True)
            logger.info("Evicted checkpoint version %d", victim)


def checkpoint_is_valid(checkpoint_dir: str, version: int) -> bool:
    """All parts present (reference save_utils.py:190-206)."""
    vdir = _version_dir(checkpoint_dir, version)
    manifest_path = os.path.join(vdir, _MANIFEST)
    if not os.path.exists(manifest_path):
        return False
    with open(manifest_path) as f:
        manifest = json.load(f)
    n = manifest["num_parts"]
    return all(
        os.path.exists(os.path.join(vdir, _part_file(i, n)))
        for i in range(n)
    )


def latest_version(checkpoint_dir: str) -> int | None:
    valid = [
        v
        for v in _list_versions(checkpoint_dir)
        if checkpoint_is_valid(checkpoint_dir, v)
    ]
    return max(valid) if valid else None


def restore_checkpoint(
    checkpoint_dir: str,
    version: int | None = None,
    num_shards: int = 1,
    shard_id: int = 0,
    table_row_ranges: dict[str, list[tuple[int, int]]] | None = None,
) -> tuple[dict[str, np.ndarray], dict[str, tuple[np.ndarray, np.ndarray]], dict]:
    """Restore (dense, embeddings, extra) for ``shard_id`` of ``num_shards``.

    Works across a *different* part count than the checkpoint was written
    with: embedding rows from all parts are concatenated and re-partitioned
    by ``int_to_id(id, num_shards)`` — the reference's resharding property
    (save_utils.py:208-261).  Dense params are returned whole to every
    shard (they are replicated on the mesh).

    ``table_row_ranges``: optional per-table ``[(lo, hi), ...]`` keep
    filters applied WHILE iterating parts, so a caller restoring a
    mesh-sharded table keeps only its own rows and never holds the full
    table in host memory.

    With ``version=None``, versions are tried newest-first: a torn or
    unreadable version (e.g. a save raced by a worker SIGKILL) falls back
    to the next older intact one instead of failing the restore.
    """
    # accept a direct version dir ({root}/version-N) like the reference's
    # --checkpoint_dir_for_init usage (tests point at version-100 dirs)
    base = os.path.basename(os.path.normpath(checkpoint_dir))
    if version is None and base.startswith("version-"):
        try:
            version = int(base.split("-", 1)[1])
            checkpoint_dir = os.path.dirname(os.path.normpath(checkpoint_dir))
        except ValueError:
            pass
    if version is not None:
        if not checkpoint_is_valid(checkpoint_dir, version):
            raise FileNotFoundError(
                f"checkpoint version {version} under {checkpoint_dir} "
                f"is invalid"
            )
        return _load_version(
            checkpoint_dir, version, num_shards, shard_id, table_row_ranges
        )
    candidates = [
        v
        for v in _list_versions(checkpoint_dir)
        if checkpoint_is_valid(checkpoint_dir, v)
    ]
    if not candidates:
        raise FileNotFoundError(f"no valid checkpoint under {checkpoint_dir}")
    last_error: Exception | None = None
    for v in reversed(candidates):
        try:
            return _load_version(
                checkpoint_dir, v, num_shards, shard_id, table_row_ranges
            )
        except Exception as ex:  # noqa: BLE001 — torn files fall through
            logger.warning(
                "Checkpoint version %d under %s unreadable (%s); "
                "falling back to an older version",
                v,
                checkpoint_dir,
                ex,
            )
            last_error = ex
    raise FileNotFoundError(
        f"all checkpoint versions under {checkpoint_dir} unreadable"
    ) from last_error


def _load_version(
    checkpoint_dir: str,
    version: int,
    num_shards: int,
    shard_id: int,
    table_row_ranges: dict[str, list[tuple[int, int]]] | None,
):
    vdir = _version_dir(checkpoint_dir, version)
    with open(os.path.join(vdir, _MANIFEST)) as f:
        manifest = json.load(f)
    n = manifest["num_parts"]

    dense: dict[str, np.ndarray] = {}
    emb_ids: dict[str, list[np.ndarray]] = {}
    emb_rows: dict[str, list[np.ndarray]] = {}
    for i in range(n):
        with np.load(os.path.join(vdir, _part_file(i, n))) as z:
            for key in z.files:
                kind, name = key.split("/", 1)
                if kind == "dense":
                    dense[name] = z[key]
                elif kind == "emb_ids":
                    emb_ids.setdefault(name, []).append(z[key])
                elif kind == "emb_rows":
                    emb_rows.setdefault(name, []).append(z[key])
        # filter per part so only locally-owned rows accumulate
        if table_row_ranges:
            for name in list(emb_ids):
                if name not in table_row_ranges or not emb_ids[name]:
                    continue
                ids = emb_ids[name][-1]
                if ids.size == 0:
                    continue
                keep = np.zeros(ids.shape, dtype=bool)
                for lo, hi in table_row_ranges[name]:
                    keep |= (ids >= lo) & (ids < hi)
                emb_ids[name][-1] = ids[keep]
                emb_rows[name][-1] = emb_rows[name][-1][keep]

    embeddings: dict[str, tuple[np.ndarray, np.ndarray]] = {}
    for name in emb_ids:
        ids = np.concatenate(emb_ids[name])
        rows = np.concatenate(emb_rows[name], axis=0)
        if num_shards > 1:
            # vectorized int_to_id (hash_utils.py: id mod N)
            mask = (ids % num_shards) == shard_id
            ids, rows = ids[mask], rows[mask]
        embeddings[name] = (ids, rows)
    return dense, embeddings, manifest.get("extra", {})


def _list_versions(checkpoint_dir: str) -> list[int]:
    out = []
    if not os.path.isdir(checkpoint_dir):
        return out
    for name in os.listdir(checkpoint_dir):
        if name.startswith("version-"):
            try:
                out.append(int(name.split("-", 1)[1]))
            except ValueError:
                continue
    return sorted(out)


def assemble_embedding_tables(
    embeddings: dict[str, tuple[np.ndarray, np.ndarray]],
) -> dict[str, np.ndarray]:
    """Reassemble full tables from ``(ids, rows)`` parts.

    Parts carry explicit global row ids, so this is independent of how
    the writer's mesh laid the table out — the ids must simply cover
    ``0..V-1`` exactly once (range-sharded writers do).  Use with
    ``restore_checkpoint(..., num_shards=1)``.
    """
    out: dict[str, np.ndarray] = {}
    for name, (ids, rows) in embeddings.items():
        order = np.argsort(ids)
        ids_sorted = ids[order]
        expected = np.arange(len(ids_sorted), dtype=ids_sorted.dtype)
        if len(ids_sorted) == 0 or not np.array_equal(ids_sorted, expected):
            raise ValueError(
                f"embedding parts for {name!r} do not cover a full "
                f"contiguous table (got {len(ids_sorted)} ids)"
            )
        out[name] = rows[order]
    return out
