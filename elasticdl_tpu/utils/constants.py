"""Framework-wide constants and enums.

Reference: ``elasticdl/python/common/constants.py`` (strategy / job-type /
pod-status vocabulary) and ``elasticdl/proto/elasticdl.proto`` (task types).
The TPU build keeps the same user-facing vocabulary so the CLI surface is
compatible, and adds TPU-specific mesh-axis names.
"""

from __future__ import annotations

import enum


class GRPC:
    # Control-plane traffic only (tasks, metrics, versions) — tensors never
    # ride RPC on the hot path in the TPU design, but eval-metric reports can
    # be large, so keep the reference's generous cap
    # (reference constants.py:1-5: 256MB max message).
    MAX_SEND_MESSAGE_LENGTH = 256 * 1024 * 1024
    MAX_RECEIVE_MESSAGE_LENGTH = 256 * 1024 * 1024


class InstanceManagerStatus:
    PENDING = "Pending"
    RUNNING = "Running"
    FINISHED = "Finished"


class JobType(enum.Enum):
    # reference common/constants.py:21-25
    TRAINING_ONLY = "training_only"
    EVALUATION_ONLY = "evaluation_only"
    PREDICTION_ONLY = "prediction_only"
    TRAINING_WITH_EVALUATION = "training_with_evaluation"


class TaskType(enum.IntEnum):
    """Work-unit types served by the master's task dispatcher.

    reference elasticdl.proto (TaskType) — WAIT is the 'no task right now,
    poll again' sentinel the servicer returns while eval tasks are pending
    (reference master/servicer.py:32-63).
    """

    TRAINING = 0
    EVALUATION = 1
    PREDICTION = 2
    WAIT = 3
    SAVE_MODEL = 4


class DistributionStrategy:
    """User-selectable strategies (reference common/constants.py:43-46).

    The TPU build maps them as:

    - LOCAL: single-process, single-chip (or single-host) jit loop.
    - PARAMETER_SERVER: accepted for CLI compatibility; dense parameters are
      *not* served by PS pods — they live on-device and sync via psum.  What
      survives from the PS design is the sharded embedding table, which
      becomes a mesh-sharded array with all-to-all lookup.
    - ALLREDUCE: the native TPU strategy — SPMD data parallelism over a
      device mesh with XLA collectives over ICI/DCN.
    """

    LOCAL = "Local"
    PARAMETER_SERVER = "ParameterServerStrategy"
    ALLREDUCE = "AllreduceStrategy"

    ALL = (LOCAL, PARAMETER_SERVER, ALLREDUCE)


class PodStatus:
    # reference common/constants.py:62-67
    INITIAL = "Initial"
    PENDING = "Pending"
    RUNNING = "Running"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"
    DELETED = "Deleted"


class ReaderType:
    # reference common/constants.py:69-72
    CSV_READER = "CSV"
    ODPS_READER = "ODPS"
    RECORDIO_READER = "RecordIO"


class MeshAxis:
    """Canonical logical mesh-axis names for the TPU build.

    dp: data parallel (batch sharding; gradient psum rides this axis)
    fsdp: fully-sharded data parallel (parameter sharding over the dp axis)
    tp: tensor parallel (feature-dim sharding of weights/activations)
    sp: sequence/context parallel (ring attention / Ulysses all-to-all)
    ep: expert / embedding parallel (sharded embedding tables, MoE experts)
    pp: pipeline parallel (layer stages; activations ppermute stage-to-stage)
    """

    DP = "dp"
    FSDP = "fsdp"
    TP = "tp"
    SP = "sp"
    EP = "ep"
    PP = "pp"

    ALL = (DP, FSDP, TP, SP, EP, PP)


class WorkerEnv:
    """Env vars the master injects into worker processes."""

    MASTER_ADDR = "EDL_TPU_MASTER_ADDR"
    WORKER_ID = "EDL_TPU_WORKER_ID"
    NUM_WORKERS = "EDL_TPU_NUM_WORKERS"
    COORDINATOR_ADDR = "EDL_TPU_COORDINATOR_ADDR"


class Initializer:
    """Default initializer names accepted by embedding layers/tables."""

    UNIFORM = "uniform"
    NORMAL = "normal"
    ZEROS = "zeros"
    ONES = "ones"


# Auto-distribute threshold for embedding tables: Keras embeddings bigger
# than this are rewritten to the distributed sharded-table layer by the
# model handler (reference common/model_handler.py:47-55: 2MB rule).
EMBEDDING_AUTO_DISTRIBUTE_BYTES = 2 * 1024 * 1024

# Max times a worker retries a minibatch on transient failure
# (reference worker/worker.py:46).
MAX_MINIBATCH_RETRY_NUM = 64

# Default port the master control-plane service listens on.
MASTER_DEFAULT_PORT = 50001
