"""XLA profiler windows: flag-armed at launch, or on-demand at runtime.

The reference's only tracing is wall-clock buckets at DEBUG level
(``common/timing_utils.py``, kept as ``utils.timing_utils``); on TPU the
tool that actually explains a slow step is the XLA profiler (op-level
device timeline, HLO attribution, TensorBoard ``profile`` plugin).  Two
ways to open a capture window:

1. **Launch flags** — ``--profile_dir d --profile_steps N`` traces steps
   [start, start + N) into ``d`` (past compile + warmup), exactly as
   before.
2. **On demand** — the ``request_profile`` master RPC arms a window on a
   RUNNING job: the command rides down on heartbeat responses
   (``HeartbeatResponse.profile``), :func:`apply_profile_command` calls
   :meth:`StepProfiler.arm`, and the next training step opens an
   ``N``-step capture into the telemetry dir — a live degraded job gets
   op-level attribution without a relaunch.  Workers dedupe by
   ``window_id`` (monotone per master), so the command may be
   re-delivered or re-sent every beat and is absorbed.

Both paths emit the same ``profile_window_open``/``profile_window_close``
events and the ``profile_window`` span, so the capture window can be
located on the same timeline as the distributed trace.

Disabled cost: with no window pending or open, :meth:`on_step` is one
attribute load and a ``not x`` check (``# elastic-lint: hot-path``).
Thread model: :meth:`arm` is called from the heartbeat thread,
:meth:`on_step` from the training thread — the engaged flag is the
lock-free gate, everything behind it synchronizes on a small lock.
"""

from __future__ import annotations

import os
import threading

from elasticdl_tpu.utils.log_utils import default_logger as logger

# subdirectory of the telemetry dir an on-demand capture lands in when
# the request names no explicit out_dir
PROFILE_SUBDIR = "profile"


class StepProfiler:
    """Capture step windows with ``jax.profiler``.

    ``on_step()`` is called once per step by the training loop and counts
    calls SINCE PROCESS START (not the model version — a checkpoint-
    resumed run at version 10000 still warms up before its window).  The
    flag-armed window starts at call ``start_step`` (past compile +
    warmup) and stops ``num_steps`` later; an :meth:`arm`-ed window
    starts at the NEXT call.  One window at a time; idle (nothing
    pending or tracing) it is one attribute load per step.
    """

    def __init__(
        self,
        out_dir: str | None,
        start_step: int = 5,
        num_steps: int = 5,
    ):
        self._lock = threading.Lock()
        self._seen = 0  # guarded-by: _lock
        self._tracing = False  # guarded-by: _lock (writes)
        self._out_dir = ""  # dir of the OPEN window  # guarded-by: _lock
        self._stop_at = 0  # last in-window call index  # guarded-by: _lock
        self._opened_at = 0  # guarded-by: _lock
        self._window_id: int | None = None  # guarded-by: _lock
        self._window_span = None  # guarded-by: _lock
        # flag-armed window (never opened yet when _flag_dir non-empty)
        self._flag_dir = out_dir or ""  # guarded-by: _lock
        self._flag_start = start_step
        self._flag_num = num_steps
        self._flag_ever_armed = bool(out_dir)
        # on-demand window waiting to open  # guarded-by: _lock
        self._pending: dict | None = None
        # replay dedup: the largest window id ever armed
        self._last_window_id = 0  # guarded-by: _lock
        # lock-free hot gate: True iff a window is pending or open.
        # Writes happen under _lock; the training thread's stale read
        # costs at most one extra locked call
        self._engaged = bool(out_dir)

    # ---- runtime arming (heartbeat thread) ---------------------------------

    def arm(
        self,
        out_dir: str,
        num_steps: int = 5,
        window_id: int | None = None,
    ) -> bool:
        """Arm an on-demand window opening at the next ``on_step``.
        Returns False when absorbed (a replayed ``window_id``) or
        refused (a window is already pending/open — the caller retries
        on a later beat; an unconsumed id stays armable)."""
        if not out_dir:
            return False
        with self._lock:
            if window_id is not None and window_id <= self._last_window_id:
                return False  # replayed command: absorbed
            if self._tracing or self._pending is not None:
                return False  # one window at a time; retry later
            if window_id is not None:
                self._last_window_id = window_id
            self._pending = {
                "out_dir": out_dir,
                "num_steps": max(1, int(num_steps)),
                "window_id": window_id,
            }
            self._engaged = True
        logger.info(
            "XLA profiler: on-demand window armed (%d steps into %s)",
            max(1, int(num_steps)),
            out_dir,
        )
        return True

    # ---- the per-step hook (training thread) -------------------------------

    def on_step(self, _step=None):  # elastic-lint: hot-path
        """Count one training step (the argument is accepted and ignored
        for call-site readability); one attribute load when idle."""
        if not self._engaged:
            return
        self._on_step_engaged()

    def _on_step_engaged(self):
        with self._lock:
            self._seen += 1
            if not self._tracing:
                if self._pending is not None:
                    pending, self._pending = self._pending, None
                    self._open_window_locked(
                        pending["out_dir"],
                        self._seen + pending["num_steps"] - 1,
                        pending["window_id"],
                    )
                elif self._flag_dir and self._seen > self._flag_start:
                    flag_dir, self._flag_dir = self._flag_dir, ""
                    self._open_window_locked(
                        flag_dir,
                        self._flag_start + self._flag_num,
                        None,
                    )
            elif self._seen > self._stop_at:
                self._close_window_locked()
            self._refresh_engaged_locked()

    # lock-holding: _lock
    def _refresh_engaged_locked(self):
        self._engaged = bool(
            self._tracing or self._pending is not None or self._flag_dir
        )

    # lock-holding: _lock
    def _open_window_locked(self, out_dir: str, stop_at: int, window_id):
        import jax

        try:
            jax.profiler.start_trace(out_dir)
        except Exception:  # noqa: BLE001 — a failed capture (another
            # trace active, unwritable dir) must not kill the training
            # thread; the window is abandoned
            logger.exception("XLA profiler: start_trace failed")
            return
        self._tracing = True
        self._out_dir = out_dir
        self._stop_at = stop_at
        self._opened_at = self._seen
        self._window_id = window_id
        # telemetry marker + span so the XLA profiler window can be
        # located on the SAME timeline as the distributed trace (both
        # no-ops when telemetry/tracing is not installed)
        from elasticdl_tpu.telemetry import tracing as _trace
        from elasticdl_tpu.telemetry import worker_hooks
        from elasticdl_tpu.telemetry.events import EVENT_PROFILE_WINDOW_OPEN

        fields = dict(at_call=self._seen, out_dir=out_dir)
        if window_id is not None:
            fields["window_id"] = int(window_id)
        worker_hooks.emit_event(EVENT_PROFILE_WINDOW_OPEN, **fields)
        tracer = _trace.get_tracer()
        if tracer is not None:
            self._window_span = tracer.start_span(
                _trace.SPAN_PROFILE_WINDOW, out_dir=out_dir
            )
        logger.info(
            "XLA profiler: tracing %d steps into %s",
            self._stop_at - self._seen + 1,
            out_dir,
        )

    # lock-holding: _lock
    def _close_window_locked(self):
        import jax

        try:
            jax.profiler.stop_trace()
        except Exception:  # noqa: BLE001 — a torn capture must not kill
            # the training thread
            logger.exception("XLA profiler: stop_trace failed")
        self._tracing = False
        from elasticdl_tpu.telemetry import worker_hooks
        from elasticdl_tpu.telemetry.events import EVENT_PROFILE_WINDOW_CLOSE

        fields = dict(
            at_call=self._seen,
            out_dir=self._out_dir,
            steps=self._seen - self._opened_at,
        )
        if self._window_id is not None:
            fields["window_id"] = int(self._window_id)
        worker_hooks.emit_event(EVENT_PROFILE_WINDOW_CLOSE, **fields)
        if self._window_span is not None:
            self._window_span.end(steps=self._seen - self._opened_at)
            self._window_span = None
        logger.info("XLA profiler: trace written to %s", self._out_dir)
        self._window_id = None
        self._out_dir = ""

    def stop(self):
        """Idempotent; called at loop exit so a short run still flushes
        a partial window (and warns when a flag window never opened)."""
        with self._lock:
            if self._tracing:
                self._close_window_locked()
            elif self._flag_dir and self._flag_ever_armed:
                logger.warning(
                    "XLA profiler: window never opened — the run had %d "
                    "steps but tracing starts after step %d "
                    "(--profile_steps only sets the window length)",
                    self._seen,
                    self._flag_start,
                )
            self._flag_dir = ""
            self._flag_ever_armed = False
            self._pending = None
            self._refresh_engaged_locked()


def apply_profile_command(
    profiler: StepProfiler,
    command: dict,
    telemetry_dir: str = "",
    tag: str = "",
) -> bool:
    """Arm ``profiler`` from a heartbeat-borne ``request_profile``
    command (the worker side of the round trip).  The capture lands in
    the command's ``out_dir`` or ``<telemetry_dir>/profile``, under a
    per-window (and per-process, via ``tag``) subdirectory so
    concurrent workers on one host never interleave trace files.
    Absorbed replays (seen window ids) return False — THE dedup that
    lets the master redistribute the command on every beat."""
    if not command or not isinstance(command, dict):
        return False
    try:
        window_id = int(command.get("window_id", 0))
    except (TypeError, ValueError):
        return False
    if window_id <= 0:
        return False
    base = str(command.get("out_dir") or "") or (
        os.path.join(telemetry_dir, PROFILE_SUBDIR) if telemetry_dir else ""
    )
    if not base:
        return False
    leaf = f"window_{window_id}" + (f"_{tag}" if tag else "")
    try:
        num_steps = int(command.get("num_steps", 5))
    except (TypeError, ValueError):
        num_steps = 5
    return profiler.arm(
        os.path.join(base, leaf), num_steps=num_steps, window_id=window_id
    )
