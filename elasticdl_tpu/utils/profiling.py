"""XLA profiler window for training runs.

The reference's only tracing is wall-clock buckets at DEBUG level
(``common/timing_utils.py``, kept as ``utils.timing_utils``); on TPU the
tool that actually explains a slow step is the XLA profiler (op-level
device timeline, HLO attribution, TensorBoard ``profile`` plugin).  This
wires it as a step-window capture: ``--profile_dir d --profile_steps N``
traces steps [start, start + N) into ``d`` — viewable with
``tensorboard --logdir d``.
"""

from __future__ import annotations

from elasticdl_tpu.utils.log_utils import default_logger as logger


class StepProfiler:
    """Capture one window of training steps with ``jax.profiler``.

    ``on_step()`` is called once per step by the training loop and counts
    calls SINCE PROCESS START (not the model version — a checkpoint-
    resumed run at version 10000 still warms up before its window); the
    trace starts at call ``start_step`` (past compile + warmup) and stops
    ``num_steps`` later.  Inactive (no output dir) it is one attribute
    lookup per step.
    """

    def __init__(
        self,
        out_dir: str | None,
        start_step: int = 5,
        num_steps: int = 5,
    ):
        self._out_dir = out_dir or ""
        self._start = start_step
        self._stop = start_step + num_steps
        self._seen = 0
        self._tracing = False
        self._done = not self._out_dir
        self._window_span = None

    def on_step(self, _step=None):
        """Count one training step (the argument is accepted and ignored
        for call-site readability)."""
        if self._done:
            return
        self._seen += 1
        if not self._tracing and self._seen > self._start:
            import jax

            jax.profiler.start_trace(self._out_dir)
            self._tracing = True
            # telemetry marker + span so the XLA profiler window can be
            # located on the SAME timeline as the distributed trace
            # (both no-ops when telemetry/tracing is not installed)
            from elasticdl_tpu.telemetry import tracing as _trace
            from elasticdl_tpu.telemetry import worker_hooks
            from elasticdl_tpu.telemetry.events import (
                EVENT_PROFILE_WINDOW_OPEN,
            )

            worker_hooks.emit_event(
                EVENT_PROFILE_WINDOW_OPEN,
                at_call=self._seen,
                out_dir=self._out_dir,
            )
            tracer = _trace.get_tracer()
            if tracer is not None:
                self._window_span = tracer.start_span(
                    _trace.SPAN_PROFILE_WINDOW, out_dir=self._out_dir
                )
            logger.info(
                "XLA profiler: tracing %d steps into %s",
                self._stop - self._start,
                self._out_dir,
            )
        elif self._tracing and self._seen > self._stop:
            self.stop()

    def stop(self):
        """Idempotent; also called at loop exit so a short run still
        flushes a partial window."""
        if self._tracing:
            import jax

            jax.profiler.stop_trace()
            self._tracing = False
            from elasticdl_tpu.telemetry import worker_hooks
            from elasticdl_tpu.telemetry.events import (
                EVENT_PROFILE_WINDOW_CLOSE,
            )

            worker_hooks.emit_event(
                EVENT_PROFILE_WINDOW_CLOSE,
                at_call=self._seen,
                out_dir=self._out_dir,
            )
            if self._window_span is not None:
                self._window_span.end(steps=self._seen - self._start)
                self._window_span = None
            logger.info("XLA profiler: trace written to %s", self._out_dir)
        elif not self._done and self._out_dir:
            logger.warning(
                "XLA profiler: window never opened — the run had %d steps "
                "but tracing starts after step %d (--profile_steps only "
                "sets the window length)",
                self._seen,
                self._start,
            )
        self._done = True
