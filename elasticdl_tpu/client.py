"""The ``elasticdl_tpu`` command-line client.

Reference: ``elasticdl/python/elasticdl/client.py:13-47`` — argparse
subcommands ``train``/``evaluate``/``predict``/``clean`` registered as the
``elasticdl`` console script (setup.py:27-29).  Same surface here:

    elasticdl_tpu train --model_def=mnist_functional_api... \
        --training_data=/data/mnist --num_epochs=2
"""

from __future__ import annotations

import argparse
import sys

from elasticdl_tpu import api
from elasticdl_tpu.utils.args import parse_master_args
from elasticdl_tpu.utils.log_utils import default_logger as logger

COMMANDS = ("train", "evaluate", "predict", "clean")


def _parse_clean_args(argv):
    parser = argparse.ArgumentParser(prog="elasticdl_tpu clean")
    parser.add_argument("--docker_image_repository", default="")
    parser.add_argument("--all", action="store_true")
    return parser.parse_args(argv)


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help"):
        print(
            "usage: elasticdl_tpu {train,evaluate,predict,clean} [options]\n"
            "Run '<command> --help' for command options."
        )
        return 0 if argv else 2
    command, rest = argv[0], argv[1:]
    if command not in COMMANDS:
        logger.error("Unknown command %r; expected one of %s", command, COMMANDS)
        return 2
    if command == "clean":
        result = api.clean(_parse_clean_args(rest))
    else:
        # make JAX_PLATFORMS authoritative BEFORE any backend
        # initializes: platform plugins may register and initialize
        # regardless of the env var (a tunneled TPU plugin does — and
        # when its link is down, that initialization HANGS a job that
        # asked for cpu).  --jax_platform still overrides later via the
        # same configure_platform call.  Gated to the compute commands
        # so clean/--help stay jax-free.
        import os

        if os.environ.get("JAX_PLATFORMS"):
            from elasticdl_tpu.parallel.elastic import configure_platform

            configure_platform(os.environ["JAX_PLATFORMS"])
        args = parse_master_args(rest)
        result = getattr(api, command)(args)
    if result:
        logger.info("%s result: %s", command, result)
    return 0


if __name__ == "__main__":
    sys.exit(main())
