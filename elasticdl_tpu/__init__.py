"""ElasticDL-TPU: a TPU-native elastic deep-learning framework.

A from-scratch rebuild of the capabilities of ElasticDL (reference:
``863473007/elasticdl`` — a Kubernetes-native elastic training framework on
TF2 eager + gRPC parameter servers) re-designed for TPU hardware:

- the worker compute plane is a ``jax.jit``-compiled SPMD train step over a
  ``jax.sharding.Mesh`` (data / tensor / sequence / expert axes) instead of a
  TF2 eager GradientTape loop;
- the gRPC parameter server is eliminated for dense parameters (gradient
  exchange is an XLA ``psum`` over ICI) and replaced for sparse embeddings by
  mesh-sharded tables with in-step all-to-all lookup;
- elasticity (dynamic data sharding + pod relaunch in the reference) becomes
  dynamic data sharding + JAX mesh re-formation driven by the master.

Package layout:

- ``elasticdl_tpu.utils``    — flags, constants, logging, hashing, serde
  (reference: ``elasticdl/python/common/``)
- ``elasticdl_tpu.master``   — control plane: task dispatcher, servicer,
  evaluation service, instance manager (reference: ``elasticdl/python/master/``)
- ``elasticdl_tpu.worker``   — compute plane: JAX worker loop, task data
  service (reference: ``elasticdl/python/worker/``)
- ``elasticdl_tpu.trainer``  — jitted step builders, train state, metrics,
  local executor (reference: ``elasticdl/python/elasticdl/local_executor.py``)
- ``elasticdl_tpu.parallel`` — mesh, sharding rules, collectives, sharded
  embedding engine, ring attention (replaces PS + FTLib, reference §2.3/§2.8)
- ``elasticdl_tpu.layers``   — model-building layers incl. the distributed
  ``Embedding`` (reference: ``elasticdl/python/elasticdl/layers/``)
- ``elasticdl_tpu.data``     — readers, RecordIO codec, dataset pipeline
  (reference: ``elasticdl/python/data/``)
- ``elasticdl_tpu.models``   — the model zoo (reference: ``model_zoo/``)
- ``elasticdl_tpu.ops``      — Pallas TPU kernels for hot ops
- ``elasticdl_tpu.rpc``      — gRPC control-plane transport + wire serde
  (reference: ``elasticdl/proto/elasticdl.proto``)
"""

__version__ = "0.1.0"
