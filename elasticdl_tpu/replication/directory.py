"""Master-side replica directory + reform-time harvest.

The directory is the master's view of where replica shards live: every
worker heartbeat carries its replica-server address and current
holdings (:meth:`~.replicator.PeerReplicator.advertisement`), and the
directory answers two questions:

- ``peers(generation)`` — the process->addr map heartbeat RESPONSES
  carry back down, so ring pushers discover their neighbors with no new
  RPC;
- ``harvest(...)`` — on re-formation, fetch the freshest COMPLETE
  replica set out of the survivors' RAM and merge it into one staged
  restore payload.

Harvest trusts FETCHED metadata, not advertised holdings: heartbeats
lag by their interval, and the whole point is recovering a push that
landed milliseconds before the preemption.  Advertised holdings feed
the coverage stats surfaced in ``telemetry.report`` and
``chaos_result.json`` instead.

Generation fencing, like everything else: holdings and shards are
tagged with the world generation they were produced in; a harvest for
generation ``g+1`` only accepts shards of generation ``g``, and the
staged payload is only served to workers presenting ``g+1``.
"""

from __future__ import annotations

import threading

from elasticdl_tpu.replication.blob import (
    blob_checksum,
    decode_snapshot,
    encode_snapshot,
    merge_snapshots,
)
from elasticdl_tpu.rpc import messages as msg
from elasticdl_tpu.utils.log_utils import default_logger as logger

FETCH_TIMEOUT_SECS = 30.0


class ReplicaDirectory:
    def __init__(self, deadlines=None):
        # the job-wide DeadlinePolicy (rpc/deadline.py): harvest probes
        # and fetches are state transfer, so its transfer tier replaces
        # the fixed FETCH_TIMEOUT_SECS when the master configured
        # --rpc_deadline_secs; None keeps the historical constant
        self._deadlines = deadlines
        self._fetch_timeout = (
            deadlines.transfer_secs
            if deadlines is not None
            else FETCH_TIMEOUT_SECS
        )
        self._lock = threading.Lock()
        # worker_id -> latest advertisement ({"addr", "process_id",
        # "generation", "holdings"}); written by heartbeat handler
        # threads, read by the reform harvest
        self._ads: dict[int, dict] = {}  # guarded-by: _lock
        # generation -> pushes observed (holdings version advances)
        self._pushes_by_generation: dict[int, int] = {}  # guarded-by: _lock
        self._last_versions: dict[tuple[int, int], int] = {}  # guarded-by: _lock
        self.harvests = 0
        self.harvest_failures = 0

    # ---- heartbeat plumbing ------------------------------------------------

    def update(self, worker_id: int, replica: dict):
        if not replica or "addr" not in replica:
            return
        with self._lock:
            self._ads[worker_id] = dict(replica)
            generation = int(replica.get("generation", 0))
            for holding in replica.get("holdings", ()):  # push counting:
                # a holding whose version advanced since the last
                # advertisement is one completed push/commit
                key = (int(holding.get("source", -1)), generation)
                version = int(holding.get("version", -1))
                if version > self._last_versions.get(key, -1):
                    self._last_versions[key] = version
                    self._pushes_by_generation[generation] = (
                        self._pushes_by_generation.get(generation, 0) + 1
                    )

    def forget_worker(self, worker_id: int):
        with self._lock:
            self._ads.pop(worker_id, None)

    def peers(self, generation: int) -> dict[str, str]:
        """process_id -> replica addr for advertisements of this
        generation (what heartbeat responses carry to ring pushers).
        Keys are STRINGS: msgpack decode rejects int map keys
        (strict_map_key), and the dict rides a HeartbeatResponse."""
        with self._lock:
            return {
                str(int(ad["process_id"])): ad["addr"]
                for ad in self._ads.values()
                if int(ad.get("generation", -1)) == generation
            }

    # ---- observability -----------------------------------------------------

    def coverage_stats(self) -> dict:
        """Replica coverage as advertised: hosts covered per generation,
        shard versions held, pushes observed — embedded in
        ``telemetry.report`` and ``chaos_result.json``."""
        with self._lock:
            by_gen: dict[int, dict] = {}
            for ad in self._ads.values():
                generation = int(ad.get("generation", 0))
                gen = by_gen.setdefault(
                    generation, {"hosts": set(), "shard_versions": {}}
                )
                gen["hosts"].add(int(ad.get("process_id", -1)))
                for holding in ad.get("holdings", ()):  # freshest per source
                    source = int(holding.get("source", -1))
                    version = int(holding.get("version", -1))
                    if version > gen["shard_versions"].get(source, -1):
                        gen["shard_versions"][source] = version
            return {
                "generations": {
                    generation: {
                        "hosts_covered": sorted(gen["hosts"]),
                        "shard_versions": {
                            str(src): v
                            for src, v in sorted(
                                gen["shard_versions"].items()
                            )
                        },
                    }
                    for generation, gen in sorted(by_gen.items())
                },
                "pushes_by_generation": {
                    str(g): n
                    for g, n in sorted(self._pushes_by_generation.items())
                },
                "harvests": self.harvests,
                "harvest_failures": self.harvest_failures,
            }

    # ---- reform-time harvest -----------------------------------------------

    def harvest(
        self,
        live_worker_ids: list[int],
        num_sources: int,
        generation: int,
        staged_for: int,
    ) -> dict | None:
        """Pull the freshest complete replica set from the survivors.

        ``num_sources``: how many process shards compose the state (the
        OLD world size); ``generation``: the world generation the shards
        were produced in; ``staged_for``: the generation that will be
        allowed to restore from the result.  Returns a stage dict
        ``{"generation", "version", "checksum", "payload", "sources"}``
        or None when no complete verified set exists (disk fallback).
        """
        from elasticdl_tpu.replication.service import ReplicaClient

        with self._lock:
            addrs = sorted(
                {
                    ad["addr"]
                    for wid, ad in self._ads.items()
                    if wid in set(live_worker_ids)
                    and int(ad.get("generation", -1)) == generation
                }
            )
        if not addrs:
            self.harvest_failures += 1
            logger.warning(
                "Replica harvest: no live replica servers advertised for "
                "generation %d; falling back to disk",
                generation,
            )
            return None
        clients = []
        try:
            clients = [
                (addr, ReplicaClient(addr, deadlines=self._deadlines))
                for addr in addrs
            ]
            # probe every live server for every source's metadata (ALL
            # retained versions, not just the newest — an older shard
            # may be the only complete set left after a mid-push death),
            # then pick the highest version with COMPLETE coverage
            offers: dict[int, list[tuple[int, object, str]]] = {}
            for addr, client in clients:
                for source in range(num_sources):
                    meta = self._probe(client, source, generation)
                    if meta is None:
                        continue
                    for version in meta.versions or [meta.version]:
                        offers.setdefault(source, []).append(
                            (version, client, addr)
                        )
            version = self._complete_version(offers, num_sources)
            if version is None:
                self.harvest_failures += 1
                logger.warning(
                    "Replica harvest: coverage incomplete for generation "
                    "%d (sources offered: %s of %d); falling back to disk",
                    generation,
                    sorted(offers),
                    num_sources,
                )
                return None
            snapshots = []
            for source in range(num_sources):
                shard = self._fetch(
                    offers[source], source, version, generation
                )
                if shard is None:
                    self.harvest_failures += 1
                    logger.warning(
                        "Replica harvest: shard %d@%d vanished mid-"
                        "harvest; falling back to disk",
                        source,
                        version,
                    )
                    return None
                snapshots.append(decode_snapshot(shard.payload))
        finally:
            for _addr, client in clients:
                client.close()
        dense, parts = merge_snapshots(snapshots)
        payload = encode_snapshot(dense, parts)
        self.harvests += 1
        return {
            "generation": staged_for,
            "version": version,
            "checksum": blob_checksum(payload),
            "payload": payload,
            "sources": num_sources,
        }

    def _probe(self, client, source: int, generation: int):
        try:
            resp = client.fetch_replica(
                msg.FetchReplicaRequest(source=source, probe=True),
                timeout=self._fetch_timeout,
            )
        except Exception as ex:  # noqa: BLE001 — a dying survivor is a
            # missing offer, not a harvest crash
            logger.warning(
                "Replica probe for source %d failed: %s", source, ex
            )
            return None
        if resp is None or not resp.has or resp.generation != generation:
            return None
        return resp

    @staticmethod
    def _complete_version(
        offers: dict[int, list], num_sources: int
    ) -> int | None:
        """Highest version every source has at least one offer for."""
        if set(offers) != set(range(num_sources)):
            return None
        candidates = set.intersection(
            *({v for v, _c, _a in offer} for offer in offers.values())
        )
        return max(candidates) if candidates else None

    def _fetch(self, offer_list, source: int, version: int, generation: int):
        """Fetch-and-verify one shard from any offering holder."""
        for offered_version, client, addr in offer_list:
            if offered_version != version:
                continue
            try:
                resp = client.fetch_replica(
                    msg.FetchReplicaRequest(source=source, version=version),
                    timeout=self._fetch_timeout,
                )
            except Exception:  # noqa: BLE001 — try the next holder
                continue
            if (
                resp is None
                or not resp.has
                or resp.version != version
                or resp.generation != generation
                or blob_checksum(resp.payload) != resp.checksum
            ):
                logger.warning(
                    "Replica harvest: shard %d@%d from %s torn or stale; "
                    "trying another holder",
                    source,
                    version,
                    addr,
                )
                continue
            return resp
        return None
