"""Worker-side replica service: the ring-push receiver + harvest source.

Rides the job's existing RPC transport (``rpc.service`` generic server,
msgpack frames of ``rpc.messages``) under its own service name, so a
replica push is wire-identical in discipline to every other control-
plane call.  The servicer is transport-agnostic like ``MasterServicer``
— unit tests call it directly with zero transport.
"""

from __future__ import annotations

import grpc

from elasticdl_tpu.replication.store import ReplicaShard, ReplicaStore
from elasticdl_tpu.rpc import messages as msg
from elasticdl_tpu.rpc.service import RpcClient, create_server

REPLICA_SERVICE_NAME = "elasticdl_tpu.Replica"

REPLICA_METHODS = (
    "push_replica",
    "fetch_replica",
)


class ReplicaServicer:
    """Serves one process's :class:`ReplicaStore`.

    ``fetch_replica`` answers with whatever the store CURRENTLY holds
    for the requested source — the master's harvest trusts fetched
    metadata, not heartbeat-lagged advertisements, so a push that
    completed milliseconds before a preemption is still harvestable.
    """

    def __init__(self, store: ReplicaStore):
        self._store = store

    @property
    def store(self) -> ReplicaStore:
        return self._store

    def push_replica(
        self, request: msg.PushReplicaRequest
    ) -> msg.PushReplicaResponse:
        accepted, reason = self._store.put(
            ReplicaShard(
                source=request.source,
                version=request.version,
                generation=request.generation,
                checksum=request.checksum,
                payload=request.payload,
            )
        )
        return msg.PushReplicaResponse(accepted=accepted, reason=reason)

    def fetch_replica(
        self, request: msg.FetchReplicaRequest
    ) -> msg.FetchReplicaResponse:
        version = None if request.version < 0 else request.version
        shard = self._store.get(request.source, version=version)
        if shard is None:
            return msg.FetchReplicaResponse(source=request.source)
        return msg.FetchReplicaResponse(
            has=True,
            source=shard.source,
            version=shard.version,
            generation=shard.generation,
            checksum=shard.checksum,
            payload=b"" if request.probe else shard.payload,
            versions=self._store.versions(request.source),
        )


def start_replica_server(
    store: ReplicaStore, port: int = 0
) -> tuple[grpc.Server, int]:
    """Bind a replica server on an ephemeral port; returns
    ``(server, bound_port)``.  Few threads: the only callers are one
    ring neighbor and (during reform) the master's harvester."""
    server = create_server(
        ReplicaServicer(store),
        port,
        max_workers=4,
        methods=REPLICA_METHODS,
        service_name=REPLICA_SERVICE_NAME,
    )
    server.start()
    return server, server._edl_bound_port


class ReplicaClient(RpcClient):
    """Stub for one peer's replica server (ring push / harvest pull).

    ``deadlines`` is the job-wide :class:`~elasticdl_tpu.rpc.deadline.
    DeadlinePolicy` — replica pushes/fetches are state transfer, so the
    policy's transfer tier applies when a caller passes no explicit
    timeout; None keeps the historical fixed-constant behavior."""

    def __init__(self, addr: str, deadlines=None):
        super().__init__(
            addr,
            methods=REPLICA_METHODS,
            service_name=REPLICA_SERVICE_NAME,
            deadlines=deadlines,
        )

    def push_replica(
        self, request: msg.PushReplicaRequest, timeout: float | None = None
    ) -> msg.PushReplicaResponse:
        return self._call("push_replica", request, timeout=timeout)

    def fetch_replica(
        self, request: msg.FetchReplicaRequest, timeout: float | None = None
    ) -> msg.FetchReplicaResponse:
        return self._call("fetch_replica", request, timeout=timeout)
