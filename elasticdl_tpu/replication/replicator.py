"""Worker-side replication: snapshot cadence, ring push, hot restore.

:class:`PeerReplicator` runs inside the lockstep worker at task
boundaries only (the same collective-safety rule as the periodic
checkpointer: every process decides identically from the shared step,
so any gather inside the snapshot lines up).  The snapshot reuses
``elastic.state_checkpoint_parts`` — the chief's shard carries the
replicated dense leaves, every host's shard carries the table rows it
owns — so replication and disk checkpointing can never disagree about
what "this host's share of the state" means.

:func:`restore_from_replica` is the other half: a relaunched process
asks the master for the harvested replica stage of ITS generation and,
when present, re-places the state at the exact step of the last
replication — no disk read on the reform critical path.
"""

from __future__ import annotations

import os

from elasticdl_tpu.parallel import elastic
from elasticdl_tpu.replication.blob import (
    blob_checksum,
    decode_snapshot,
    encode_snapshot,
)
from elasticdl_tpu.replication.service import ReplicaClient
from elasticdl_tpu.replication.store import ReplicaShard, ReplicaStore
from elasticdl_tpu.rpc import messages as msg
from elasticdl_tpu.utils.log_utils import default_logger as logger

# a push is host-RAM to host-RAM over the local network: seconds, not
# minutes — a hung neighbor must not stall the training thread forever
PUSH_TIMEOUT_SECS = 30.0

REPLICA_HOST_ENV = "MY_POD_IP"  # k8s pods advertise their pod IP

# chaos corruption (--corrupt same_slice_ring): force the slice-blind
# (i+1)%n neighbor even on a multi-slice world, so the
# cross_slice_replica_coverage invariant can be proven falsifiable — a
# slice loss then takes a shard and its only replica together
SAME_SLICE_RING_ENV = "ELASTICDL_TPU_CHAOS_SAME_SLICE_RING"

# chaos corruption (--corrupt drop_shard_parts): strip the sharded
# table rows from the pushed blob AFTER the event's has_sharded field
# is computed from the real state, so the sharded replica-coverage
# extension of cross_slice_replica_coverage can be proven falsifiable —
# the push honestly reports "this state HAS sharded rows" while
# carrying none, which is exactly the shape of "a shard's only replica
# died"
DROP_SHARD_PARTS_ENV = "ELASTICDL_TPU_CHAOS_DROP_SHARD_PARTS"


def _parts_row_count(parts) -> int:
    """Total table rows across a snapshot's sharded parts (each part is
    ``name -> (ids, rows)``)."""
    if not parts:
        return 0
    return sum(len(ids) for ids, _ in parts.values())


def replica_host() -> str:
    return os.environ.get(REPLICA_HOST_ENV, "") or "127.0.0.1"


def ring_neighbor(
    process_id: int, num_processes: int, slice_map: list[int] | None = None
) -> int:
    """The ring-push target for ``process_id``.

    Single-slice worlds keep the classic ``(i+1) % n``.  On a
    multi-slice world the neighbor is REPINNED to the next process (in
    ring order) living on a DIFFERENT slice, so at least one copy of
    every shard survives a whole-slice preemption — with the classic
    ring, a slice loss takes state and replicas together whenever two
    ring-adjacent processes share a slice."""
    if num_processes < 2:
        return process_id
    if not slice_map or len(set(slice_map)) <= 1:
        return (process_id + 1) % num_processes
    my_slice = slice_map[process_id]
    for hop in range(1, num_processes):
        candidate = (process_id + hop) % num_processes
        if slice_map[candidate] != my_slice:
            return candidate
    return (process_id + 1) % num_processes


class PeerReplicator:
    def __init__(
        self,
        store: ReplicaStore,
        process_id: int,
        num_processes: int,
        generation: int,
        addr: str,
        replication_steps: int = 0,
        num_slices: int = 1,
        slice_map: list[int] | None = None,
    ):
        self._store = store
        self._process_id = process_id
        self._num_processes = num_processes
        self._generation = generation
        self._addr = addr
        # slice-aware ring.  ``slice_map`` (process -> slice) should be
        # the MESH-derived physical placement (mesh_process_slice_map):
        # on hardware whose slice_index disagrees with the canonical
        # assignment, replicas must land off the PHYSICAL slice or a
        # real preemption takes shard and copy together.  The canonical
        # map is only the fallback (it equals the mesh-derived one on
        # forced/CPU layouts).  The chaos corruption env forces the
        # slice-blind classic ring so the coverage invariant is
        # falsifiable.
        from elasticdl_tpu.parallel.mesh import slice_assignments

        if slice_map is not None and len(slice_map) == num_processes:
            self._slice_map = list(slice_map)
        elif num_slices > 1:
            self._slice_map = slice_assignments(num_processes, num_slices)
        else:
            self._slice_map = []
        if len(set(self._slice_map)) <= 1:
            self._slice_map = []
        self._slice_id = (
            self._slice_map[process_id] if self._slice_map else 0
        )
        self._same_slice_ring = bool(
            os.environ.get(SAME_SLICE_RING_ENV, "")
        )
        # the job-wide deadline policy (rpc/deadline.py, env-forwarded
        # like the retry budget): pushes are state transfer, so the
        # transfer tier replaces the fixed PUSH_TIMEOUT_SECS when a
        # policy is configured — one object, no second timeout story
        from elasticdl_tpu.rpc.deadline import DeadlinePolicy

        self._deadlines = DeadlinePolicy.from_env()
        self._push_timeout = (
            self._deadlines.transfer_secs
            if self._deadlines is not None
            else PUSH_TIMEOUT_SECS
        )
        # 0 = replicate at EVERY task boundary (the default cadence);
        # N > 0 = milestone-crossing every N steps, like the checkpointer
        self._steps = max(0, int(replication_steps or 0))
        self._last_milestone = 0
        self._last_version = -1
        # process_id -> replica addr, learned from heartbeat responses
        # (written by the heartbeat thread, read at task boundaries)
        self._peers: dict[int, str] = {}
        self._client: ReplicaClient | None = None
        self._client_addr = ""
        self.pushes = 0
        self.push_failures = 0

    @property
    def neighbor(self) -> int:
        if self._same_slice_ring:
            # corruption mode: the pre-slice-aware ring, kept ONLY so
            # --corrupt same_slice_ring can prove the coverage checker
            # trips when a replica lands on its owner's slice
            return (self._process_id + 1) % self._num_processes
        return ring_neighbor(
            self._process_id, self._num_processes, self._slice_map
        )

    def _slice_of(self, process_id: int) -> int:
        return (
            self._slice_map[process_id]
            if self._slice_map and 0 <= process_id < len(self._slice_map)
            else 0
        )

    # ---- peer discovery (heartbeat thread) ---------------------------------

    def advertisement(self) -> dict:
        """The ``replica`` field of every heartbeat: where this process
        serves shards and what its RAM holds right now."""
        return {
            "addr": self._addr,
            "process_id": self._process_id,
            "slice_id": self._slice_id,
            "generation": self._generation,
            "holdings": self._store.holdings(),
        }

    def set_peers(self, peers: dict):
        if peers:
            self._peers = {int(k): v for k, v in peers.items()}

    # ---- replication cadence (training thread, task boundaries) ------------

    def note_restored_version(self, version: int):
        if self._steps:
            self._last_milestone = version // self._steps
        self._last_version = version

    def maybe_replicate(self, trainer, mesh) -> bool:
        """Replicate if due.  Call at task boundaries on EVERY process —
        the decision is a pure function of the shared step, and the
        snapshot may contain a gather collective."""
        if trainer is None:
            return False
        version = int(trainer.step)
        if self._steps:
            milestone = version // self._steps
            if milestone <= self._last_milestone:
                return False
            self._last_milestone = milestone
        elif version <= self._last_version:
            return False
        self.replicate_now(trainer, mesh)
        return True

    def replicate_now(self, trainer, mesh):
        from elasticdl_tpu.telemetry import worker_hooks as telemetry_hooks
        from elasticdl_tpu.telemetry.events import EVENT_REPLICA_PUSH
        from elasticdl_tpu.telemetry.tracing import (
            SPAN_REPLICA_PUSH,
            trace_span,
        )

        version = int(trainer.step)
        self._last_version = version
        with trace_span(
            SPAN_REPLICA_PUSH, step=version, target=self.neighbor
        ):
            # same dense/parts split as the disk checkpoint: the chief's
            # shard carries replicated leaves, every shard its own rows
            dense, parts = elastic.state_checkpoint_parts(
                trainer.state, mesh, materialize_dense=self._process_id == 0
            )
            # sharded-coverage bookkeeping BEFORE any corruption: the
            # event must report what the STATE has, the blob what the
            # push actually carried — the gap is what the chaos
            # invariant audits
            has_sharded = bool(parts)
            sharded_tables = len(parts)
            sharded_rows = _parts_row_count(parts)
            if has_sharded and os.environ.get(DROP_SHARD_PARTS_ENV, ""):
                parts = {}
                sharded_rows = 0
            blob = encode_snapshot(dense, parts)
            shard = ReplicaShard(
                source=self._process_id,
                version=version,
                generation=self._generation,
                checksum=blob_checksum(blob),
                payload=blob,
            )
            # local commit FIRST: this process is a harvest source for
            # its own shard even if the neighbor push below fails
            self._store.put(shard)
            # chaos hook: a KILL_DURING_REPLICATION fault dies HERE —
            # after the local snapshot, before the neighbor holds the new
            # version — so harvest must detect the incomplete coverage
            # and fall back to an older complete set (or to disk)
            from elasticdl_tpu.chaos import hooks as chaos_hooks

            chaos_hooks.notify_replica_push(version)
            ok = self._push(shard)
        telemetry_hooks.emit_event(
            EVENT_REPLICA_PUSH,
            step=version,
            source=self._process_id,
            target=self.neighbor,
            # slice placement of the push: what the multi-slice chaos
            # invariant (cross_slice_replica_coverage) audits — on a
            # multi-slice world a shard's ring replica must live on a
            # DIFFERENT slice than its owner
            source_slice=self._slice_id,
            target_slice=self._slice_of(self.neighbor),
            num_slices=len(set(self._slice_map)) if self._slice_map else 1,
            ok=bool(ok),
            # sharded-table coverage: has_sharded reflects the live
            # state, sharded_rows what the push carried — a push with
            # has_sharded and zero rows is a shard whose replica
            # carries no table coverage (the corrupt-mode signature)
            has_sharded=has_sharded,
            sharded_tables=sharded_tables,
            sharded_rows=sharded_rows,
        )

    def _push(self, shard: ReplicaShard) -> bool:
        if self._num_processes < 2:
            return False
        addr = self._peers.get(self.neighbor, "")
        if not addr:
            # peers not discovered yet (first heartbeat round-trip still
            # in flight); the local commit above keeps this version
            # harvestable from ONE host in the meantime
            self.push_failures += 1
            return False
        try:
            if self._client is None or self._client_addr != addr:
                if self._client is not None:
                    self._client.close()
                self._client = ReplicaClient(
                    addr, deadlines=self._deadlines
                )
                self._client_addr = addr
            resp = self._client.push_replica(
                msg.PushReplicaRequest(
                    source=shard.source,
                    version=shard.version,
                    generation=shard.generation,
                    checksum=shard.checksum,
                    payload=shard.payload,
                ),
                timeout=self._push_timeout,
            )
            accepted = bool(resp is not None and resp.accepted)
        except Exception as ex:  # noqa: BLE001 — a dead neighbor must
            # not crash the pusher; the master's failure detection owns
            # declaring it dead
            logger.warning(
                "Replica push to process %d (%s) failed: %s",
                self.neighbor,
                addr,
                ex,
            )
            accepted = False
        if accepted:
            self.pushes += 1
        else:
            self.push_failures += 1
        return accepted

    def stats(self) -> dict:
        return {
            "pushes": self.pushes,
            "push_failures": self.push_failures,
            "rejected": self._store.rejected,
        }

    def close(self):
        if self._client is not None:
            self._client.close()
            self._client = None


def restore_from_replica(
    trainer,
    master,
    cluster_version: int,
    process_id: int = 0,
    min_version: int | None = None,
) -> int | None:
    """Restore the trainer from the master's harvested replica stage.

    Returns the restored step, or None when no stage exists for this
    generation (caller falls back to the disk path).  Every process of
    the generation sees the same answer — the stage is set before the
    relaunch and fenced by ``cluster_version`` — so the restore-source
    decision is identical everywhere (the lockstep invariant).

    ``min_version``: the newest DISK milestone available (the caller's
    fallback).  A staged replica older than it is declined — possible
    only when ``replication_steps`` is coarser than ``checkpoint_steps``
    — so the replica path can never lose work relative to disk.  The
    floor is read from the shared checkpoint directory, so every
    process computes the same one.
    """
    try:
        resp = master.get_restore_state(
            msg.GetRestoreStateRequest(
                cluster_version=cluster_version, process_id=process_id
            )
        )
    except Exception as ex:  # noqa: BLE001 — an old master without the
        # RPC (rolling upgrade) must degrade to the disk path, not crash
        logger.warning("Replica restore-state query failed: %s", ex)
        return None
    if resp is None or not resp.has:
        return None
    if min_version is not None and int(resp.version) < min_version:
        logger.warning(
            "Replica stage at version %d is older than the disk "
            "milestone %d; restoring from disk instead",
            int(resp.version),
            min_version,
        )
        return None
    if blob_checksum(resp.payload) != resp.checksum:
        logger.warning(
            "Replica restore stage failed checksum; falling back to disk"
        )
        return None
    from elasticdl_tpu.telemetry import worker_hooks as telemetry_hooks
    from elasticdl_tpu.telemetry.events import EVENT_REPLICA_RESTORE
    from elasticdl_tpu.telemetry.tracing import (
        SPAN_REPLICA_RESTORE,
        trace_span,
    )
    from elasticdl_tpu.trainer.checkpointing import apply_restored_values

    version = int(resp.version)
    # reform-phase span: on a replica-served reform this REPLACES the
    # checkpoint_restore_state disk read in the downtime critical path
    with trace_span(SPAN_REPLICA_RESTORE, step=version):
        dense, parts = decode_snapshot(resp.payload)
        apply_restored_values(trainer, dense, parts, version)
    from elasticdl_tpu.chaos import hooks as chaos_hooks

    chaos_hooks.notify_replica_restore(version)
    telemetry_hooks.emit_event(
        EVENT_REPLICA_RESTORE,
        step=version,
        # sharded coverage actually APPLIED: replication_no_lost_steps
        # requires pushed sharded rows to come back as restored sharded
        # rows, not merely as a restore event
        sharded_rows=_parts_row_count(parts),
        sharded_tables=len(parts) if parts else 0,
    )
    logger.info(
        "Process %d restored state at version %d from peer replica "
        "(generation %d)",
        process_id,
        version,
        cluster_version,
    )
    return version
