"""State-shard wire format: one host's checkpoint split as bytes.

A shard is the ``(dense, parts)`` pair ``elastic.state_checkpoint_parts``
produces — ``dense``: name -> full array (chief only), ``parts``:
table name -> ``(ids, rows)`` for the rows this host owns.  Encoding is
msgpack with raw array bytes (dtype + shape + C-contiguous data), the
same zero-dependency discipline as :mod:`elasticdl_tpu.rpc.messages`.

Torn-transfer detection: a shard travels with its CRC32
(:func:`blob_checksum`); receivers (the peer store on push, the master
on harvest, the worker on restore) verify before committing, so a
truncated or bit-flipped payload is detected and skipped rather than
restored.
"""

from __future__ import annotations

import zlib

import msgpack
import numpy as np


def _pack_array(arr: np.ndarray) -> dict:
    arr = np.ascontiguousarray(arr)
    return {
        "dtype": arr.dtype.str,
        "shape": list(arr.shape),
        "data": arr.tobytes(),
    }


def _unpack_array(raw: dict) -> np.ndarray:
    return np.frombuffer(
        raw["data"], dtype=np.dtype(raw["dtype"])
    ).reshape(raw["shape"])


def encode_snapshot(dense: dict, parts: dict) -> bytes:
    """Serialize one host's state shard to bytes."""
    return msgpack.packb(
        {
            "dense": {k: _pack_array(v) for k, v in dense.items()},
            "parts": {
                k: {"ids": _pack_array(ids), "rows": _pack_array(rows)}
                for k, (ids, rows) in parts.items()
            },
        },
        use_bin_type=True,
    )


def decode_snapshot(blob: bytes) -> tuple[dict, dict]:
    """Inverse of :func:`encode_snapshot`."""
    raw = msgpack.unpackb(blob, raw=False)
    dense = {k: _unpack_array(v) for k, v in raw["dense"].items()}
    parts = {
        k: (_unpack_array(v["ids"]), _unpack_array(v["rows"]))
        for k, v in raw["parts"].items()
    }
    return dense, parts


def blob_checksum(blob: bytes) -> str:
    """CRC32 as 8 hex chars — cheap enough for every push, strong enough
    to catch truncation and torn writes (not an integrity MAC)."""
    return f"{zlib.crc32(blob) & 0xFFFFFFFF:08x}"


def merge_snapshots(snapshots: list[tuple[dict, dict]]) -> tuple[dict, dict]:
    """Union per-host shards into one full checkpoint view.

    Dense leaves are replicated, so shards either agree or only one
    (the chief's) carries them — last writer wins.  Table parts carry
    disjoint row ranges per owning host (the writer-election in
    ``elastic._owned_row_ranges``), so same-name parts concatenate.
    """
    dense: dict = {}
    ids_acc: dict[str, list[np.ndarray]] = {}
    rows_acc: dict[str, list[np.ndarray]] = {}
    for shard_dense, shard_parts in snapshots:
        dense.update(shard_dense)
        for name, (ids, rows) in shard_parts.items():
            ids_acc.setdefault(name, []).append(ids)
            rows_acc.setdefault(name, []).append(rows)
    parts = {
        name: (
            np.concatenate(ids_acc[name]),
            np.concatenate(rows_acc[name], axis=0),
        )
        for name in ids_acc
    }
    return dense, parts
