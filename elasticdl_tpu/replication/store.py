"""In-RAM replica shard store — what a worker's replica server serves.

One store per lockstep process, holding the latest verified shard per
SOURCE process: its own snapshot (committed locally at replication
time) plus whatever ring neighbors pushed.  Commits are atomic under a
lock and gated on checksum + generation, so a torn push (the sender
SIGKILL'd mid-transfer, a truncated payload) can never shadow the last
good version — the freshest COMPLETE set is always servable.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass

from elasticdl_tpu.replication.blob import blob_checksum
from elasticdl_tpu.utils.log_utils import default_logger as logger


@dataclass(frozen=True)
class ReplicaShard:
    """One host's encoded state shard at one (version, generation)."""

    source: int
    version: int
    generation: int
    checksum: str
    payload: bytes


class ReplicaStore:
    """Holds the ``KEEP_VERSIONS`` newest verified shards per source.

    Keeping more than one version matters: a host commits its own new
    snapshot BEFORE the neighbor acknowledges the push, so with a
    keep-latest-only store a death in that window would destroy the last
    COMPLETE replica set (own shard already at v_new, peer's copy still
    v_old) and force a disk fallback.  With two versions retained, the
    harvest can still assemble the older complete set.
    """

    KEEP_VERSIONS = 2

    def __init__(self, generation: int = 0):
        self._generation = generation
        # source -> {version -> shard}, at most KEEP_VERSIONS newest
        self._shards: dict[int, dict[int, ReplicaShard]] = {}  # guarded-by: _lock
        self._lock = threading.Lock()
        # torn / stale pushes refused (observability); unlocked reads by
        # report/invariant code are fine, increments take the lock —
        # += on a shared int is load/add/store, not atomic
        self.rejected = 0  # guarded-by: _lock (writes)
        # memory-ledger accounting: the two-versions-per-source
        # retention is exactly the kind of silent resident set that
        # walks a host into OOM under elasticity
        from elasticdl_tpu.telemetry import memory as memory_mod

        self._ledger_cb = self.nbytes
        memory_mod.register_component(
            memory_mod.COMPONENT_REPLICA_STORE, self._ledger_cb
        )

    def nbytes(self) -> int:
        """Total retained shard payload bytes (all sources, all
        versions) — the memory ledger's accounting callback."""
        with self._lock:
            return sum(
                len(shard.payload)
                for held in self._shards.values()
                for shard in held.values()
            )

    def close(self):
        """Drop the ledger callback so a discarded store's retained
        payloads (two versions per source of model-sized blobs) are not
        pinned by the component registry.  Identity-guarded: a newer
        store registered under the same name stays live.  Worker
        processes die with their store (SIGKILL), but the in-process
        harnesses and tests build several stores per process."""
        from elasticdl_tpu.telemetry import memory as memory_mod

        memory_mod.unregister_component(
            memory_mod.COMPONENT_REPLICA_STORE, self._ledger_cb
        )

    @property
    def generation(self) -> int:
        return self._generation

    def put(self, shard: ReplicaShard) -> tuple[bool, str]:
        """Commit a shard; returns ``(accepted, reason)``.

        Refuses: checksum mismatch (torn transfer), a generation other
        than this store's world (stale pusher after a re-formation), and
        duplicates / versions older than everything retained (a late
        copy must not evict a fresher shard).
        """
        if blob_checksum(shard.payload) != shard.checksum:
            with self._lock:
                self.rejected += 1
            logger.warning(
                "Replica shard source=%d version=%d refused: checksum "
                "mismatch (torn transfer)",
                shard.source,
                shard.version,
            )
            return False, "checksum_mismatch"
        if shard.generation != self._generation:
            with self._lock:
                self.rejected += 1
            return False, "generation_mismatch"
        with self._lock:
            held = self._shards.setdefault(shard.source, {})
            if shard.version in held or (
                len(held) >= self.KEEP_VERSIONS
                and shard.version < min(held)
            ):
                self.rejected += 1
                return False, "stale_version"
            held[shard.version] = shard
            while len(held) > self.KEEP_VERSIONS:
                del held[min(held)]
        return True, ""

    def get(
        self, source: int, version: int | None = None
    ) -> ReplicaShard | None:
        """The newest shard for ``source``, or the exact ``version``."""
        with self._lock:
            held = self._shards.get(source)
            if not held:
                return None
            if version is None:
                return held[max(held)]
            return held.get(version)

    def versions(self, source: int) -> list[int]:
        with self._lock:
            return sorted(self._shards.get(source, ()))

    def holdings(self) -> list[dict]:
        """Metadata of the newest shard per source (the heartbeat
        advertisement; harvest reads full version sets via probe)."""
        with self._lock:
            out = []
            for held in self._shards.values():
                shard = held[max(held)]
                out.append(
                    {
                        "source": shard.source,
                        "version": shard.version,
                        "generation": shard.generation,
                        "checksum": shard.checksum,
                    }
                )
            return out
