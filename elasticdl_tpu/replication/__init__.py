"""Peer state replication: in-memory hot restore for re-formed worlds.

Every ``replication_steps`` model versions (default: every task
boundary) each lockstep process snapshots its share of the trainer
state host-side — the SAME split ``elastic.state_checkpoint_parts``
uses for disk checkpoints (replicated leaves from the chief's local
replica, vocab-sharded table rows per owning host) — keeps the
snapshot in its own RAM (:mod:`.store`) and pushes it to its ring
neighbor ``(i + 1) % n`` over the job's RPC transport (:mod:`.service`),
so every piece of state lives in at least two hosts' RAM.

On re-formation the master harvests the freshest COMPLETE replica set
from the survivors' stores (:mod:`.directory`), stages the merged state
in its own RAM, and the relaunched generation restores from that stage
(:func:`.replicator.restore_from_replica`) at the exact step of the
last replication — reform downtime no longer pays a disk read, and the
lost-work window shrinks from ``checkpoint_steps`` to
``replication_steps``.  Disk checkpoints remain the durable fallback:
incomplete coverage (adjacent hosts lost, torn pushes, a cold master)
falls back to ``trainer.checkpointing.restore_trainer_state``
unchanged.

Design doc: ``docs/designs/replication.md``.
"""
