"""The SPMD training engine: sharded state + compiled collective step.

This is the TPU-native replacement for the reference's whole data plane:

- ``pull_variable``/``push_gradient`` gRPC fan-out (worker.py:295-530) →
  nothing: parameters live on device, sharded or replicated per the rules;
  gradient reduction is a psum XLA inserts from the shardings.
- PS-side optimizer apply (ps/servicer.py:107-188) → ``optax`` update
  inside the same jitted step.
- FTLib allreduce (collective_ops/communicator.py) → the same psum.

One ``SPMDTrainer`` instance per worker process; the same code runs on a
1-device Local mesh and a multi-host pod slice.
"""

from __future__ import annotations

from typing import Callable, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding

from elasticdl_tpu.ops.attention import attention_mesh_scope
from elasticdl_tpu.parallel import elastic
from elasticdl_tpu.parallel import sharding as sharding_lib
from elasticdl_tpu.parallel.mesh import batch_divisor
from elasticdl_tpu.trainer.state import TrainState
from elasticdl_tpu.trainer.step import (
    build_eval_step,
    build_predict_step,
    build_train_step,
)
from elasticdl_tpu.utils.constants import EMBEDDING_AUTO_DISTRIBUTE_BYTES

# Layout-invariant RNG: state is *created* sharded (init jitted with
# out_shardings below), and with non-partitionable threefry (the JAX
# 0.4.x default) the partitioner does NOT preserve random bits across
# layouts — the same seed then inits different weights on dp=2,tp=2
# than on one device, breaking mesh-parity tests and cross-topology
# reproducibility.  Partitionable threefry makes random bits a pure
# function of (key, position), independent of the mesh.
jax.config.update("jax_threefry_partitionable", True)


class SPMDTrainer:
    def __init__(
        self,
        mesh: Mesh,
        model,
        loss_fn: Callable,
        tx,
        sample_features,
        rules: Sequence[sharding_lib.Rule] = (),
        compute_dtype=None,
        remat: bool = False,
        donate: bool = True,
        rng_seed: int = 0,
        embedding_threshold: int | None = EMBEDDING_AUTO_DISTRIBUTE_BYTES,
        device_parse: Callable | None = None,
        donate_batch: bool = False,
    ):
        """``embedding_threshold``: tables bigger than this many bytes are
        auto-distributed over the mesh (the reference's 2MB model-handler
        policy); pass ``None`` when a ModelHandler supplies the rules
        explicitly, so the policy has exactly one owner.

        ``donate_batch`` (``--device_prefetch``): batch/mask buffers are
        donated to the train-step dispatch alongside the state — a
        placed batch is consumed by its dispatch and must never be
        re-read (the device-pipeline staging layer enforces single-take
        ownership).  Lockstep worlds must agree on this setting: it is
        part of the compiled program, and the enabling env is
        master-forwarded so they always do."""
        self.mesh = mesh

        sample_features = _host_slice_for_init(sample_features)

        def create_state():
            init_features = (
                device_parse(sample_features)
                if device_parse is not None
                else sample_features
            )
            variables = model.init(
                jax.random.PRNGKey(rng_seed), init_features, training=False
            )
            params = variables.get("params", {})
            model_state = {
                k: v for k, v in variables.items() if k != "params"
            }
            return TrainState.create(model.apply, params, tx, model_state)

        # Shapes first (no FLOPs), then shard-aware materialization: the
        # state is *created* already laid out over the mesh, so no host
        # copy of a model bigger than one host's RAM is ever needed.
        state_shapes = jax.eval_shape(create_state)
        if embedding_threshold is not None:
            from elasticdl_tpu.layers.embedding import auto_partition_rules

            rules = tuple(rules) + tuple(
                auto_partition_rules(
                    state_shapes.params, mesh, embedding_threshold
                )
            )
        self.state_specs = sharding_lib.infer_param_specs(
            state_shapes, mesh, rules
        )
        self.state_shardings = sharding_lib.specs_to_shardings(
            self.state_specs, mesh
        )
        with mesh, attention_mesh_scope(mesh):
            self.state = jax.jit(
                create_state, out_shardings=self.state_shardings
            )()
        self._batch_shardings_cache: dict = {}
        self._stacked_scan_cache: dict = {}
        # mesh topology is immutable for this trainer's lifetime: resolve
        # the multi-process layout once, not per minibatch
        self._multiprocess = elastic.is_multiprocess_mesh(mesh)
        self._process_index = (
            elastic.my_process_index(mesh) if self._multiprocess else 0
        )
        self._local_range_cache: dict = {}

        # the SAME builders LocalExecutor uses (trainer/step.py) — the only
        # SPMD addition is pinning the updated state to the mesh layout
        self._donate_batch = bool(donate_batch)
        self._train_step = build_train_step(
            loss_fn,
            compute_dtype=compute_dtype,
            remat=remat,
            donate=donate,
            state_shardings=self.state_shardings,
            device_parse=device_parse,
            donate_batch=self._donate_batch,
        )
        self._eval_step = build_eval_step(loss_fn, device_parse=device_parse)
        self._predict_step = build_predict_step(device_parse=device_parse)

    # ---- batch placement --------------------------------------------------

    def _batch_sharding(self, ndim: int) -> NamedSharding:
        if ndim not in self._batch_shardings_cache:
            # a mesh with sp > 1 means the user chose sequence
            # parallelism: dim 1 of every rank>=2 batch array is the
            # sequence dim (the framework layout convention) and shards
            # over sp; batch_sharding ignores sp_dim on sp=1 meshes
            self._batch_shardings_cache[ndim] = sharding_lib.batch_sharding(
                self.mesh, ndim, sp_dim=1 if ndim >= 2 else None
            )
        return self._batch_shardings_cache[ndim]

    def place_batch(self, tree):
        """Shard a host-global batch over the mesh's data axes.

        Single-process: a plain sharded device_put.  Multi-process mesh:
        every process passes the SAME host-global batch; each contributes
        the rows its devices own — no cross-host copy, and the global
        Array equals the host batch.  Row-range lookups are memoized per
        shape (pure functions of the immutable mesh/sharding).
        """

        def _place(x):
            x = np.asarray(x)
            sh = self._batch_sharding(x.ndim)
            if not self._multiprocess:
                return jax.device_put(x, sh)
            cached = self._local_range_cache.get(x.shape)
            if cached is None:
                if elastic.dim0_split_only(sh, x.shape):
                    cached = elastic.local_batch_ranges(
                        sh, x.shape, self._process_index
                    )
                else:
                    cached = ()  # e.g. sp spans processes: split on dim 1+
                self._local_range_cache[x.shape] = cached
            if not cached:
                # universal path: every process holds the full host batch
                # (lockstep reads whole tasks), each device slices its
                # block — correct for ANY sharding layout
                return jax.make_array_from_callback(
                    x.shape, sh, lambda idx: x[idx]
                )
            local = np.concatenate([x[lo:hi] for lo, hi in cached], axis=0)
            return jax.make_array_from_process_local_data(
                sh, local, global_shape=x.shape
            )

        return jax.tree_util.tree_map(_place, tree)

    def pad_batch(self, tree):
        """Pad the batch's leading dim up to a multiple of the data-axis
        size (XLA needs equal shards; padded rows get zero loss weight is
        the caller's concern — the worker pads only the final partial
        batch of a task)."""
        div = batch_divisor(self.mesh)

        def _pad(x):
            x = np.asarray(x)
            rem = x.shape[0] % div
            if rem == 0:
                return x
            pad = div - rem
            return np.concatenate([x, np.repeat(x[-1:], pad, axis=0)], axis=0)

        return jax.tree_util.tree_map(_pad, tree), div

    def place_padded(self, tree):
        """pad_batch + place_batch — the legacy minimal-padding feed for
        host batches whose leading dim may not divide the data axes.
        The runtimes' hot paths use :meth:`pad_to` + :meth:`row_mask`
        instead (shape-canonical batching: ONE program shape per step
        kind, padded rows exactly zero-weighted)."""
        padded, _ = self.pad_batch(tree)
        return self.place_batch(padded)

    # ---- shape-canonical batching ------------------------------------------
    # THE canonical row count itself is a pure function of static config
    # (stacking.canonical_batch_rows over the mesh's batch divisor) —
    # the runtimes compute it at build time, before this trainer exists.

    def pad_to(self, tree, rows: int):
        """Pad the batch's leading dim to EXACTLY ``rows`` (repeating the
        last row; padded rows carry zero weight via :meth:`row_mask`, so
        the fill only has to be shape/dtype-valid, not meaningful)."""

        def _pad(x):
            x = np.asarray(x)
            n = x.shape[0]
            if n == rows:
                return x
            if n > rows:
                raise ValueError(
                    f"batch of {n} rows exceeds the canonical shape "
                    f"({rows} rows)"
                )
            return np.concatenate(
                [x, np.repeat(x[-1:], rows - n, axis=0)], axis=0
            )

        return jax.tree_util.tree_map(_pad, tree)

    def row_mask(self, n_real: int, rows: int) -> np.ndarray:
        """``(rows,)`` float32 sample weights: 1 for the real rows, 0 for
        the padding :meth:`pad_to` appended."""
        mask = np.zeros(rows, np.float32)
        mask[:n_real] = 1.0
        return mask

    def place_canonical(self, tree, rows: int):
        """pad_to + place_batch — THE canonical-shape feed all three
        runtimes use (one body, so their dispatch shapes cannot
        diverge); outputs are trimmed back by :func:`trim_pad`, and the
        loss side carries :meth:`place_mask` weights so the padding is
        weightless."""
        return self.place_batch(self.pad_to(tree, rows))

    def place_mask(self, n_real: int, rows: int):
        """:meth:`row_mask` placed like any 1-D batch leaf."""
        return self.place_batch(self.row_mask(n_real, rows))

    # ---- steps ------------------------------------------------------------

    @property
    def state(self):
        return self._state

    @state.setter
    def state(self, value):
        # external assignment (checkpoint restore, re-formation): the
        # host step mirror is unknown until read
        self._state = value
        self._step_cache = None

    def train_step(self, features, labels, weights=None):
        with self.mesh, attention_mesh_scope(self.mesh):
            self._state, metrics = self._train_step(
                self._state, features, labels, weights
            )
        if self._step_cache is not None:
            self._step_cache += 1
        return metrics

    def train_steps_stacked(
        self, stacked_features, stacked_labels, stacked_weights=None
    ):
        """K optimizer steps in ONE dispatch: a jitted ``lax.scan`` over
        batches stacked on a leading axis (semantically identical to K
        sequential ``train_step`` calls).  Amortizes per-dispatch
        overhead — decisive on high-latency links (tunneled dev TPUs,
        remote hosts), a free ~2x even on local hosts.  Returns the last
        step's metrics.  ``stacked_weights``: optional ``(K, rows)``
        per-row sample weights (shape-canonical batching), scanned
        alongside the batches."""
        num_steps = jax.tree_util.tree_leaves(stacked_features)[0].shape[0]
        key = (num_steps, stacked_weights is not None)
        scan_fn = self._stacked_scan_cache.get(key)
        if scan_fn is None:
            step_fn = self._train_step
            weighted = stacked_weights is not None

            def scan_steps(state, feats, labels, weights=None):
                def body(s, xs):
                    s2, metrics = step_fn(
                        s, xs[0], xs[1], xs[2] if weighted else None
                    )
                    return s2, metrics

                xs = (feats, labels, weights) if weighted else (feats, labels)
                return jax.lax.scan(body, state, xs)

            # pin the updated state to the mesh layout exactly like
            # build_train_step does — without it the scan output's
            # sharding can drift from state_shardings and multi-process
            # host reads (checkpoint, dump) fail on the re-laid-out tree.
            # donate_batch extends donation to the stacked (k, rows, ...)
            # batch/weight inputs: dead after the scan, their memory is
            # reused for outputs (zero steady-state h2d allocations)
            scan_fn = jax.jit(
                scan_steps,
                donate_argnums=(0, 1, 2, 3)
                if self._donate_batch
                else (0,),
                out_shardings=(self.state_shardings, None),
            )
            self._stacked_scan_cache[key] = scan_fn
        with self.mesh, attention_mesh_scope(self.mesh):
            if stacked_weights is None:
                self._state, metrics = scan_fn(
                    self._state, stacked_features, stacked_labels
                )
            else:
                self._state, metrics = scan_fn(
                    self._state,
                    stacked_features,
                    stacked_labels,
                    stacked_weights,
                )
        if self._step_cache is not None:
            self._step_cache += int(num_steps)
        return jax.tree_util.tree_map(lambda m: m[-1], metrics)

    def place_stacked(self, tree):
        """Place a (K, batch, ...) stacked tree: same layout as
        :meth:`place_batch` per step with a replicated leading K axis."""
        from jax.sharding import PartitionSpec as P

        def _place(x):
            x = np.asarray(x)
            per_step = self._batch_sharding(x.ndim - 1)
            sh = NamedSharding(
                self.mesh, P(None, *per_step.spec)
            )
            if not self._multiprocess:
                return jax.device_put(x, sh)
            return jax.make_array_from_callback(
                x.shape, sh, lambda idx: x[idx]
            )

        return jax.tree_util.tree_map(_place, tree)

    def eval_step(self, features, labels, weights=None):
        with self.mesh, attention_mesh_scope(self.mesh):
            return self._eval_step(self.state, features, labels, weights)

    def predict_step(self, features):
        with self.mesh, attention_mesh_scope(self.mesh):
            return self._predict_step(self.state, features)

    @property
    def step(self) -> int:
        """Model version — served from a host mirror so per-batch version
        checks never force a device readback (a full sync + roundtrip,
        ~100ms on tunneled dev links); one readback re-seeds the mirror
        after any external state assignment."""
        if self._step_cache is None:
            self._step_cache = int(jax.device_get(self._state.step))
        return self._step_cache


def _host_slice_for_init(sample_features):
    """A tiny host batch is enough to trace init (values are irrelevant)."""
    return jax.tree_util.tree_map(
        lambda x: np.asarray(x)[:1], sample_features
    )


def trim_pad(outputs, n: int):
    """Drop the rows :meth:`SPMDTrainer.pad_batch` added for shard
    divisibility (device arrays come back as host numpy)."""
    return jax.tree_util.tree_map(lambda x: np.asarray(x)[:n], outputs)
