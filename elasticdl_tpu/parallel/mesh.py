"""Logical device mesh construction.

The mesh is the TPU build's "cluster topology": where the reference
enumerates PS pods and worker pods (``k8s_instance_manager.py``), we
enumerate devices into named logical axes:

- ``dp``   data parallel (gradient psum rides here)
- ``fsdp`` fully-sharded data parallel (parameter sharding)
- ``tp``   tensor parallel
- ``sp``   sequence/context parallel (ring attention)
- ``ep``   expert/embedding parallel (sharded embedding tables, MoE)
- ``pp``   pipeline parallel (GPipe stage schedule, ops/pipeline.py)

``--mesh_shape dp=4,tp=2`` on the CLI maps to ``MeshConfig``.  Axes of
size 1 are kept in the mesh (they cost nothing and keep PartitionSpecs
uniform), so the same model code runs on any mesh shape.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax
import numpy as np
from jax.sharding import Mesh

from elasticdl_tpu.utils.constants import MeshAxis
from elasticdl_tpu.utils.log_utils import default_logger as logger


def parse_mesh_shape(mesh_shape: str) -> dict[str, int]:
    """Parse ``'dp=4,tp=2'`` into an ordered axis-size dict."""
    out: dict[str, int] = {}
    if not mesh_shape:
        return out
    for part in mesh_shape.split(","):
        part = part.strip()
        if not part:
            continue
        name, _, size = part.partition("=")
        name = name.strip()
        if name not in MeshAxis.ALL:
            raise ValueError(
                f"unknown mesh axis {name!r}; valid: {MeshAxis.ALL}"
            )
        out[name] = int(size)
        if out[name] <= 0:
            raise ValueError(f"axis {name!r} must be positive")
    return out


def detect_num_slices(devices, slice_index_fn=None) -> int:
    """Distinct TPU slices among ``devices`` (1 when the backend exposes
    no ``slice_index`` — CPU, single slice, or older runtimes).

    ``slice_index_fn`` overrides the attribute lookup — how the
    multichip dryrun forces a multi-slice layout onto host-platform CPU
    devices (which cannot carry a ``slice_index``)."""
    if slice_index_fn is not None:
        return len({slice_index_fn(d) for d in devices}) or 1
    slices = {getattr(d, "slice_index", None) for d in devices}
    if None in slices or not slices:
        return 1
    return len(slices)


def slice_assignments(num_processes: int, num_slices: int) -> list[int]:
    """THE canonical process->slice map: contiguous blocks, earlier
    slices absorbing the remainder (``np.array_split`` semantics).

    Shared by the instance manager (world kwargs), the lockstep worker
    (forced slice layout on backends without a device ``slice_index``)
    and the replica ring (off-slice neighbor repin), so no two layers
    can ever disagree about which process lives on which slice."""
    if num_processes <= 0:
        return []
    num_slices = max(1, min(int(num_slices), num_processes))
    out: list[int] = []
    base, extra = divmod(num_processes, num_slices)
    for s in range(num_slices):
        out.extend([s] * (base + (1 if s < extra else 0)))
    return out


def process_slice_index_fn(num_processes: int, num_slices: int):
    """A ``slice_index_fn`` for :meth:`MeshConfig.create` deriving each
    device's slice from its owning PROCESS via the canonical
    :func:`slice_assignments` map — how a forced multi-slice layout is
    imposed on backends whose devices carry no usable ``slice_index``.
    Deliberately ignores any device ``slice_index``: multi-process CPU
    worlds expose a constant 0 on EVERY device, which would collapse
    the forced layout back to one slice; callers that trust hardware
    attributes go through :func:`resolved_slice_index_fn`."""
    assign = slice_assignments(num_processes, num_slices)

    def fn(device):
        proc = int(getattr(device, "process_index", 0) or 0)
        return assign[min(proc, len(assign) - 1)] if assign else 0

    return fn


def mesh_process_slice_map(mesh, slice_index_fn=None) -> list[int]:
    """process_index -> slice id for every process in the mesh, derived
    from the DEVICES (the resolved layout the collectives actually
    follow), ordered by process index.  On hardware whose ``slice_index``
    disagrees with the canonical process->slice assignment, the mesh is
    the truth — consumers that need physical placement (the replica
    ring's off-slice guarantee) read this, never the canonical map."""
    get_slice = slice_index_fn or (
        lambda d: getattr(d, "slice_index", 0) or 0
    )
    by_proc: dict[int, int] = {}
    for d in mesh.devices.flat:
        by_proc[int(d.process_index)] = int(get_slice(d))
    return [by_proc[p] for p in sorted(by_proc)]


def resolved_slice_index_fn(devices, num_processes: int, num_slices: int):
    """The ``slice_index_fn`` a world assigned ``num_slices`` slices
    should build its mesh with:

    - None when single-slice, or when the backend already exposes a
      non-degenerate multi-slice topology (real TPU multislice: the
      hardware ``slice_index`` is authoritative);
    - the canonical process->slice map otherwise (CPU backends expose
      no ``slice_index`` — or a constant one on every device of a
      multi-process world, which is just as sliceless)."""
    if num_slices <= 1:
        return None
    if detect_num_slices(devices) > 1:
        return None
    return process_slice_index_fn(num_processes, num_slices)


def plan_dcn_axes(
    sizes: dict[str, int], n_slices: int, dcn_axes: dict[str, int] | None
) -> dict[str, int]:
    """Which part of each mesh axis spans slices (rides DCN).

    Defaults to putting ALL of the slice dimension on ``dp`` — gradient
    all-reduce is the lowest-rate collective, so it is the one that can
    afford DCN; everything else stays intra-slice on ICI (the
    scaling-book layout).  An explicit ``dcn_axes`` (from
    ``--dcn_mesh_shape``) overrides, e.g. ``fsdp=2`` for cross-slice
    parameter sharding.
    """
    if n_slices <= 1:
        return {}
    if dcn_axes:
        prod = int(np.prod(list(dcn_axes.values())))
        if prod != n_slices:
            raise ValueError(
                f"dcn_mesh_shape product {prod} != number of slices "
                f"{n_slices}"
            )
        for axis, deg in dcn_axes.items():
            if sizes.get(axis, 1) % deg:
                raise ValueError(
                    f"dcn axis {axis}={deg} does not divide mesh "
                    f"{axis}={sizes.get(axis, 1)}"
                )
        return dict(dcn_axes)
    if sizes.get(MeshAxis.DP, 1) % n_slices:
        raise ValueError(
            f"dp={sizes.get(MeshAxis.DP, 1)} not divisible by "
            f"{n_slices} slices; pass --dcn_mesh_shape explicitly"
        )
    return {MeshAxis.DP: n_slices}


def order_devices_hybrid(
    devices, sizes: dict[str, int], dcn: dict[str, int], slice_index_fn=None
) -> np.ndarray:
    """Fallback hybrid ordering: group devices by slice, lay each slice
    out row-major over the intra-slice (ICI) shape, and concatenate
    slices along the DCN axes — so the outer (slice) stride of a DCN axis
    crosses slices and everything else stays inside one.

    (``mesh_utils.create_hybrid_device_mesh`` does this with
    topology-aware intra-slice orders; this fallback keeps the same
    slice/axis assignment when that API is unavailable.)
    """
    get_slice = slice_index_fn or (
        lambda d: getattr(d, "slice_index", 0)
    )
    by_slice: dict = {}
    for d in devices:
        by_slice.setdefault(get_slice(d), []).append(d)
    slice_ids = sorted(by_slice)
    if len({len(v) for v in by_slice.values()}) != 1:
        raise ValueError(f"unequal devices per slice: {sorted(by_slice)}")
    live = [a for a, deg in dcn.items() if deg > 1]
    if len(live) != 1:
        raise ValueError(
            "fallback hybrid ordering supports exactly one DCN axis; "
            f"got {dcn} (use a jax version with create_hybrid_device_mesh "
            "for multi-axis DCN layouts)"
        )
    ici_shape = tuple(sizes[a] // dcn.get(a, 1) for a in sizes)
    arrays = [
        np.asarray(by_slice[s], dtype=object).reshape(ici_shape)
        for s in slice_ids
    ]
    # slice-major concatenation along the DCN axis: positions that differ
    # only in their intra-slice coordinate stay within one slice
    return np.concatenate(arrays, axis=list(sizes).index(live[0]))


@dataclass
class MeshConfig:
    """Axis sizes for the logical mesh; unspecified axes default to 1.

    When ``dp`` is omitted it is *inferred* as "all remaining devices"
    (num_devices / product of the given axes), so a bare job scales to
    whatever slice it lands on.  ``dcn_axes`` declares which part of
    which axis spans TPU slices (multi-slice jobs; collectives on those
    axis strides ride DCN, everything else ICI).
    """

    axes: dict[str, int] = field(default_factory=dict)
    dcn_axes: dict[str, int] = field(default_factory=dict)

    @classmethod
    def from_string(
        cls, mesh_shape: str, dcn_mesh_shape: str = ""
    ) -> "MeshConfig":
        return cls(
            parse_mesh_shape(mesh_shape), parse_mesh_shape(dcn_mesh_shape)
        )

    def resolved_axes(self, num_devices: int) -> dict[str, int]:
        sizes = {name: self.axes.get(name, 1) for name in MeshAxis.ALL}
        fixed = int(np.prod([s for s in sizes.values()]))
        if MeshAxis.DP not in self.axes:
            if num_devices % (fixed) != 0:
                raise ValueError(
                    f"{num_devices} devices not divisible by mesh "
                    f"product {fixed}"
                )
            sizes[MeshAxis.DP] = num_devices // fixed
        total = int(np.prod(list(sizes.values())))
        if total > num_devices:
            raise ValueError(
                f"mesh {sizes} needs {total} devices but "
                f"{num_devices} are available"
            )
        return sizes

    def create(self, devices=None, slice_index_fn=None) -> Mesh:
        """``slice_index_fn``: override the per-device slice attribute —
        the dryrun's hook for exercising the hybrid ICI/DCN layout on
        host-platform CPU devices (``__graft_entry__.dryrun_multichip``
        forces 2 slices through plan_dcn_axes with it)."""
        devices = devices if devices is not None else jax.devices()
        sizes = self.resolved_axes(len(devices))
        total = int(np.prod(list(sizes.values())))
        # an explicitly smaller mesh uses a device subset (useful for
        # single-device baselines on a multi-device host)
        devices = list(devices)[:total]
        axis_names = tuple(sizes)
        shape = tuple(sizes[a] for a in axis_names)
        get_slice = slice_index_fn or (
            lambda d: getattr(d, "slice_index", 0)
        )
        n_slices = detect_num_slices(devices, slice_index_fn)
        if n_slices > 1:
            per_slice: dict = {}
            for d in devices:
                key = get_slice(d)
                per_slice[key] = per_slice.get(key, 0) + 1
            if len(set(per_slice.values())) != 1:
                # a sub-mesh that doesn't tile the slices evenly (e.g. an
                # explicit smaller mesh truncated mid-slice) cannot be
                # laid out hybrid; a flat mesh is still correct
                logger.warning(
                    "Device subset spans slices unevenly (%s); building "
                    "a flat mesh instead of a hybrid one",
                    per_slice,
                )
                n_slices = 1
        if n_slices > 1:
            dcn = plan_dcn_axes(sizes, n_slices, self.dcn_axes or None)
            ici_shape = tuple(
                sizes[a] // dcn.get(a, 1) for a in axis_names
            )
            dcn_shape = tuple(dcn.get(a, 1) for a in axis_names)
            if slice_index_fn is not None:
                # forced slices: mesh_utils would re-read the (absent)
                # device attributes — use the in-repo hybrid ordering
                device_array = order_devices_hybrid(
                    devices, sizes, dcn, slice_index_fn
                )
            else:
                try:
                    from jax.experimental import mesh_utils

                    device_array = mesh_utils.create_hybrid_device_mesh(
                        ici_shape, dcn_shape, devices=devices
                    )
                except Exception:
                    device_array = order_devices_hybrid(
                        devices, sizes, dcn
                    )
            topology = f"{n_slices} slices (DCN axes {dcn})"
        else:
            if self.dcn_axes:
                # not silently: the user declared a multi-slice layout the
                # backend doesn't expose — collectives may cross DCN in
                # whatever order the flat mesh happens to pick
                logger.warning(
                    "--dcn_mesh_shape %s given but the backend exposes "
                    "a single slice (no device slice_index); building a "
                    "flat mesh",
                    self.dcn_axes,
                )
            try:
                from jax.experimental import mesh_utils

                device_array = mesh_utils.create_device_mesh(
                    shape, devices=devices
                )
            except Exception:
                # fallback (e.g. host-platform CPU devices): row-major
                device_array = np.asarray(devices).reshape(shape)
            topology = "1 slice"
        mesh = Mesh(device_array, axis_names)
        logger.info(
            "Created mesh %s over %d devices, %s",
            {a: s for a, s in sizes.items() if s > 1} or {"dp": 1},
            len(devices),
            topology,
        )
        return mesh


def data_parallel_axes(mesh: Mesh) -> tuple[str, ...]:
    """Axes the batch dimension is sharded over, size-1 axes excluded (dp
    and fsdp both consume batch; fsdp additionally shards parameters).
    The single definition of "the batch axes" — batch_sharding and
    batch_divisor both derive from it."""
    return tuple(
        a
        for a in (MeshAxis.DP, MeshAxis.FSDP)
        if a in mesh.axis_names and mesh.shape[a] > 1
    )


def batch_divisor(mesh: Mesh) -> int:
    """Global batch must be divisible by this for input sharding."""
    n = 1
    for a in data_parallel_axes(mesh):
        n *= mesh.shape[a]
    return n
