"""Logical device mesh construction.

The mesh is the TPU build's "cluster topology": where the reference
enumerates PS pods and worker pods (``k8s_instance_manager.py``), we
enumerate devices into named logical axes:

- ``dp``   data parallel (gradient psum rides here)
- ``fsdp`` fully-sharded data parallel (parameter sharding)
- ``tp``   tensor parallel
- ``sp``   sequence/context parallel (ring attention)
- ``ep``   expert/embedding parallel (sharded embedding tables)

``--mesh_shape dp=4,tp=2`` on the CLI maps to ``MeshConfig``.  Axes of
size 1 are kept in the mesh (they cost nothing and keep PartitionSpecs
uniform), so the same model code runs on any mesh shape.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax
import numpy as np
from jax.sharding import Mesh

from elasticdl_tpu.utils.constants import MeshAxis
from elasticdl_tpu.utils.log_utils import default_logger as logger


def parse_mesh_shape(mesh_shape: str) -> dict[str, int]:
    """Parse ``'dp=4,tp=2'`` into an ordered axis-size dict."""
    out: dict[str, int] = {}
    if not mesh_shape:
        return out
    for part in mesh_shape.split(","):
        part = part.strip()
        if not part:
            continue
        name, _, size = part.partition("=")
        name = name.strip()
        if name not in MeshAxis.ALL:
            raise ValueError(
                f"unknown mesh axis {name!r}; valid: {MeshAxis.ALL}"
            )
        out[name] = int(size)
        if out[name] <= 0:
            raise ValueError(f"axis {name!r} must be positive")
    return out


@dataclass
class MeshConfig:
    """Axis sizes for the logical mesh; unspecified axes default to 1.

    When ``dp`` is omitted it is *inferred* as "all remaining devices"
    (num_devices / product of the given axes), so a bare job scales to
    whatever slice it lands on.
    """

    axes: dict[str, int] = field(default_factory=dict)

    @classmethod
    def from_string(cls, mesh_shape: str) -> "MeshConfig":
        return cls(parse_mesh_shape(mesh_shape))

    def resolved_axes(self, num_devices: int) -> dict[str, int]:
        sizes = {name: self.axes.get(name, 1) for name in MeshAxis.ALL}
        fixed = int(np.prod([s for s in sizes.values()]))
        if MeshAxis.DP not in self.axes:
            if num_devices % (fixed) != 0:
                raise ValueError(
                    f"{num_devices} devices not divisible by mesh "
                    f"product {fixed}"
                )
            sizes[MeshAxis.DP] = num_devices // fixed
        total = int(np.prod(list(sizes.values())))
        if total > num_devices:
            raise ValueError(
                f"mesh {sizes} needs {total} devices but "
                f"{num_devices} are available"
            )
        return sizes

    def create(self, devices=None) -> Mesh:
        devices = devices if devices is not None else jax.devices()
        sizes = self.resolved_axes(len(devices))
        total = int(np.prod(list(sizes.values())))
        # an explicitly smaller mesh uses a device subset (useful for
        # single-device baselines on a multi-device host)
        devices = list(devices)[:total]
        axis_names = tuple(sizes)
        shape = tuple(sizes[a] for a in axis_names)
        try:
            from jax.experimental import mesh_utils

            device_array = mesh_utils.create_device_mesh(
                shape, devices=devices
            )
        except Exception:
            # fallback (e.g. host-platform CPU devices): row-major reshape
            device_array = np.asarray(devices).reshape(shape)
        mesh = Mesh(device_array, axis_names)
        logger.info(
            "Created mesh %s over %d devices",
            {a: s for a, s in sizes.items() if s > 1} or {"dp": 1},
            len(devices),
        )
        return mesh


def data_parallel_axes(mesh: Mesh) -> tuple[str, ...]:
    """Axes the batch dimension is sharded over, size-1 axes excluded (dp
    and fsdp both consume batch; fsdp additionally shards parameters).
    The single definition of "the batch axes" — batch_sharding and
    batch_divisor both derive from it."""
    return tuple(
        a
        for a in (MeshAxis.DP, MeshAxis.FSDP)
        if a in mesh.axis_names and mesh.shape[a] > 1
    )


def batch_divisor(mesh: Mesh) -> int:
    """Global batch must be divisible by this for input sharding."""
    n = 1
    for a in data_parallel_axes(mesh):
        n *= mesh.shape[a]
    return n
