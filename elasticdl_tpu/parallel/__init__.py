"""Parallelism: device meshes, sharding rules, collectives, distributed
training.

This package replaces the reference's entire distribution machinery — the
gRPC parameter server (``elasticdl/python/ps/``), the FTLib collective
communicator (``collective_ops/communicator.py``), and the worker's
push/pull plumbing (``worker.py:295-530``) — with the TPU-native model:
one logical device mesh, parameters annotated with shardings, and XLA
inserting the collectives (psum over ICI for gradients, all-to-all for
sharded embedding lookups).  See SURVEY §7 target-architecture mapping.
"""

from elasticdl_tpu.parallel.mesh import MeshConfig, parse_mesh_shape
from elasticdl_tpu.parallel.sharding import (
    batch_sharding,
    infer_param_specs,
    replicated,
)

__all__ = [
    "MeshConfig",
    "parse_mesh_shape",
    "batch_sharding",
    "infer_param_specs",
    "replicated",
]
