"""Multi-process SPMD runtime: world formation and host-data placement.

This is the TPU-native replacement for the reference's cross-worker data
plane (PS pull/push ``elasticdl/python/worker/worker.py:295-530``; FTLib
allreduce ``collective_ops/communicator.py:30-67``): N worker processes —
one per TPU host — join ONE ``jax.distributed`` world, build ONE global
mesh, and run the SAME jitted step in lockstep; gradient exchange is the
psum XLA derives from shardings, riding ICI (and DCN across slices).

Membership is master-owned (the reference's k8s watch equivalent): the
master assigns ``process_id``/``num_processes``/``coordinator_addr`` via
the argv round-trip and re-forms the world (new cluster_version, new
coordinator) when a worker dies — there is no gossip.

Worker liveness inside the world is the coordination service's concern;
liveness *of* the world is the master's (heartbeat timeouts).
"""

from __future__ import annotations

import socket

import jax
import numpy as np

from elasticdl_tpu.utils.log_utils import default_logger as logger


def configure_platform(platform: str | None):
    """Pin the JAX platform before any backend initializes.

    ``JAX_PLATFORMS=cpu`` in the environment is not always authoritative
    (platform plugins may still register and initialize — e.g. a tunneled
    TPU plugin — which poisons ``jax.process_count()`` for the CPU
    backend); setting the config explicitly is.
    """
    if platform:
        jax.config.update("jax_platforms", platform)


def configure_compilation_cache(cache_dir: str | None):
    """Enable the persistent XLA compilation cache.  On TPU a re-formed
    world (or a re-run of the same job) then loads its executables from
    disk instead of recompiling — compile time is a real term in both
    re-formation latency and job startup."""
    if cache_dir:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # cache every executable: the default thresholds skip exactly the
        # small programs a test-size job re-forms over
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)


def initialize_world(
    coordinator_addr: str,
    num_processes: int,
    process_id: int,
    platform: str | None = None,
    timeout_secs: int = 60,
):
    """Join the job's ``jax.distributed`` world (process 0 additionally
    hosts the coordination service at ``coordinator_addr``)."""
    configure_platform(platform)
    if platform == "cpu":
        # cross-process CPU collectives need an explicit implementation.
        # Set ONLY here, between platform selection and distributed init:
        # jaxlib's gloo factory requires the distributed client, so a
        # single-process job (tests, LocalExecutor, the CLI) with this
        # config set cannot initialize the cpu backend at all.
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    # reform-phase span (telemetry/tracing.py, no-op when tracing is
    # off): the coordination-service handshake blocks until every peer
    # of the (re-)formed world arrives, so its duration IS the
    # world-formation term of reform downtime
    from elasticdl_tpu.telemetry.tracing import (
        SPAN_WORLD_INITIALIZE,
        trace_span,
    )

    with trace_span(
        SPAN_WORLD_INITIALIZE,
        num_processes=num_processes,
        process_id=process_id,
    ):
        jax.distributed.initialize(
            coordinator_address=coordinator_addr,
            num_processes=num_processes,
            process_id=process_id,
            initialization_timeout=timeout_secs,
        )
    logger.info(
        "Joined distributed world: process %d/%d (coordinator %s)",
        process_id,
        num_processes,
        coordinator_addr,
    )


def shutdown_world():
    try:
        jax.distributed.shutdown()
    except Exception:  # noqa: BLE001 — peers may already be gone
        pass


def pick_coordinator_port() -> int:
    """A free TCP port for the next world's coordination service (each
    re-formation gets a fresh one: the old coordinator died with its
    process 0)."""
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


# ---- host-data placement ---------------------------------------------------


def mesh_process_indices(mesh) -> list[int]:
    """Sorted process indices participating in the mesh."""
    return sorted({d.process_index for d in mesh.devices.flat})


def is_multiprocess_mesh(mesh) -> bool:
    """Mesh spans >1 process.  (Do NOT use ``jax.process_count()`` for
    this: it reports the default backend, which may be a single-process
    platform plugin even when the mesh's backend is multi-process.)"""
    return len(mesh_process_indices(mesh)) > 1


def my_process_index(mesh) -> int:
    """This process's index in the mesh's backend (NOT
    ``jax.process_index()``, which reads the default backend)."""
    return mesh.devices.flat[0].client.process_index()


def local_batch_ranges(
    sharding, global_shape: tuple, process_index: int
) -> list[tuple[int, int]]:
    """The ascending, de-duplicated dim-0 ``[start, stop)`` ranges of the
    global batch owned by ``process_index`` under ``sharding``.

    This is the contract of ``jax.make_array_from_process_local_data``:
    each process contributes its shards' rows in global index order.
    Deriving the ranges from ``devices_indices_map`` (instead of assuming
    process-contiguous layout) keeps placement correct for ANY device
    order the mesh builder chose — including ICI-topology-optimized
    orders on real pods.
    """
    ranges = set()
    for device, idx in sharding.devices_indices_map(global_shape).items():
        if device.process_index != process_index:
            continue
        sl = idx[0]
        start = sl.start if sl.start is not None else 0
        stop = sl.stop if sl.stop is not None else global_shape[0]
        ranges.add((start, stop))
    return sorted(ranges)


def state_checkpoint_parts(state, mesh, materialize_dense: bool = True):
    """Split the live device state into ``(dense, parts)`` for part-based
    checkpointing, driven by each array's ACTUAL sharding (the sharded
    analogue of ``trainer.state.state_to_checkpoint``):

    - fully-replicated leaves -> ``dense`` (read from the local replica,
      no communication; skipped entirely when ``materialize_dense`` is
      False — non-chief processes discard them, so they must not pay N-1
      device-to-host copies);
    - 2-D leaves range-sharded on dim 0 only (embedding tables, and any
      fsdp dim-0 shard) -> ``parts``: ``name -> (ids, rows)`` for the
      row ranges this process OWNS — when dp replicates a range across
      processes, only the lowest process index owning it writes it, so
      parts are disjoint and each host writes exactly its slice (a table
      larger than one host's RAM never materializes; reference
      per-PS-shard checkpointing, common/save_utils.py:100-116);
    - anything else sharded -> gathered collectively into ``dense``.

    Collective: every process of the mesh must call this at the same
    point (leaf classification is identical everywhere, so the gathers
    line up).
    """
    flat = flat_state_arrays(state)
    my_proc = my_process_index(mesh) if is_multiprocess_mesh(mesh) else None

    dense: dict = {}
    parts: dict = {}
    to_gather: dict = {}
    for name, arr in flat.items():
        if not isinstance(arr, jax.Array):
            if materialize_dense:
                dense[name] = np.asarray(arr)
            continue
        sharding = arr.sharding
        if sharding.is_fully_replicated:
            if materialize_dense:
                dense[name] = np.asarray(arr)
            continue
        if arr.ndim == 2 and _dim0_sharded_only(arr):
            owned = _owned_row_ranges(sharding, arr.shape, my_proc)
            ranges: dict[tuple[int, int], np.ndarray] = {}
            for shard in arr.addressable_shards:
                r = _dim0_range(shard.index, arr.shape)
                if r in owned:
                    ranges[r] = np.asarray(shard.data)
            ordered = sorted(ranges)
            if ordered:
                ids = np.concatenate(
                    [np.arange(lo, hi, dtype=np.int64) for lo, hi in ordered]
                )
                rows = np.concatenate([ranges[r] for r in ordered], axis=0)
            else:
                ids = np.zeros((0,), dtype=np.int64)
                rows = np.zeros((0, arr.shape[1]), dtype=arr.dtype)
            parts[name] = (ids, rows)
        else:
            to_gather[name] = arr
    if to_gather:
        gathered = replicate_to_hosts(to_gather, mesh)
        if materialize_dense:
            dense.update(gathered)
    return dense, parts


def _owned_row_ranges(sharding, shape, my_proc) -> set[tuple[int, int]]:
    """Dim-0 ranges this process WRITES: when dp replicates a range over
    several processes, the lowest process index owning it is the writer
    (deterministic, communication-free)."""
    if my_proc is None:
        # single-process mesh: everything addressable is owned
        return {
            _dim0_range(idx, shape)
            for idx in sharding.devices_indices_map(shape).values()
        }
    owner: dict[tuple[int, int], int] = {}
    for device, idx in sharding.devices_indices_map(shape).items():
        r = _dim0_range(idx, shape)
        prev = owner.get(r)
        if prev is None or device.process_index < prev:
            owner[r] = device.process_index
    return {r for r, proc in owner.items() if proc == my_proc}


def _dim0_range(idx, shape) -> tuple[int, int]:
    sl = idx[0]
    lo = sl.start if sl.start is not None else 0
    hi = sl.stop if sl.stop is not None else shape[0]
    return (lo, hi)


def local_table_row_ranges(state, mesh) -> dict:
    """Per-table dim-0 row ranges this process's devices hold — the keep
    filter a restore passes to ``save_utils.restore_checkpoint`` so no
    host ever accumulates a whole distributed table."""
    proc = my_process_index(mesh)
    out = {}
    for name, arr in flat_state_arrays(state).items():
        if (
            isinstance(arr, jax.Array)
            and arr.ndim == 2
            and not arr.sharding.is_fully_replicated
            and _dim0_sharded_only(arr)
        ):
            out[name] = local_batch_ranges(arr.sharding, arr.shape, proc)
    return out


def flat_state_arrays(state) -> dict:
    """Checkpoint-named flat view of the state's restorable leaves
    (``params/...`` + mutable collections), KEEPING device arrays as-is
    (tree_to_dict would device_get sharded arrays whole, which is exactly
    what part-based checkpointing exists to avoid)."""
    flat = {
        f"params/{k}": v for k, v in _flat_arrays(state.params).items()
    }
    if state.model_state:
        flat.update(_flat_arrays(state.model_state))
    return flat


def _flat_arrays(tree) -> dict:
    from elasticdl_tpu.utils.tree_utils import _key_str

    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return {
        "/".join(_key_str(k) for k in path): leaf for path, leaf in flat
    }


def dim0_split_only(sharding, shape) -> bool:
    """The layout splits only dim 0: every trailing dim is a full slice
    on every device.  Shared predicate for part-based checkpointing
    (2-D tables) and batch placement (dp/fsdp-only batch layouts)."""
    for idx in sharding.devices_indices_map(shape).values():
        for dim, sl in enumerate(idx[1:], start=1):
            if not (
                sl.start in (None, 0) and sl.stop in (None, shape[dim])
            ):
                return False
    return True


def _dim0_sharded_only(arr) -> bool:
    return dim0_split_only(arr.sharding, arr.shape)


def replicate_to_hosts(tree, mesh):
    """All-gather a (possibly sharded) device tree so every process holds
    the full values — the collective equivalent of ``device_get`` on a
    single-process mesh.  Used to materialize eval outputs and state for
    host-side reporting/export; runs on ALL processes (it is a collective
    program)."""
    from jax.sharding import NamedSharding, PartitionSpec

    replicated = NamedSharding(mesh, PartitionSpec())

    def _sharding_tree(t):
        return jax.tree_util.tree_map(lambda _: replicated, t)

    with mesh:
        gathered = jax.jit(
            lambda t: t, out_shardings=_sharding_tree(tree)
        )(tree)
    return jax.device_get(gathered)
