"""Multi-process SPMD runtime: world formation and host-data placement.

This is the TPU-native replacement for the reference's cross-worker data
plane (PS pull/push ``elasticdl/python/worker/worker.py:295-530``; FTLib
allreduce ``collective_ops/communicator.py:30-67``): N worker processes —
one per TPU host — join ONE ``jax.distributed`` world, build ONE global
mesh, and run the SAME jitted step in lockstep; gradient exchange is the
psum XLA derives from shardings, riding ICI (and DCN across slices).

Membership is master-owned (the reference's k8s watch equivalent): the
master assigns ``process_id``/``num_processes``/``coordinator_addr`` via
the argv round-trip and re-forms the world (new cluster_version, new
coordinator) when a worker dies — there is no gossip.

Worker liveness inside the world is the coordination service's concern;
liveness *of* the world is the master's (heartbeat timeouts).
"""

from __future__ import annotations

import socket

import jax
import numpy as np

from elasticdl_tpu.utils.log_utils import default_logger as logger


def configure_platform(platform: str | None):
    """Pin the JAX platform before any backend initializes.

    ``JAX_PLATFORMS=cpu`` in the environment is not always authoritative
    (platform plugins may still register and initialize — e.g. a tunneled
    TPU plugin — which poisons ``jax.process_count()`` for the CPU
    backend); setting the config explicitly is.
    """
    if platform:
        jax.config.update("jax_platforms", platform)
        if platform == "cpu":
            # cross-process CPU collectives need an explicit implementation
            jax.config.update("jax_cpu_collectives_implementation", "gloo")


def initialize_world(
    coordinator_addr: str,
    num_processes: int,
    process_id: int,
    platform: str | None = None,
    timeout_secs: int = 60,
):
    """Join the job's ``jax.distributed`` world (process 0 additionally
    hosts the coordination service at ``coordinator_addr``)."""
    configure_platform(platform)
    jax.distributed.initialize(
        coordinator_address=coordinator_addr,
        num_processes=num_processes,
        process_id=process_id,
        initialization_timeout=timeout_secs,
    )
    logger.info(
        "Joined distributed world: process %d/%d (coordinator %s)",
        process_id,
        num_processes,
        coordinator_addr,
    )


def shutdown_world():
    try:
        jax.distributed.shutdown()
    except Exception:  # noqa: BLE001 — peers may already be gone
        pass


def pick_coordinator_port() -> int:
    """A free TCP port for the next world's coordination service (each
    re-formation gets a fresh one: the old coordinator died with its
    process 0)."""
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


# ---- host-data placement ---------------------------------------------------


def mesh_process_indices(mesh) -> list[int]:
    """Sorted process indices participating in the mesh."""
    return sorted({d.process_index for d in mesh.devices.flat})


def is_multiprocess_mesh(mesh) -> bool:
    """Mesh spans >1 process.  (Do NOT use ``jax.process_count()`` for
    this: it reports the default backend, which may be a single-process
    platform plugin even when the mesh's backend is multi-process.)"""
    return len(mesh_process_indices(mesh)) > 1


def my_process_index(mesh) -> int:
    """This process's index in the mesh's backend (NOT
    ``jax.process_index()``, which reads the default backend)."""
    return mesh.devices.flat[0].client.process_index()


def local_batch_ranges(
    sharding, global_shape: tuple, process_index: int
) -> list[tuple[int, int]]:
    """The ascending, de-duplicated dim-0 ``[start, stop)`` ranges of the
    global batch owned by ``process_index`` under ``sharding``.

    This is the contract of ``jax.make_array_from_process_local_data``:
    each process contributes its shards' rows in global index order.
    Deriving the ranges from ``devices_indices_map`` (instead of assuming
    process-contiguous layout) keeps placement correct for ANY device
    order the mesh builder chose — including ICI-topology-optimized
    orders on real pods.
    """
    ranges = set()
    for device, idx in sharding.devices_indices_map(global_shape).items():
        if device.process_index != process_index:
            continue
        sl = idx[0]
        start = sl.start if sl.start is not None else 0
        stop = sl.stop if sl.stop is not None else global_shape[0]
        ranges.add((start, stop))
    return sorted(ranges)


def replicate_to_hosts(tree, mesh):
    """All-gather a (possibly sharded) device tree so every process holds
    the full values — the collective equivalent of ``device_get`` on a
    single-process mesh.  Used to materialize eval outputs and state for
    host-side reporting/export; runs on ALL processes (it is a collective
    program)."""
    from jax.sharding import NamedSharding, PartitionSpec

    replicated = NamedSharding(mesh, PartitionSpec())

    def _sharding_tree(t):
        return jax.tree_util.tree_map(lambda _: replicated, t)

    with mesh:
        gathered = jax.jit(
            lambda t: t, out_shardings=_sharding_tree(tree)
        )(tree)
    return jax.device_get(gathered)
