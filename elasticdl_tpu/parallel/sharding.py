"""Parameter/batch sharding rules.

Where the reference decides "which PS pod owns this variable" by name hash
(``hash_utils.py:4``, ``worker.py:371-381``), the TPU build decides "which
mesh axes shard this array" by *rules over parameter paths*: an ordered
list of ``(path_regex, PartitionSpec)`` pairs, first match wins, default
replicated.  Layers can also attach explicit specs via flax metadata;
rules are the policy layer on top.

FSDP: with an ``fsdp`` axis of size > 1, parameters without an explicit
rule are sharded along their largest divisible dimension — the standard
ZeRO-3-style layout where each dp rank owns a parameter slice and XLA
all-gathers just-in-time.
"""

from __future__ import annotations

import re
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from elasticdl_tpu.utils.constants import MeshAxis
from elasticdl_tpu.utils.tree_utils import _key_str


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh, ndim: int = 0, sp_dim: int | None = None) -> NamedSharding:
    """Leading-dim batch sharding over dp(+fsdp); optionally shard a
    sequence dimension over sp."""
    from elasticdl_tpu.parallel.mesh import data_parallel_axes

    axes = data_parallel_axes(mesh)
    spec = [axes if axes else None]
    if ndim:
        rest = [None] * (ndim - 1)
        if (
            sp_dim is not None
            and MeshAxis.SP in mesh.axis_names
            and mesh.shape[MeshAxis.SP] > 1
        ):
            rest[sp_dim - 1] = MeshAxis.SP
        spec.extend(rest)
    return NamedSharding(mesh, P(*spec))


class Rule:
    def __init__(self, pattern: str, spec: P):
        self.regex = re.compile(pattern)
        self.spec = spec

    def matches(self, path: str) -> bool:
        return self.regex.search(path) is not None


def _axis_size(mesh: Mesh, axis) -> int:
    if axis is None:
        return 1
    if isinstance(axis, (tuple, list)):
        return int(np.prod([_axis_size(mesh, a) for a in axis]))
    return mesh.shape[axis] if axis in mesh.axis_names else 0


def _spec_fits(spec: P, shape, mesh: Mesh) -> bool:
    for dim, axis in enumerate(spec):
        if axis is None:
            continue
        size = _axis_size(mesh, axis)
        if size == 0 or dim >= len(shape) or shape[dim] % size != 0:
            return False
    return True


def _fsdp_spec(shape, mesh: Mesh) -> P:
    """Shard the largest divisible dim over fsdp; replicate if none fits."""
    size = mesh.shape.get(MeshAxis.FSDP, 1)
    if size <= 1 or not shape:
        return P()
    dims = sorted(range(len(shape)), key=lambda d: -shape[d])
    for d in dims:
        if shape[d] % size == 0 and shape[d] >= size:
            spec = [None] * len(shape)
            spec[d] = MeshAxis.FSDP
            return P(*spec)
    return P()


def infer_param_specs(
    params,
    mesh: Mesh,
    rules: Sequence[Rule] = (),
) -> dict:
    """PartitionSpec pytree for ``params``: first matching rule wins (if it
    fits the shape), then FSDP auto-sharding, else replicated."""

    def _spec_for(path_entries, leaf):
        path = "/".join(_key_str(k) for k in path_entries)
        shape = np.shape(leaf)
        for rule in rules:
            if rule.matches(path):
                if _spec_fits(rule.spec, shape, mesh):
                    return rule.spec
                break
        return _fsdp_spec(shape, mesh)

    return jax.tree_util.tree_map_with_path(_spec_for, params)


def specs_to_shardings(specs, mesh: Mesh):
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s),
        specs,
        is_leaf=lambda x: isinstance(x, P),
    )


def place_tree(tree, shardings):
    """Device-put a pytree with per-leaf shardings."""
    return jax.tree_util.tree_map(
        lambda leaf, sh: jax.device_put(leaf, sh), tree, shardings
    )


# Default tensor-parallel rules for transformer-style parameter names.
# (flax puts weights under e.g. ".../attention/query/kernel"); column- vs
# row-parallel follows the Megatron convention so only one psum per block
# is needed — XLA derives it from these shardings.
def default_tp_rules() -> list[Rule]:
    tp = MeshAxis.TP
    return [
        Rule(r"(query|key|value|q_proj|k_proj|v_proj)/kernel$", P(None, tp)),
        Rule(r"(out|o_proj|attn_out)/kernel$", P(tp, None)),
        Rule(r"(mlp/up|mlp/gate|mlp_up|fc1|intermediate)/kernel$", P(None, tp)),
        Rule(r"(mlp/down|mlp_down|fc2|output)/kernel$", P(tp, None)),
        Rule(r"embedding/embedding$", P(tp, None)),
        Rule(r"(lm_head|logits)/kernel$", P(None, tp)),
    ]
