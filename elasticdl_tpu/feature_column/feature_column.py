"""Feature columns — tabular-feature spec shared by host and device.

Reference: ``elasticdl/python/elasticdl/feature_column/feature_column.py``
clones ``tf.feature_column.embedding_column`` so lookups route through the
EmbeddingDelegate RPC; models combine columns with
``tf.keras.layers.DenseFeatures`` (model_zoo census_feature_columns.py).

The TPU build splits a column into its two natural halves:

- **host half** (:func:`transform_features`): string hashing / vocabulary
  lookup / dtype coercion on numpy batches, in the data pipeline.  Strings
  never reach the device — XLA has no string type, and the reference also
  does this work outside the train step (in the TF input graph).
- **device half** (:class:`DenseFeatures`): pure array math inside jit —
  embedding gathers (mesh-sharded tables via layers.Embedding), one-/multi-
  hot encodings, bucketize, concat.  All static-shaped, MXU-friendly.

Categorical columns produce int32 id arrays with ``-1`` for missing /
out-of-vocabulary values; embedding and indicator encodings treat negative
ids as absent (matching safe_embedding_lookup_sparse semantics).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from elasticdl_tpu.layers.embedding import Embedding
from elasticdl_tpu.utils.hash_utils import string_to_id


@dataclasses.dataclass(frozen=True)
class NumericColumn:
    key: str
    shape: tuple = (1,)
    dtype: Any = np.float32
    normalizer_fn: Optional[Callable] = None

    @property
    def name(self) -> str:
        return self.key

    def transform(self, features: dict) -> np.ndarray:
        return np.asarray(features[self.key]).astype(self.dtype)


@dataclasses.dataclass(frozen=True)
class BucketizedColumn:
    source: NumericColumn
    boundaries: tuple

    @property
    def key(self) -> str:
        return self.source.key

    @property
    def name(self) -> str:
        return f"{self.key}_bucketized"

    @property
    def num_buckets(self) -> int:
        return len(self.boundaries) + 1

    def transform(self, features: dict) -> np.ndarray:
        x = self.source.transform(features)
        return np.digitize(x, self.boundaries).astype(np.int32)


@dataclasses.dataclass(frozen=True)
class HashedCategoricalColumn:
    key: str
    hash_bucket_size: int

    @property
    def name(self) -> str:
        return self.key

    @property
    def num_buckets(self) -> int:
        return self.hash_bucket_size

    def transform(self, features: dict) -> np.ndarray:
        vals = np.asarray(features[self.key])
        if vals.dtype.kind in ("U", "S", "O"):
            flat = np.array(
                [
                    string_to_id(
                        v.decode() if isinstance(v, bytes) else str(v),
                        self.hash_bucket_size,
                    )
                    for v in vals.reshape(-1)
                ],
                dtype=np.int32,
            )
            return flat.reshape(vals.shape)
        return (vals.astype(np.int64) % self.hash_bucket_size).astype(np.int32)


@dataclasses.dataclass(frozen=True)
class VocabularyCategoricalColumn:
    key: str
    vocabulary: tuple

    def __post_init__(self):
        # transform runs per batch on the input hot path; build the
        # vocab->index table once
        object.__setattr__(
            self, "_table", {v: i for i, v in enumerate(self.vocabulary)}
        )

    @property
    def name(self) -> str:
        return self.key

    @property
    def num_buckets(self) -> int:
        return len(self.vocabulary)

    def transform(self, features: dict) -> np.ndarray:
        table = self._table
        vals = np.asarray(features[self.key])

        def _lookup(v):
            if isinstance(v, bytes):
                v = v.decode()
            return table.get(v, -1)  # OOV -> -1 (absent)

        flat = np.array(
            [_lookup(v) for v in vals.reshape(-1)], dtype=np.int32
        )
        return flat.reshape(vals.shape)


@dataclasses.dataclass(frozen=True)
class IdentityCategoricalColumn:
    key: str
    num_buckets: int

    @property
    def name(self) -> str:
        return self.key

    def transform(self, features: dict) -> np.ndarray:
        vals = np.asarray(features[self.key]).astype(np.int64)
        # out-of-range -> -1 (absent), like TF with default_value unset
        vals = np.where(
            (vals >= 0) & (vals < self.num_buckets), vals, -1
        )
        return vals.astype(np.int32)


CategoricalColumn = (
    HashedCategoricalColumn,
    VocabularyCategoricalColumn,
    IdentityCategoricalColumn,
    BucketizedColumn,
)


@dataclasses.dataclass(frozen=True)
class EmbeddingColumn:
    categorical: Any
    dimension: int
    combiner: str = "mean"
    initializer: Any = "uniform"

    @property
    def key(self) -> str:
        return self.categorical.key

    @property
    def name(self) -> str:
        return f"{self.categorical.name}_embedding"

    def transform(self, features: dict) -> np.ndarray:
        return self.categorical.transform(features)


@dataclasses.dataclass(frozen=True)
class IndicatorColumn:
    categorical: Any

    @property
    def key(self) -> str:
        return self.categorical.key

    @property
    def name(self) -> str:
        return f"{self.categorical.name}_indicator"

    def transform(self, features: dict) -> np.ndarray:
        return self.categorical.transform(features)


# ---- factory functions (tf.feature_column-compatible names) ----------------


def numeric_column(key, shape=(1,), dtype=np.float32, normalizer_fn=None):
    return NumericColumn(key, tuple(np.ravel(shape)), dtype, normalizer_fn)


def bucketized_column(source: NumericColumn, boundaries: Sequence[float]):
    return BucketizedColumn(source, tuple(boundaries))


def categorical_column_with_hash_bucket(key, hash_bucket_size, dtype=None):
    return HashedCategoricalColumn(key, int(hash_bucket_size))


def categorical_column_with_vocabulary_list(key, vocabulary_list):
    return VocabularyCategoricalColumn(key, tuple(vocabulary_list))


def categorical_column_with_identity(key, num_buckets):
    return IdentityCategoricalColumn(key, int(num_buckets))


def embedding_column(
    categorical_column, dimension, combiner="mean", initializer="uniform"
):
    """The EDL embedding_column analogue (reference
    feature_column/feature_column.py:12): same signature, but the table it
    creates is a mesh-shardable layers.Embedding parameter instead of a
    delegate routing RPCs."""
    return EmbeddingColumn(
        categorical_column, int(dimension), combiner, initializer
    )


def indicator_column(categorical_column):
    return IndicatorColumn(categorical_column)


def transform_features(columns, features: dict) -> dict:
    """Host half: raw feature dict -> numeric/int arrays keyed by *column
    name* (two columns deriving from the same source key — e.g. a numeric
    and a bucketized view of ``age`` — must not clobber each other).  Run
    inside ``dataset_fn`` on numpy batches (strings hashed / vocab-mapped
    here, before anything touches the device).  Raw string-valued source
    keys are dropped so the batch is device-placeable."""
    out = {
        k: v
        for k, v in features.items()
        if np.asarray(v).dtype.kind not in ("U", "S", "O")
    }
    for col in columns:
        out[col.name] = col.transform(features)
    return out


class DenseFeatures(nn.Module):
    """Device half: the ``tf.keras.layers.DenseFeatures`` equivalent.

    Consumes the :func:`transform_features` output and produces the
    concatenated ``(batch, total_dim)`` float tensor, in the given column
    order.  Embedding columns instantiate :class:`layers.Embedding`
    submodules named after the column so the auto-partition policy sees
    them like any other table.
    """

    columns: tuple
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, features: dict):
        outputs = []
        batch = None
        for col in self.columns:
            # transform_features keys by column name; accept raw source-key
            # batches too for columns whose transform is identity-like
            x = features[col.name] if col.name in features else features[col.key]
            batch = x.shape[0] if batch is None else batch
            if isinstance(col, NumericColumn):
                x = jnp.asarray(x, self.dtype).reshape(batch, -1)
                if col.normalizer_fn is not None:
                    x = col.normalizer_fn(x)
                outputs.append(x)
            elif isinstance(col, EmbeddingColumn):
                ids = jnp.asarray(x).reshape(batch, -1)
                emb = Embedding(
                    input_dim=col.categorical.num_buckets,
                    output_dim=col.dimension,
                    embeddings_initializer=col.initializer,
                    combiner=col.combiner,
                    dtype=self.dtype,
                    name=col.name,
                )(ids)
                outputs.append(emb)
            elif isinstance(col, IndicatorColumn):
                ids = jnp.asarray(x).reshape(batch, -1)
                onehot = jax.nn.one_hot(
                    jnp.maximum(ids, 0),
                    col.categorical.num_buckets,
                    dtype=self.dtype,
                )
                onehot = onehot * (ids >= 0)[..., None].astype(self.dtype)
                outputs.append(onehot.sum(axis=1))  # multi-hot over the bag
            elif isinstance(col, BucketizedColumn):
                ids = jnp.asarray(x).reshape(batch, -1)
                onehot = jax.nn.one_hot(
                    ids, col.num_buckets, dtype=self.dtype
                )
                outputs.append(onehot.reshape(batch, -1))
            else:
                raise TypeError(
                    f"column {col!r} cannot be used directly in "
                    "DenseFeatures; wrap categorical columns in "
                    "embedding_column or indicator_column"
                )
        return jnp.concatenate(outputs, axis=-1)
