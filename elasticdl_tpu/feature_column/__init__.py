from elasticdl_tpu.feature_column.feature_column import (  # noqa: F401
    DenseFeatures,
    bucketized_column,
    categorical_column_with_hash_bucket,
    categorical_column_with_identity,
    categorical_column_with_vocabulary_list,
    embedding_column,
    indicator_column,
    numeric_column,
    transform_features,
)
