"""Data layer: readers, the EDLIO record format, dataset pipeline.

Reference: ``elasticdl/python/data/`` (SURVEY §2.6).  The reference's
RecordIO dependency (Go ``pyrecordio``) is replaced by EDLIO, our own
seekable record container (C++ codec + pure-Python fallback), and the
tf.data pipeline is replaced by a numpy pipeline with threaded prefetch
feeding ``jax.device_put`` directly.
"""

from elasticdl_tpu.data.dataset import Dataset
from elasticdl_tpu.data.reader import AbstractDataReader, Metadata

__all__ = ["Dataset", "AbstractDataReader", "Metadata"]
