"""Numpy dataset pipeline with threaded prefetch.

Replaces the reference's tf.data usage (``worker.py:972-977``:
``dataset_fn(ds, mode, metadata)`` then ``.batch().prefetch(1)``) with a
small composable pipeline that produces host numpy batches ready for
``jax.device_put``.  Transformations are lazy; each ``__iter__`` restarts
from the source, so a dataset built over a task's record range can be
re-consumed on retry.

The model-zoo ``dataset_fn(dataset, mode, metadata)`` contract operates on
this class: readers produce raw records, ``map`` decodes them, the worker
applies ``batch``/``prefetch``.
"""

from __future__ import annotations

import queue
import random
import threading
from typing import Any, Callable, Iterable, Iterator

import numpy as np


def _stack(elements: list):
    """Stack a list of pipeline elements into one batched element.

    Handles dicts (by key), tuples/lists (by position), scalars and
    ndarrays (np.stack).
    """
    first = elements[0]
    if isinstance(first, dict):
        return {k: _stack([e[k] for e in elements]) for k in first}
    if isinstance(first, (tuple, list)):
        cols = [_stack([e[i] for e in elements]) for i in range(len(first))]
        return tuple(cols) if isinstance(first, tuple) else cols
    return np.stack([np.asarray(e) for e in elements])


class Dataset:
    def __init__(self, source: Callable[[], Iterator]):
        self._source = source

    # ---- constructors -----------------------------------------------------

    @staticmethod
    def from_generator(gen_factory: Callable[[], Iterable]) -> "Dataset":
        return Dataset(lambda: iter(gen_factory()))

    @staticmethod
    def from_records(records: Iterable) -> "Dataset":
        materialized = records if isinstance(records, list) else list(records)
        return Dataset(lambda: iter(materialized))

    # ---- transformations --------------------------------------------------

    def map(self, fn: Callable[[Any], Any]) -> "Dataset":
        parent = self._source
        return Dataset(lambda: (fn(x) for x in parent()))

    def filter(self, predicate: Callable[[Any], bool]) -> "Dataset":
        parent = self._source
        return Dataset(lambda: (x for x in parent() if predicate(x)))

    def shuffle(self, buffer_size: int, seed: int | None = None) -> "Dataset":
        parent = self._source

        def gen():
            rng = random.Random(seed)
            buf: list = []
            for x in parent():
                buf.append(x)
                if len(buf) >= buffer_size:
                    idx = rng.randrange(len(buf))
                    buf[idx], buf[-1] = buf[-1], buf[idx]
                    yield buf.pop()
            rng.shuffle(buf)
            yield from buf

        return Dataset(gen)

    def batch(
        self, batch_size: int, drop_remainder: bool = False
    ) -> "Dataset":
        # one grouping loop (batch_list) serves both the stacked and the
        # raw-list batch APIs, so remainder semantics cannot diverge
        ds = self.batch_list(batch_size)
        if drop_remainder:
            ds = ds.filter(lambda acc: len(acc) == batch_size)
        return ds.map(_stack)

    def batch_list(self, batch_size: int) -> "Dataset":
        """Group elements into plain lists WITHOUT stacking — the raw
        half of the fused decode+batch fast path (the list feeds one
        native ``decode_example_batch`` call)."""
        parent = self._source

        def gen():
            acc: list = []
            for x in parent():
                acc.append(x)
                if len(acc) == batch_size:
                    yield acc
                    acc = []
            if acc:
                yield acc

        return Dataset(gen)

    def repeat(self, count: int = -1) -> "Dataset":
        parent = self._source

        def gen():
            n = 0
            while count < 0 or n < count:
                yielded = False
                for x in parent():
                    yielded = True
                    yield x
                if not yielded:
                    return
                n += 1

        return Dataset(gen)

    def take(self, count: int) -> "Dataset":
        parent = self._source

        def gen():
            for i, x in enumerate(parent()):
                if i >= count:
                    return
                yield x

        return Dataset(gen)

    def prefetch(self, buffer_size: int = 2) -> "Dataset":
        parent = self._source

        def gen():
            q: queue.Queue = queue.Queue(maxsize=buffer_size)
            _END = object()
            error: list = []
            # consumers may abandon the iterator mid-stream (an eval
            # loop breaking on error, a `take`, a GC'd generator): the
            # producer must notice and exit, or it blocks in q.put
            # forever and leaks a thread + its buffered batches per
            # abandoned stream
            closed = threading.Event()

            def _put_while_open(item) -> bool:
                while not closed.is_set():
                    try:
                        q.put(item, timeout=0.2)
                        return True
                    except queue.Full:
                        continue
                return False

            def producer():
                try:
                    for x in parent():
                        if not _put_while_open(x):
                            return
                except BaseException as e:  # noqa: BLE001 - re-raised below
                    error.append(e)
                finally:
                    _put_while_open(_END)

            t = threading.Thread(target=producer, daemon=True)
            t.start()
            try:
                while True:
                    x = q.get()
                    if x is _END:
                        if error:
                            raise error[0]
                        return
                    yield x
            finally:
                closed.set()

        return Dataset(gen)

    # ---- consumption ------------------------------------------------------

    def __iter__(self) -> Iterator:
        return self._source()

    def as_numpy(self) -> list:
        return list(self)


# records shuffled ahead of the vectorized parse, matching the model
# zoo's per-record convention (e.g. mnist dataset_fn: shuffle(1024, seed=0)).
# DEFAULT_SHUFFLE_POLICY is THE default for every path that honors the
# module-owned ``batch_shuffle = (buffer, seed)`` policy — the classic
# fast path here and the vectorized window shuffle
# (fast_pipeline._shuffle_policy import it, so the two paths cannot
# silently diverge on buffer or seed).
_SHUFFLE_BUFFER = 1024
DEFAULT_SHUFFLE_POLICY = (_SHUFFLE_BUFFER, 0)


def batched_model_pipeline(
    ds: Dataset,
    spec,
    mode,
    metadata,
    batch_size: int,
    shuffle_records: bool = False,
    prefetch: int = 0,
) -> Dataset:
    """Raw-record dataset -> batched model-input dataset.

    The one pipeline builder shared by every runtime (task-stream worker,
    lockstep worker, local executor).  When the model module defines the
    vectorized ``batch_parse(example_batch, mode)`` hook, records are
    grouped raw and decoded by ONE native ``decode_example_batch`` call
    per minibatch (the fused decode+batch fast path, ~40x the per-record
    decode); otherwise the reference-style per-record ``dataset_fn``
    composes with ``batch`` (reference worker.py:972-977).

    ``shuffle_records`` applies only to the fast path — in the classic
    path shuffling belongs to ``dataset_fn`` (model-owned).  Fast-path
    models keep that ownership through an optional module attribute
    ``batch_shuffle = (buffer, seed)`` (or ``None`` to disable); the
    default matches the zoo convention.  The batch count is identical
    either way: shuffling never crosses the dataset boundary, so
    lockstep's steps-per-task invariant holds.  (``shuffle_records`` is a
    plain bool rather than derived from ``mode`` here to keep this module
    free of the trainer's ``Modes`` import.)
    """
    batch_parse = getattr(spec, "batch_parse", None)
    if batch_parse is not None:
        from elasticdl_tpu.data.reader import decode_example_batch

        policy = getattr(
            getattr(spec, "module", None),
            "batch_shuffle",
            DEFAULT_SHUFFLE_POLICY,
        )
        if shuffle_records and policy is not None:
            buffer_size, seed = policy
            ds = ds.shuffle(buffer_size, seed=seed)
        out = ds.batch_list(batch_size).map(
            lambda recs: batch_parse(decode_example_batch(recs), mode)
        )
    else:
        if spec.dataset_fn is not None:
            ds = spec.dataset_fn(ds, mode, metadata)
        out = ds.batch(batch_size)
    if prefetch:
        out = out.prefetch(prefetch)
    return out
