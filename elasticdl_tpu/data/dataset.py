"""Numpy dataset pipeline with threaded prefetch.

Replaces the reference's tf.data usage (``worker.py:972-977``:
``dataset_fn(ds, mode, metadata)`` then ``.batch().prefetch(1)``) with a
small composable pipeline that produces host numpy batches ready for
``jax.device_put``.  Transformations are lazy; each ``__iter__`` restarts
from the source, so a dataset built over a task's record range can be
re-consumed on retry.

The model-zoo ``dataset_fn(dataset, mode, metadata)`` contract operates on
this class: readers produce raw records, ``map`` decodes them, the worker
applies ``batch``/``prefetch``.
"""

from __future__ import annotations

import queue
import random
import threading
from typing import Any, Callable, Iterable, Iterator

import numpy as np


def _stack(elements: list):
    """Stack a list of pipeline elements into one batched element.

    Handles dicts (by key), tuples/lists (by position), scalars and
    ndarrays (np.stack).
    """
    first = elements[0]
    if isinstance(first, dict):
        return {k: _stack([e[k] for e in elements]) for k in first}
    if isinstance(first, (tuple, list)):
        cols = [_stack([e[i] for e in elements]) for i in range(len(first))]
        return tuple(cols) if isinstance(first, tuple) else cols
    return np.stack([np.asarray(e) for e in elements])


class Dataset:
    def __init__(self, source: Callable[[], Iterator]):
        self._source = source

    # ---- constructors -----------------------------------------------------

    @staticmethod
    def from_generator(gen_factory: Callable[[], Iterable]) -> "Dataset":
        return Dataset(lambda: iter(gen_factory()))

    @staticmethod
    def from_records(records: Iterable) -> "Dataset":
        materialized = records if isinstance(records, list) else list(records)
        return Dataset(lambda: iter(materialized))

    # ---- transformations --------------------------------------------------

    def map(self, fn: Callable[[Any], Any]) -> "Dataset":
        parent = self._source
        return Dataset(lambda: (fn(x) for x in parent()))

    def filter(self, predicate: Callable[[Any], bool]) -> "Dataset":
        parent = self._source
        return Dataset(lambda: (x for x in parent() if predicate(x)))

    def shuffle(self, buffer_size: int, seed: int | None = None) -> "Dataset":
        parent = self._source

        def gen():
            rng = random.Random(seed)
            buf: list = []
            for x in parent():
                buf.append(x)
                if len(buf) >= buffer_size:
                    idx = rng.randrange(len(buf))
                    buf[idx], buf[-1] = buf[-1], buf[idx]
                    yield buf.pop()
            rng.shuffle(buf)
            yield from buf

        return Dataset(gen)

    def batch(
        self, batch_size: int, drop_remainder: bool = False
    ) -> "Dataset":
        parent = self._source

        def gen():
            acc: list = []
            for x in parent():
                acc.append(x)
                if len(acc) == batch_size:
                    yield _stack(acc)
                    acc = []
            if acc and not drop_remainder:
                yield _stack(acc)

        return Dataset(gen)

    def repeat(self, count: int = -1) -> "Dataset":
        parent = self._source

        def gen():
            n = 0
            while count < 0 or n < count:
                yielded = False
                for x in parent():
                    yielded = True
                    yield x
                if not yielded:
                    return
                n += 1

        return Dataset(gen)

    def take(self, count: int) -> "Dataset":
        parent = self._source

        def gen():
            for i, x in enumerate(parent()):
                if i >= count:
                    return
                yield x

        return Dataset(gen)

    def prefetch(self, buffer_size: int = 2) -> "Dataset":
        parent = self._source

        def gen():
            q: queue.Queue = queue.Queue(maxsize=buffer_size)
            _END = object()
            error: list = []

            def producer():
                try:
                    for x in parent():
                        q.put(x)
                except BaseException as e:  # noqa: BLE001 - re-raised below
                    error.append(e)
                finally:
                    q.put(_END)

            t = threading.Thread(target=producer, daemon=True)
            t.start()
            while True:
                x = q.get()
                if x is _END:
                    if error:
                        raise error[0]
                    return
                yield x

        return Dataset(gen)

    # ---- consumption ------------------------------------------------------

    def __iter__(self) -> Iterator:
        return self._source()

    def as_numpy(self) -> list:
        return list(self)
