"""Vectorized task pipeline: scanner chunks -> native batch decode ->
windowed numpy shuffle -> sliced minibatches.

This is the data plane's hot path.  The classic pipeline
(``dataset.batched_model_pipeline``) moves every record through a chain of
Python generators (read -> shuffle buffer -> batch grouping -> decode) —
3-4 microseconds of interpreter work per record, which on a single-core
host caps end-to-end training at ~250k records/sec regardless of how fast
the chip is.  The reference leaned on tf.data's C++ runtime for exactly
this reason (``elasticdl/python/worker/worker.py:972-977`` builds
``dataset_fn(...).batch().prefetch(1)`` over a C++ pipeline).

Here the per-record work is zero Python objects end to end:

- the EDLIO scanner fills ONE reusable buffer with a few thousand
  concatenated payloads per FFI call (``recordio._NativeScanner.next_chunk``),
- ``decode_concat_batch`` decodes that buffer straight into ``(N, ...)``
  batch arrays (one ``memcpy`` per (record, feature), all in C),
- shuffling is a numpy row permutation over a decode window (default one
  task), and minibatches are array slices.

The model's ``batch_parse(example_batch, mode)`` hook then maps raw
columns to (features, labels) exactly as in the classic fast path.

Eligibility is probed, not assumed: the first chunk must decode natively
(uniform schema, wire-format dtypes).  If it doesn't — or the model has
no ``batch_parse``, or the reader no ``read_record_chunks`` — callers get
the classic pipeline via :func:`build_task_batches`, the chooser shared
by ALL the per-task runtimes: LocalExecutor, the lockstep worker, and
the task-stream worker (training since r5 — ``worker.py
_train_task_stream`` — plus its eval/predict task paths; the exactly-
once accounting takes per-batch counts, so it is pipeline-agnostic).

Shuffle semantics: the classic path streams records through a
``shuffle(buffer, seed)`` reservoir; here the same ``batch_shuffle``
module policy seeds a numpy permutation over the decode window (>= the
reservoir, typically the whole task) — a strictly stronger local shuffle,
equally deterministic, and identical across lockstep processes because it
is a pure function of (policy seed, task range).  The BATCH COUNT is
identical to the classic path (full batches plus one final partial), so
lockstep's steps-per-task invariant holds on either path.
"""

from __future__ import annotations

from typing import Iterator

import jax
import numpy as np

from elasticdl_tpu.data.dataset import (
    DEFAULT_SHUFFLE_POLICY,
    Dataset,
    batched_model_pipeline,
)
from elasticdl_tpu.data.reader import (
    decode_concat_batch,
    decode_example,
)

# decode window cap: rows are accumulated (decoded) up to this many bytes
# before a shuffle+emit flush.  64 MiB keeps worst-case resident window
# memory small next to model state while giving a far deeper shuffle than
# the classic path's 1024-record reservoir.
_WINDOW_BYTES = 64 << 20



class FallbackNeeded(Exception):
    """First chunk failed the native decode probe: schema drift, sparse
    frames, or no native codec — take the classic per-record path."""


def _vectorized_task_batches(
    reader,
    task,
    batch_parse,
    mode,
    batch_size: int,
    shuffle_seed: int | None,
    window_bytes: int = _WINDOW_BYTES,
    stack_k: int | None = None,
    stack_divisor: int = 1,
) -> Iterator:
    """Yield parsed minibatches of ``task``'s records, all-C/numpy per
    record.  Raises :class:`FallbackNeeded` before the first yield if the
    first chunk does not decode natively.

    ``stack_k`` (training runtimes): emit runs of ``stack_k`` full
    batches as :class:`~elasticdl_tpu.trainer.stacking.PreStacked`
    dispatch groups — ``batch_parse`` applied ONCE over the k*B rows and
    the result reshaped ``(k, B, ...)``, a zero-copy view of the
    contiguous permuted window (valid because batch_parse is row-wise by
    contract: batch composition is arbitrary).  Requires ``batch_size``
    divisible by ``stack_divisor`` (the mesh's batch divisor, so the
    padding step the plain path applies would be a no-op); leftover full
    batches and the final partial batch are emitted plain."""
    if stack_k is not None and stack_k != "auto" and stack_k < 2:
        stack_k = None
    if stack_k is not None and batch_size % max(1, stack_divisor):
        stack_k = None
    chunks = reader.read_record_chunks(task)
    first = next(iter(chunks), None)
    if first is None:
        return
    buf, lengths = first
    template = decode_example(bytes(memoryview(buf)[: int(lengths[0])]))
    decoded = decode_concat_batch(buf, lengths, template)
    if decoded is None:
        raise FallbackNeeded(task.shard_name)

    row_bytes = max(1, sum(v.nbytes for v in template.values()))
    window_rows = max(batch_size, window_bytes // row_bytes)
    rng = (
        np.random.RandomState(shuffle_seed)
        if shuffle_seed is not None
        else None
    )

    if stack_k is not None:
        # probe one parsed batch: prediction-shaped parses (no labels)
        # cannot group, whatever the caller asked for
        n0 = min(batch_size, int(len(lengths)))
        sample = batch_parse(
            {k: v[:n0] for k, v in decoded.items()}, mode
        )
        if not isinstance(sample, tuple):
            stack_k = None
        elif stack_k == "auto":
            # size the dispatch group from the PARSED wire bytes of one
            # batch (scaled from however many rows the first chunk
            # holds) — the same rule run_stacked_steps would apply
            from elasticdl_tpu.trainer.stacking import (
                auto_steps_per_dispatch,
                measured_dispatch_overhead,
            )

            sample_bytes = sum(
                np.asarray(leaf).nbytes
                for leaf in jax.tree_util.tree_leaves(sample)
            )
            stack_k = auto_steps_per_dispatch(
                int(sample_bytes / max(1, n0) * batch_size),
                measured_dispatch_overhead(),
            )
            if stack_k < 2:
                stack_k = None

    window: list[dict] = [decoded]
    pending = int(len(lengths))
    carry: dict | None = None

    def _flush(final: bool):
        nonlocal window, pending, carry
        parts = ([carry] if carry else []) + window
        window, pending = [], 0
        if not parts:
            return
        if len(parts) == 1:
            merged = parts[0]
        else:
            merged = {
                k: np.concatenate([p[k] for p in parts]) for k in parts[0]
            }
        n = len(next(iter(merged.values())))
        if rng is not None:
            perm = rng.permutation(n)
            merged = {k: v[perm] for k, v in merged.items()}
        full = n // batch_size * batch_size
        lo = 0
        if stack_k is not None:
            from elasticdl_tpu.trainer.stacking import PreStacked

            # a window smaller than k full batches still groups — one
            # PreStacked of however many full batches it holds (e.g. a
            # 32-batch task under auto k=36 dispatches as one scan-32)
            k_eff = min(stack_k, full // batch_size)
            group_rows = max(1, k_eff) * batch_size
            while k_eff >= 2 and full - lo >= group_rows:
                parsed = batch_parse(
                    {
                        k: v[lo : lo + group_rows]
                        for k, v in merged.items()
                    },
                    mode,
                )
                feats, labels = parsed
                stacked_f = jax.tree_util.tree_map(
                    lambda a: a.reshape(
                        (k_eff, batch_size) + a.shape[1:]
                    ),
                    feats,
                )
                stacked_l = jax.tree_util.tree_map(
                    lambda a: a.reshape(
                        (k_eff, batch_size) + a.shape[1:]
                    ),
                    labels,
                )
                yield PreStacked(
                    stacked_f,
                    stacked_l,
                    group_rows,
                    jax.tree_util.tree_map(lambda a: a[0], stacked_f),
                )
                lo += group_rows
        for lo in range(lo, full, batch_size):
            yield batch_parse(
                {k: v[lo : lo + batch_size] for k, v in merged.items()},
                mode,
            )
        if full < n:
            tail = {k: v[full:] for k, v in merged.items()}
            if final:
                yield batch_parse(tail, mode)
                carry = None
            else:
                carry = tail
        else:
            carry = None

    for buf, lengths in chunks:
        # mid-task schema drift cannot fall back (batches already
        # yielded; a restart would re-train records): surface it
        decoded = decode_concat_batch(buf, lengths, template)
        if decoded is None:
            raise RuntimeError(
                f"record schema changed mid-shard in {task.shard_name} "
                f"[{task.start}, {task.end}): the vectorized decoder "
                "requires a uniform schema per shard"
            )
        window.append(decoded)
        pending += int(len(lengths))
        if pending >= window_rows:
            yield from _flush(final=False)
    yield from _flush(final=True)


def _shuffle_policy(spec, shuffle_records: bool) -> int | None:
    """None = no shuffle; else the permutation seed (module-owned
    ``batch_shuffle`` policy, same contract as the classic fast path)."""
    if not shuffle_records:
        return None
    policy = getattr(
        getattr(spec, "module", None),
        "batch_shuffle",
        DEFAULT_SHUFFLE_POLICY,
    )
    if policy is None:
        return None
    _buffer, seed = policy
    return int(seed)


def build_task_batches(
    reader,
    task,
    spec,
    mode,
    metadata,
    batch_size: int,
    shuffle_records: bool = False,
    prefetch: int = 0,
    require_deterministic_choice: bool = False,
    stack_k: int | None = None,
    stack_divisor: int = 1,
) -> Dataset:
    """THE task -> minibatch-stream chooser for per-task runtimes.

    Vectorized fast path when the model defines ``batch_parse`` and the
    reader exposes raw chunks; classic ``batched_model_pipeline``
    otherwise (and automatically — via a first-chunk probe — for data the
    native decoder cannot batch).  Returns a :class:`Dataset` either way,
    so callers can re-iterate a task on retry.

    ``require_deterministic_choice`` (lockstep worlds): the two paths
    shuffle differently (windowed permutation vs 1024-record reservoir),
    so every process must take the SAME path.  The first-chunk probe is
    a pure function of the shard data — identical everywhere — but
    native-codec availability is per-host; under this flag a host that
    WOULD take the fast path but lacks the codec raises instead of
    silently training on a different batch stream than its peers.
    """
    batch_parse = getattr(spec, "batch_parse", None)
    chunk_reader = getattr(reader, "read_record_chunks", None)
    if (
        require_deterministic_choice
        and batch_parse is not None
        and chunk_reader is not None
    ):
        from elasticdl_tpu.data import recordio

        if not recordio.native_available():
            raise RuntimeError(
                "lockstep data-path divergence: this process lacks the "
                "native EDLIO codec (_native.so), so it would silently "
                "shuffle different batches than peers taking the "
                "vectorized path. Build it (python -m "
                "elasticdl_tpu.data.recordio.build) or deploy one image "
                "for all workers."
            )

    def classic(prefetch_n: int = prefetch) -> Dataset:
        return batched_model_pipeline(
            Dataset.from_generator(lambda: reader.read_records(task)),
            spec,
            mode,
            metadata,
            batch_size,
            shuffle_records=shuffle_records,
            prefetch=prefetch_n,
        )

    if batch_parse is None or chunk_reader is None:
        return classic()
    seed = _shuffle_policy(spec, shuffle_records)

    def gen():
        fast = _vectorized_task_batches(
            reader,
            task,
            batch_parse,
            mode,
            batch_size,
            seed,
            stack_k=stack_k,
            stack_divisor=stack_divisor,
        )
        try:
            first = next(fast)
        except (FallbackNeeded, StopIteration):
            # probe failed (or empty task): identical record stream via
            # the classic path; nothing has been yielded yet.  The
            # OUTER wrapper below already prefetches — an inner layer
            # here would double-buffer and spawn a second thread
            yield from classic(prefetch_n=0)
            return
        yield first
        yield from fast

    out = Dataset(gen)
    if prefetch:
        # same decode/compute overlap the classic path gets: matters for
        # the eval/predict loops, which consume the task pipeline on the
        # main thread (training overlaps one level up, TaskPrefetcher)
        out = out.prefetch(prefetch)
    return out
