"""Synthetic dataset generators writing EDLIO shards.

Reference: ``elasticdl/python/data/recordio_gen/`` (census / frappe /
heart / mnist generators, ~610 LoC).  The cluster this build runs on has no
egress, so instead of downloading the real datasets the generators emit
*learnable* synthetic data with the same schema: each class is a random
template plus noise, so models genuinely converge and accuracy assertions
are meaningful (the reference's own tier-2 tests use generated data the
same way, ``test_utils.py:92-162``).
"""
