"""Learnable synthetic datasets with the reference model zoo's schemas.

Each ``gen_*`` function writes EDLIO shard files into ``out_dir`` and
returns the directory.  Records use the framework example codec
(:func:`elasticdl_tpu.data.reader.encode_example`).

Schemas mirror the reference datasets:

- mnist:   image uint8 [28,28],   label int64          (mnist_*.py models)
- cifar10: image uint8 [32,32,3], label int64          (cifar10_*.py models)
- frappe:  feature int64 [10] sparse ids, label int64  (deepfm_*.py models)
- census:  13 named columns + label                    (census_dnn_model)
- heart:   13 named columns + target                   (heart_functional_api)
- iris:    4 float features, label int64               (odps_iris_dnn_model)
"""

from __future__ import annotations

import os

import numpy as np

from elasticdl_tpu.data import recordio
from elasticdl_tpu.data.reader import encode_example


def _write_shards(out_dir, name, examples, num_shards):
    os.makedirs(out_dir, exist_ok=True)
    per = (len(examples) + num_shards - 1) // num_shards
    for s in range(num_shards):
        chunk = examples[s * per : (s + 1) * per]
        if not chunk:
            continue
        with recordio.Writer(
            os.path.join(out_dir, f"{name}-{s:03d}.edlio")
        ) as w:
            for ex in chunk:
                w.write(encode_example(ex))
    return out_dir


def _class_template_images(rng, num_classes, shape):
    """One smooth random template per class; samples = template + noise."""
    templates = rng.uniform(0, 255, size=(num_classes, *shape))
    return templates


def gen_mnist(
    out_dir: str,
    num_records: int = 2048,
    num_shards: int = 4,
    seed: int = 0,
    image_shape=(28, 28),
    num_classes: int = 10,
):
    # class templates come from a fixed RNG so train/eval/predict splits
    # (different `seed`s) share one underlying distribution
    templates = _class_template_images(
        np.random.RandomState(1234), num_classes, image_shape
    )
    rng = np.random.RandomState(seed)
    examples = []
    for _ in range(num_records):
        label = rng.randint(num_classes)
        img = templates[label] + rng.normal(0, 32.0, size=image_shape)
        examples.append(
            {
                "image": np.clip(img, 0, 255).astype(np.uint8),
                "label": np.int64(label),
            }
        )
    return _write_shards(out_dir, "mnist", examples, num_shards)


def gen_cifar10(
    out_dir: str, num_records: int = 1024, num_shards: int = 4, seed: int = 0
):
    templates = _class_template_images(
        np.random.RandomState(1234), 10, (32, 32, 3)
    )
    rng = np.random.RandomState(seed)
    examples = []
    for _ in range(num_records):
        label = rng.randint(10)
        img = templates[label] + rng.normal(0, 32.0, size=(32, 32, 3))
        examples.append(
            {
                "image": np.clip(img, 0, 255).astype(np.uint8),
                "label": np.int64(label),
            }
        )
    return _write_shards(out_dir, "cifar10", examples, num_shards)


def gen_frappe(
    out_dir: str,
    num_records: int = 4096,
    num_shards: int = 4,
    seed: int = 0,
    num_features: int = 10,
    vocab_size: int = 5383,
):
    """Sparse-id dataset for the DeepFM models: the label is a function of a
    hidden per-id weight vector so factorization models can learn it."""
    id_weights = np.random.RandomState(1234).normal(0, 1.0, size=vocab_size)
    rng = np.random.RandomState(seed)
    examples = []
    for _ in range(num_records):
        ids = rng.randint(0, vocab_size, size=num_features).astype(np.int64)
        score = id_weights[ids].sum()
        examples.append(
            {"feature": ids, "label": np.int64(score > 0)}
        )
    return _write_shards(out_dir, "frappe", examples, num_shards)


CENSUS_NUMERIC = ["age", "capital-gain", "capital-loss", "hours-per-week"]
CENSUS_CATEGORICAL = [
    "workclass",
    "education",
    "marital-status",
    "occupation",
    "relationship",
    "race",
    "sex",
    "native-country",
    "education-num",
]
CENSUS_VOCAB = 100


def gen_census(
    out_dir: str,
    num_records: int = 4096,
    num_shards: int = 4,
    seed: int = 0,
    vocab_size: int = CENSUS_VOCAB,
):
    rng_w = np.random.RandomState(1234)
    cat_weights = {
        c: rng_w.normal(0, 1.0, size=vocab_size) for c in CENSUS_CATEGORICAL
    }
    num_weights = rng_w.normal(0, 1.0, size=len(CENSUS_NUMERIC))
    rng = np.random.RandomState(seed)
    examples = []
    for _ in range(num_records):
        numeric = rng.normal(0, 1.0, size=len(CENSUS_NUMERIC))
        cats = {
            c: np.int64(rng.randint(vocab_size))
            for c in CENSUS_CATEGORICAL
        }
        score = float(numeric @ num_weights) + sum(
            cat_weights[c][int(v)] for c, v in cats.items()
        )
        ex = {
            name: np.float32(val)
            for name, val in zip(CENSUS_NUMERIC, numeric)
        }
        ex.update(cats)
        ex["label"] = np.int64(score > 0)
        examples.append(ex)
    return _write_shards(out_dir, "census", examples, num_shards)


HEART_COLUMNS = [
    "age",
    "sex",
    "cp",
    "trestbps",
    "chol",
    "fbs",
    "restecg",
    "thalach",
    "exang",
    "oldpeak",
    "slope",
    "ca",
    "thal",
]


def gen_heart(
    out_dir: str, num_records: int = 2048, num_shards: int = 2, seed: int = 0
):
    weights = np.random.RandomState(1234).normal(0, 1.0, size=len(HEART_COLUMNS))
    rng = np.random.RandomState(seed)
    examples = []
    for _ in range(num_records):
        feats = rng.normal(0, 1.0, size=len(HEART_COLUMNS))
        ex = {
            name: np.float32(v) for name, v in zip(HEART_COLUMNS, feats)
        }
        ex["target"] = np.int64(feats @ weights > 0)
        examples.append(ex)
    return _write_shards(out_dir, "heart", examples, num_shards)


def gen_iris(
    out_dir: str, num_records: int = 512, num_shards: int = 2, seed: int = 0
):
    centers = np.random.RandomState(1234).normal(0, 3.0, size=(3, 4))
    rng = np.random.RandomState(seed)
    examples = []
    for _ in range(num_records):
        label = rng.randint(3)
        feats = centers[label] + rng.normal(0, 0.5, size=4)
        examples.append(
            {
                "features": feats.astype(np.float32),
                "label": np.int64(label),
            }
        )
    return _write_shards(out_dir, "iris", examples, num_shards)


def gen_sequence(
    out_dir: str,
    num_records: int = 1024,
    num_shards: int = 2,
    seed: int = 0,
    seq_len: int = 128,
    vocab: int = 256,
    noise: float = 0.05,
):
    """Token sequences for the long-context transformer: a fixed random
    permutation Markov chain (next = perm[cur], flipped to a random token
    with prob ``noise``), so next-token prediction is learnable to
    ~(1 - noise) accuracy.  Records carry seq_len + 1 tokens; dataset_fn
    shifts them into (input, target) pairs."""
    perm = np.random.RandomState(1234).permutation(vocab)
    rng = np.random.RandomState(seed)
    examples = []
    for _ in range(num_records):
        tokens = np.empty(seq_len + 1, dtype=np.int64)
        tokens[0] = rng.randint(vocab)
        for t in range(1, seq_len + 1):
            if rng.rand() < noise:
                tokens[t] = rng.randint(vocab)
            else:
                tokens[t] = perm[tokens[t - 1]]
        examples.append({"tokens": tokens})
    return _write_shards(out_dir, "sequence", examples, num_shards)


GENERATORS = {
    "mnist": gen_mnist,
    "sequence": gen_sequence,
    "cifar10": gen_cifar10,
    "frappe": gen_frappe,
    "census": gen_census,
    "heart": gen_heart,
    "iris": gen_iris,
}


def main(argv=None):
    import argparse

    p = argparse.ArgumentParser(description="Generate synthetic EDLIO data")
    p.add_argument("dataset", choices=sorted(GENERATORS))
    p.add_argument("out_dir")
    p.add_argument("--num_records", type=int, default=None)
    p.add_argument("--num_shards", type=int, default=4)
    p.add_argument("--seed", type=int, default=0)
    a = p.parse_args(argv)
    kwargs = dict(num_shards=a.num_shards, seed=a.seed)
    if a.num_records:
        kwargs["num_records"] = a.num_records
    out = GENERATORS[a.dataset](a.out_dir, **kwargs)
    print(out)


if __name__ == "__main__":
    main()
