"""Frappe (libfm format) → EDLIO shards for the DeepFM models.

Reference: ``elasticdl/python/data/recordio_gen/frappe_recordio_gen.py``
downloads ``frappe.{train,validation,test}.libfm`` and writes RecordIO.
This build parses LOCAL copies of the real libfm format instead (no
egress): one example per line, ``label idx:val idx:val ...`` — raw
feature indices are remapped to a dense contiguous id space built over
ALL splits (the reference's feature map), and each row is padded with id
0 to the corpus-wide max feature count.

Schema matches the deepfm models: ``feature`` int64 [maxlen], ``label``
int64 (the reference maps label -1/0 -> 0).

With no ``--source``, writes the learnable synthetic facsimile
(``synthetic.gen_frappe``: 10 ids per row, vocab 5383 — the real
frappe's shape).

Usage::

    python -m elasticdl_tpu.data.recordio_gen.frappe OUT_DIR \
        [--source /dir/with/frappe.train.libfm ...]
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

from elasticdl_tpu.data.recordio_gen import synthetic
from elasticdl_tpu.data.recordio_gen._writers import write_shards

SPLITS = ("train", "validation", "test")


def _split_file(source_dir: str, split: str) -> str | None:
    for name in (f"frappe.{split}.libfm", f"{split}.libfm"):
        path = os.path.join(source_dir, name)
        if os.path.exists(path):
            return path
    return None


def parse_libfm(path: str) -> tuple[list[int], list[list[int]]]:
    """One libfm file -> (labels, raw-id rows)."""
    labels, rows = [], []
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            parts = line.split()
            if not parts:
                continue
            labels.append(1 if float(parts[0]) > 0 else 0)
            rows.append([int(tok.split(":")[0]) for tok in parts[1:]])
    return labels, rows


def build_feature_map(all_rows) -> dict[int, int]:
    """Raw feature index -> dense id, 1-based (0 is the pad id) — the
    reference builds the same corpus-wide remap before conversion."""
    fmap: dict[int, int] = {}
    for rows in all_rows:
        for row in rows:
            for raw in row:
                if raw not in fmap:
                    fmap[raw] = len(fmap) + 1
    return fmap


def _examples(labels, rows, fmap, maxlen):
    for label, row in zip(labels, rows):
        ids = np.zeros(maxlen, dtype=np.int64)
        mapped = [fmap[r] for r in row]
        ids[: len(mapped)] = mapped
        yield {"feature": ids, "label": np.int64(label)}


def generate(
    out_dir: str,
    source: str | None = None,
    records_per_shard: int = 16 * 1024,
    num_records: int = 8192,
    seed: int = 0,
) -> str:
    if source:
        parsed = {}
        for split in SPLITS:
            path = _split_file(source, split)
            if path:
                parsed[split] = parse_libfm(path)
        if not parsed:
            raise ValueError(f"no frappe libfm files under {source}")
        fmap = build_feature_map(rows for _, rows in parsed.values())
        maxlen = max(
            len(row) for _, rows in parsed.values() for row in rows
        )
        for split, (labels, rows) in parsed.items():
            write_shards(
                os.path.join(out_dir, split),
                _examples(labels, rows, fmap, maxlen),
                records_per_shard,
            )
        return out_dir
    synthetic.gen_frappe(
        os.path.join(out_dir, "train"), num_records=num_records, seed=seed
    )
    synthetic.gen_frappe(
        os.path.join(out_dir, "test"),
        num_records=max(256, num_records // 8),
        num_shards=1,
        seed=seed + 1,
    )
    return out_dir


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("dir", help="Output directory")
    p.add_argument(
        "--source",
        default=None,
        help="Local dir with frappe.{train,validation,test}.libfm "
        "(omit for the synthetic facsimile)",
    )
    p.add_argument("--records_per_shard", type=int, default=16 * 1024)
    p.add_argument("--num_records", type=int, default=8192)
    a = p.parse_args(argv)
    print(
        generate(
            a.dir,
            source=a.source,
            records_per_shard=a.records_per_shard,
            num_records=a.num_records,
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
