"""Spark job converting a TAR of raw files into EDLIO shards.

Reference: ``elasticdl/python/data/recordio_gen/sample_pyspark_recordio_gen/
spark_gen_recordio.py`` — partitions the tar's file list over Spark
workers; each partition calls the model module's
``prepare_data_for_a_single_file(file_object, filename) -> bytes`` and
writes its records into per-partition shard files.

The partition body (:func:`convert_tar_partition`) is a plain function —
fully testable without Spark; :func:`main` only adds the SparkContext
fan-out, and pyspark is imported lazily so the module loads (and tests
run) on images without it.
"""

from __future__ import annotations

import argparse
import glob
import os
import tarfile

from elasticdl_tpu.data import recordio
from elasticdl_tpu.utils.log_utils import default_logger as logger
from elasticdl_tpu.utils.model_utils import load_module_from_path


def convert_tar_partition(
    tar_path: str,
    filenames,
    prepare_fn,
    output_dir: str,
    partition_id: int,
    records_per_file: int,
) -> int:
    """Convert this partition's files from the tar into EDLIO shards
    named ``data-<partition>-<counter>.edlio`` (reference
    ``process_data`` :21-64).  Pre-existing shards of the same partition
    are removed first (reruns must not mix generations).  Returns the
    record count written."""
    for stale in glob.glob(
        os.path.join(output_dir, f"data-{partition_id}-*")
    ):
        os.remove(stale)

    filename_set = set(filenames)
    written = 0
    counter = 0
    payloads: list[bytes] = []

    def _flush():
        nonlocal counter
        if not payloads:
            return
        path = os.path.join(
            output_dir, f"data-{partition_id}-{counter:04d}.edlio"
        )
        logger.info("Writing %d records to %s", len(payloads), path)
        with recordio.Writer(path) as w:
            for payload in payloads:
                w.write(payload)
        counter += 1
        payloads.clear()

    with tarfile.open(tar_path) as tar:
        for tar_info in tar.getmembers():
            if tar_info.name not in filename_set:
                continue
            fileobj = tar.extractfile(tar_info)
            if fileobj is None:
                continue
            payloads.append(prepare_fn(fileobj, tar_info.name))
            written += 1
            if len(payloads) == records_per_file:
                _flush()
    _flush()
    return written


def list_tar_data_files(tar_path: str) -> list:
    """Data file names in the tar, skipping dotfiles (reference
    main :96-102)."""
    with tarfile.open(tar_path) as tar:
        return [
            info.name
            for info in tar.getmembers()
            if tar.extractfile(info) is not None
            and not info.name.split("/")[-1].startswith(".")
        ]


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="Spark job to convert training data to EDLIO format"
    )
    parser.add_argument("--training_data_tar_file", required=True)
    parser.add_argument("--output_dir", required=True)
    parser.add_argument(
        "--model_file",
        required=True,
        help="Module exporting prepare_data_for_a_single_file",
    )
    parser.add_argument("--records_per_file", default=1024, type=int)
    parser.add_argument("--num_workers", default=2, type=int)
    args = parser.parse_args(argv)

    try:
        from pyspark import SparkContext, TaskContext
    except ImportError as e:
        raise ImportError(
            "spark_gen_recordio needs pyspark; for single-machine "
            "conversion call convert_tar_partition directly"
        ) from e

    filename_list = list_tar_data_files(args.training_data_tar_file)
    model_module = load_module_from_path(args.model_file)
    os.makedirs(args.output_dir, exist_ok=True)

    tar_path = args.training_data_tar_file
    output_dir = args.output_dir
    records_per_file = args.records_per_file
    prepare_fn = model_module.prepare_data_for_a_single_file

    def _partition(filenames):
        convert_tar_partition(
            tar_path,
            list(filenames),
            prepare_fn,
            output_dir,
            TaskContext().partitionId(),
            records_per_file,
        )
        return filenames

    sc = SparkContext()
    sc.parallelize(filename_list, args.num_workers).mapPartitions(
        _partition
    ).collect()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
