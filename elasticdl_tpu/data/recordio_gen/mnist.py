"""MNIST → EDLIO shards (train/test splits).

Reference: ``elasticdl/python/data/recordio_gen/image_label.py`` pulls
mnist via keras and writes per-split RecordIO shards.  This environment
has no network egress, so the source options are:

- ``--source DIR_OR_NPZ``: a LOCAL copy of the real dataset in its native
  distribution format (IDX files ``train-images-idx3-ubyte[.gz]`` etc.,
  or a keras-cache-layout ``mnist.npz``), parsed by
  :mod:`elasticdl_tpu.data.recordio_gen.image_label`;
- no ``--source``: a deterministic, learnable synthetic facsimile with
  the exact schema (``image`` uint8 [28,28], ``label`` int64) — enough
  signal that the zoo's MNIST CNN reaches >0.9 eval accuracy, which is
  how the no-egress CI exercises the full train-to-accuracy path.

Usage::

    python -m elasticdl_tpu.data.recordio_gen.mnist OUT_DIR \
        [--source /path/to/idx_dir_or_npz]

Output: ``OUT_DIR/train/*.edlio`` and ``OUT_DIR/test/*.edlio``.
"""

from __future__ import annotations

import argparse
import os
import sys

from elasticdl_tpu.data.recordio_gen import image_label, synthetic


def generate(
    out_dir: str,
    source: str | None = None,
    num_records: int = 8192,
    records_per_shard: int = 4096,
) -> str:
    """Write train/test EDLIO shards under ``out_dir``; returns it."""
    if source:
        splits = image_label.load_source(source)
        for split, (x, y) in splits.items():
            image_label.convert(
                x,
                y,
                os.path.join(out_dir, split),
                records_per_shard=records_per_shard,
            )
        return out_dir
    num_shards = max(1, num_records // records_per_shard)
    synthetic.gen_mnist(
        os.path.join(out_dir, "train"),
        num_records=num_records,
        num_shards=num_shards,
        seed=0,
    )
    synthetic.gen_mnist(
        os.path.join(out_dir, "test"),
        num_records=max(256, num_records // 8),
        num_shards=1,
        seed=1,
    )
    return out_dir


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("dir", help="Output directory")
    p.add_argument(
        "--source",
        default=None,
        help="Local IDX directory or mnist.npz (omit for the synthetic "
        "facsimile — no network egress here)",
    )
    p.add_argument("--num_records", type=int, default=8192)
    p.add_argument("--records_per_shard", type=int, default=4096)
    a = p.parse_args(argv)
    print(
        generate(
            a.dir,
            source=a.source,
            num_records=a.num_records,
            records_per_shard=a.records_per_shard,
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
