"""Shared shard-writing helpers for the recordio_gen converters.

One implementation of "write examples into rotating EDLIO shards" and of
the shuffled train/test split, used by every dataset converter (census,
frappe, heart, image_label, synthetic) so shard naming and rotation
behave identically across datasets.
"""

from __future__ import annotations

import os

import numpy as np

from elasticdl_tpu.data import recordio
from elasticdl_tpu.data.reader import encode_example


def write_shards(
    out_dir: str,
    examples,
    records_per_shard: int = 8192,
    prefix: str = "data",
    encode=encode_example,
) -> int:
    """Write an iterable of example dicts (or pre-encoded bytes when
    ``encode`` is None) into ``{out_dir}/{prefix}-NNNNN.edlio`` shards of
    ``records_per_shard`` records; returns the record count."""
    if records_per_shard <= 0:
        raise ValueError(
            f"records_per_shard must be positive, got {records_per_shard}"
        )
    os.makedirs(out_dir, exist_ok=True)
    shard, writer, written = 0, None, 0
    try:
        for ex in examples:
            if written % records_per_shard == 0:
                if writer is not None:
                    writer.close()
                writer = recordio.Writer(
                    os.path.join(out_dir, f"{prefix}-{shard:05d}.edlio")
                )
                shard += 1
            writer.write(encode(ex) if encode is not None else ex)
            written += 1
    finally:
        if writer is not None:
            writer.close()
    return written


def write_train_test_split(
    out_dir: str,
    examples: list,
    eval_fraction: float,
    seed: int = 0,
    records_per_shard: int = 8192,
) -> str:
    """Shuffle ``examples`` and write ``{out_dir}/train`` and
    ``{out_dir}/test`` shard directories (test gets ``eval_fraction``)."""
    order = np.random.RandomState(seed).permutation(len(examples))
    n_eval = int(len(examples) * eval_fraction)
    write_shards(
        os.path.join(out_dir, "train"),
        (examples[i] for i in order[n_eval:]),
        records_per_shard,
    )
    if n_eval:
        write_shards(
            os.path.join(out_dir, "test"),
            (examples[i] for i in order[:n_eval]),
            records_per_shard,
        )
    return out_dir
