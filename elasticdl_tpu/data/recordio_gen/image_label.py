"""Convert real image/label datasets into EDLIO shards.

Reference: ``elasticdl/python/data/recordio_gen/image_label.py`` — pulls
mnist/fashion_mnist/cifar10 via keras and writes per-split RecordIO
shards (``{dir}/{dataset}/{train,test}/data-NNNNN``).  This build has no
network egress, so it ingests LOCAL copies in the datasets' native
distribution formats instead:

- IDX (the classic ``train-images-idx3-ubyte[.gz]`` files of MNIST /
  Fashion-MNIST), parsed directly from the binary format;
- ``.npz`` archives with ``x_train/y_train/x_test/y_test`` arrays (the
  layout keras's dataset cache uses).

Output schema matches the model zoo (synthetic.py): ``image`` uint8,
``label`` int64.

Usage::

    python -m elasticdl_tpu.data.recordio_gen.image_label OUT_DIR \
        --dataset mnist --source /path/to/idx_dir_or_npz
"""

from __future__ import annotations

import argparse
import gzip
import os
import struct
import sys

import numpy as np

from elasticdl_tpu.data.recordio_gen._writers import write_shards
from elasticdl_tpu.utils.log_utils import default_logger as logger

# canonical IDX file basenames per split (gz or raw)
_IDX_FILES = {
    "train": ("train-images-idx3-ubyte", "train-labels-idx1-ubyte"),
    "test": ("t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte"),
}

_IDX_DTYPES = {
    0x08: np.uint8,
    0x09: np.int8,
    0x0B: np.dtype(">i2"),
    0x0C: np.dtype(">i4"),
    0x0D: np.dtype(">f4"),
    0x0E: np.dtype(">f8"),
}


def read_idx(path: str) -> np.ndarray:
    """Parse one IDX-format file (optionally gzipped).

    Format: 2 zero bytes, a dtype code, a dims count, then big-endian
    uint32 sizes per dim, then the raw values.
    """
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        zero1, zero2, dtype_code, ndim = struct.unpack("BBBB", f.read(4))
        if zero1 != 0 or zero2 != 0:
            raise ValueError(f"not an IDX file: {path}")
        if dtype_code not in _IDX_DTYPES:
            raise ValueError(f"unknown IDX dtype 0x{dtype_code:02x}: {path}")
        shape = struct.unpack(f">{ndim}I", f.read(4 * ndim))
        data = np.frombuffer(f.read(), dtype=_IDX_DTYPES[dtype_code])
        if data.size != int(np.prod(shape)):
            raise ValueError(
                f"IDX payload size mismatch in {path}: "
                f"{data.size} values for shape {shape}"
            )
        return data.reshape(shape)


def _find_idx(source_dir: str, basename: str) -> str | None:
    for candidate in (basename, basename + ".gz"):
        path = os.path.join(source_dir, candidate)
        if os.path.exists(path):
            return path
    return None


def load_source(source: str) -> dict[str, tuple[np.ndarray, np.ndarray]]:
    """Load ``{split: (x, y)}`` from an IDX directory or an npz file."""
    if os.path.isfile(source) and source.endswith(".npz"):
        with np.load(source) as z:
            out = {}
            for split, (xk, yk) in {
                "train": ("x_train", "y_train"),
                "test": ("x_test", "y_test"),
            }.items():
                if xk in z.files and yk in z.files:
                    out[split] = (np.asarray(z[xk]), np.asarray(z[yk]))
            if not out:
                raise ValueError(
                    f"{source} has none of x_train/y_train/x_test/y_test"
                )
            return out
    if os.path.isdir(source):
        out = {}
        for split, (img_base, lbl_base) in _IDX_FILES.items():
            img = _find_idx(source, img_base)
            lbl = _find_idx(source, lbl_base)
            if img and lbl:
                out[split] = (read_idx(img), read_idx(lbl))
        if not out:
            raise ValueError(f"no IDX files found under {source}")
        return out
    raise ValueError(f"source must be an IDX directory or .npz: {source!r}")


def convert(
    x: np.ndarray,
    y: np.ndarray,
    out_dir: str,
    records_per_shard: int = 16 * 1024,
    fraction: float = 1.0,
) -> int:
    """Write ``(x, y)`` pairs as EDLIO shards ``data-NNNNN.edlio``
    (reference convert(), image_label.py:12-58)."""
    if len(x) != len(y):
        raise ValueError(f"images/labels length mismatch: {len(x)}/{len(y)}")
    total = int(len(x) * fraction)
    written = write_shards(
        out_dir,
        (
            {
                "image": np.asarray(x[row], dtype=np.uint8),
                "label": np.int64(np.asarray(y[row]).reshape(())),
            }
            for row in range(total)
        ),
        records_per_shard,
    )
    logger.info(
        "Wrote %d of %d records under %s", written, len(x), out_dir
    )
    return written


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="Convert image datasets (IDX or npz) into EDLIO shards"
    )
    parser.add_argument("dir", help="Output directory")
    parser.add_argument(
        "--dataset",
        choices=["mnist", "fashion_mnist", "cifar10"],
        default="mnist",
    )
    parser.add_argument(
        "--source",
        required=True,
        help="IDX directory or .npz archive with the dataset",
    )
    parser.add_argument("--records_per_shard", type=int, default=16 * 1024)
    parser.add_argument(
        "--fraction",
        type=float,
        default=1.0,
        help="Fraction of each split to convert",
    )
    args = parser.parse_args(argv)
    splits = load_source(args.source)
    for split, (x, y) in splits.items():
        convert(
            x,
            y,
            os.path.join(args.dir, args.dataset, split),
            records_per_shard=args.records_per_shard,
            fraction=args.fraction,
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
