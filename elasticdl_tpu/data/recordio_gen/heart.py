"""Cleveland heart-disease CSV → EDLIO shards.

Reference: ``elasticdl/python/data/recordio_gen/heart_recordio_gen.py``
downloads ``heart.csv`` (header row; 13 features + ``target``; ``thal``
is a string categorical) and writes TF-Example RecordIO.  This build
parses a LOCAL copy of the same CSV instead (no egress).

Schema matches :mod:`elasticdl_tpu.models.heart_functional_api`: all
numeric columns float32, ``thal`` stored as a stable sha256 id (the
example codec carries tensors, not strings — same encoding note as
:mod:`.census`), ``target`` int64.

With no ``--source``, writes the learnable synthetic facsimile
(``synthetic.gen_heart``).

Usage::

    python -m elasticdl_tpu.data.recordio_gen.heart OUT_DIR \
        [--source /path/to/heart.csv] [--eval_fraction 0.2]
"""

from __future__ import annotations

import argparse
import csv
import os
import sys

import numpy as np

from elasticdl_tpu.data.recordio_gen import synthetic
from elasticdl_tpu.data.recordio_gen._writers import write_train_test_split
from elasticdl_tpu.data.recordio_gen.census import encode_categorical

LABEL_KEY = "target"
# the one string-valued column; everything else is numeric, where any
# unparsable token (the raw Cleveland data marks missing values '?') is
# a missing value, NOT a category — it must become 0.0, never a hash id
CATEGORICAL_KEYS = frozenset({"thal"})


def parse_row(row: dict) -> dict:
    ex: dict[str, np.ndarray] = {}
    for key, value in row.items():
        key = key.strip()
        value = value.strip()
        if key == LABEL_KEY:
            ex[key] = np.int64(value)
        elif key in CATEGORICAL_KEYS:
            # kept int64 so the hashed column's mod-bucketing sees exact
            # ids (thal: fixed/normal/reversible)
            ex[key] = encode_categorical(value)
        else:
            try:
                ex[key] = np.float32(value)
            except ValueError:
                ex[key] = np.float32(0.0)
    return ex


def read_source(path: str) -> list[dict]:
    with open(path, "r", encoding="utf-8", newline="") as f:
        rows = [parse_row(r) for r in csv.DictReader(f)]
    if not rows:
        raise ValueError(f"no csv rows in {path}")
    return rows


def generate(
    out_dir: str,
    source: str | None = None,
    eval_fraction: float = 0.2,
    num_records: int = 2048,
    seed: int = 0,
) -> str:
    if source:
        return write_train_test_split(
            out_dir, read_source(source), eval_fraction, seed=seed
        )
    synthetic.gen_heart(
        os.path.join(out_dir, "train"), num_records=num_records, seed=seed
    )
    synthetic.gen_heart(
        os.path.join(out_dir, "test"),
        num_records=max(256, num_records // 8),
        num_shards=1,
        seed=seed + 1,
    )
    return out_dir


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("dir", help="Output directory")
    p.add_argument(
        "--source",
        default=None,
        help="Local heart.csv (omit for the synthetic facsimile)",
    )
    p.add_argument("--eval_fraction", type=float, default=0.2)
    p.add_argument("--num_records", type=int, default=2048)
    a = p.parse_args(argv)
    print(
        generate(
            a.dir,
            source=a.source,
            eval_fraction=a.eval_fraction,
            num_records=a.num_records,
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
