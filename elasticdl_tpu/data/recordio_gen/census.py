"""UCI Adult ("census income") → EDLIO shards.

Reference: ``elasticdl/python/data/recordio_gen/census_recordio_gen.py``
downloads ``adult.data`` and writes TF-Example RecordIO.  This build
parses a LOCAL copy of the real file format instead (no egress):
comma-separated with optional spaces, 14 feature fields + income label,
``?`` for missing values, label ``>50K``/``<=50K``.

Schema matches the census model variants
(:mod:`elasticdl_tpu.models.census_dnn_model`):

- numeric float32: age, capital-gain, capital-loss, hours-per-week
- categorical int64: workclass, education, marital-status, occupation,
  relationship, race, sex, native-country (string values are stored as
  stable sha256 ids — the framework example codec carries tensors, not
  strings; see :func:`encode_categorical`), education-num (already
  integral)
- label int64 (1 = income >50K)

With no ``--source``, writes the learnable synthetic facsimile with the
same schema (``synthetic.gen_census``).

Usage::

    python -m elasticdl_tpu.data.recordio_gen.census OUT_DIR \
        [--source /path/to/adult.data] [--eval_fraction 0.2]
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

from elasticdl_tpu.data.recordio_gen import synthetic
from elasticdl_tpu.data.recordio_gen._writers import write_train_test_split
from elasticdl_tpu.utils.hash_utils import string_to_id

# adult.data field order (UCI "adult" names file)
FIELDS = [
    "age",
    "workclass",
    "fnlwgt",
    "education",
    "education-num",
    "marital-status",
    "occupation",
    "relationship",
    "race",
    "sex",
    "capital-gain",
    "capital-loss",
    "hours-per-week",
    "native-country",
    "label",
]

NUMERIC = list(synthetic.CENSUS_NUMERIC)
CATEGORICAL_STR = [
    c for c in synthetic.CENSUS_CATEGORICAL if c != "education-num"
]

# String categoricals are stored as sha256 ids mod 2**32.  A downstream
# hashed column with a power-of-two bucket count B <= 2**32 then lands
# each value in the SAME bucket as hashing the raw string would
# (sha256(v) mod 2**32 mod B == sha256(v) mod B when B divides 2**32);
# the census columns use 64 buckets, so parity holds exactly.
_STR_ID_SPACE = 2**32


def encode_categorical(value: str) -> np.int64:
    return np.int64(string_to_id(value, _STR_ID_SPACE))


def parse_line(line: str) -> dict | None:
    """One adult.data row -> example dict (None for blank/short rows).

    Missing values (``?``): numeric -> 0, categorical -> hashed "?" id
    (a consistent bucket of its own, which is how hashed columns treat
    any unseen token anyway).
    """
    parts = [p.strip() for p in line.strip().rstrip(".").split(",")]
    if len(parts) != len(FIELDS):
        return None
    row = dict(zip(FIELDS, parts))
    ex: dict[str, np.ndarray] = {}
    for k in NUMERIC:
        try:
            ex[k] = np.float32(row[k])
        except ValueError:
            ex[k] = np.float32(0.0)
    for k in CATEGORICAL_STR:
        ex[k] = encode_categorical(row[k])
    try:
        ex["education-num"] = np.int64(row["education-num"])
    except ValueError:
        ex["education-num"] = np.int64(0)
    ex["label"] = np.int64(">50K" in row["label"])
    return ex


def read_source(path: str) -> list[dict]:
    examples = []
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            ex = parse_line(line)
            if ex is not None:
                examples.append(ex)
    if not examples:
        raise ValueError(f"no parseable adult.data rows in {path}")
    return examples


def generate(
    out_dir: str,
    source: str | None = None,
    eval_fraction: float = 0.2,
    records_per_shard: int = 8192,
    num_records: int = 8192,
    seed: int = 0,
) -> str:
    if source:
        return write_train_test_split(
            out_dir,
            read_source(source),
            eval_fraction,
            seed=seed,
            records_per_shard=records_per_shard,
        )
    synthetic.gen_census(
        os.path.join(out_dir, "train"), num_records=num_records, seed=seed
    )
    synthetic.gen_census(
        os.path.join(out_dir, "test"),
        num_records=max(256, num_records // 8),
        num_shards=1,
        seed=seed + 1,
    )
    return out_dir


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("dir", help="Output directory")
    p.add_argument(
        "--source",
        default=None,
        help="Local adult.data file (omit for the synthetic facsimile)",
    )
    p.add_argument("--eval_fraction", type=float, default=0.2)
    p.add_argument("--records_per_shard", type=int, default=8192)
    p.add_argument("--num_records", type=int, default=8192)
    a = p.parse_args(argv)
    print(
        generate(
            a.dir,
            source=a.source,
            eval_fraction=a.eval_fraction,
            records_per_shard=a.records_per_shard,
            num_records=a.num_records,
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
