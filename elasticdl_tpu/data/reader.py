"""Data reader contract + example record codec.

Reference: ``elasticdl/python/data/reader/data_reader.py`` — the ABC every
reader implements (``read_records(task)``, ``create_shards()``,
``records_output_types``, ``Metadata``) that ties the data layer to the
task dispatcher: ``create_shards()`` output is exactly the shard dict the
dispatcher slices into tasks.
"""

from __future__ import annotations

import abc
from dataclasses import dataclass, field
from typing import Any, Iterator

import numpy as np

from elasticdl_tpu.utils.tensor import (
    deserialize_tensors,
    ndarray_to_tensor,
    serialize_tensors,
)


@dataclass
class Metadata:
    """Schema info a reader can surface to ``dataset_fn``
    (reference data_reader.py:40-49)."""

    column_names: list[str] = field(default_factory=list)
    column_dtypes: dict[str, Any] = field(default_factory=dict)
    extra: dict = field(default_factory=dict)


class AbstractDataReader(abc.ABC):
    def __init__(self, **kwargs):
        self._kwargs = kwargs

    @abc.abstractmethod
    def read_records(self, task) -> Iterator:
        """Yield the raw records of ``task``'s range [task.start, task.end)."""

    @abc.abstractmethod
    def create_shards(self) -> dict[str, tuple[int, int]]:
        """Map shard_name -> (start_index, num_records)."""

    @property
    def records_output_types(self):
        """Dtype hint for the record stream (bytes by default)."""
        return bytes

    @property
    def metadata(self) -> Metadata:
        return Metadata()


def encode_example(features: dict[str, np.ndarray]) -> bytes:
    """Standard record payload: a named-tensor dict (framework codec used by
    the synthetic dataset generators and the built-in model zoo).

    Replaces the reference's TF Example/RecordIO payloads with the
    framework's own tensor frames — no TF proto dependency.
    """
    return serialize_tensors(
        {k: ndarray_to_tensor(k, v) for k, v in features.items()}
    )


def decode_example(payload: bytes) -> dict[str, np.ndarray]:
    return {k: t.values for k, t in deserialize_tensors(payload).items()}


def decode_example_batch(payloads) -> dict[str, np.ndarray]:
    """Decode N example payloads into ONE batched feature dict — the
    vectorized counterpart of ``decode_example`` + ``np.stack``.

    When the native codec is loaded and every record matches the first
    record's schema, the whole batch is decoded by a single C call
    (one memcpy per (record, feature) into preallocated ``(N, ...)``
    arrays); any schema drift falls back to the per-record path.  This is
    the role tf.data's C++ runtime plays for the reference
    (``worker.py:972-977`` batches with tf.data); measured ~40x over the
    per-record decode on small records (2.6M records/sec/core).
    """
    payloads = list(payloads)
    if not payloads:
        return {}
    first = decode_example(payloads[0])
    n = len(payloads)
    if n == 1:
        return {k: v[np.newaxis, ...] for k, v in first.items()}

    out = _native_decode_batch(payloads, first)
    if out is not None:
        return out
    decoded = [first] + [decode_example(p) for p in payloads[1:]]
    return {k: np.stack([d[k] for d in decoded]) for k in first}


def _native_decode_batch(
    payloads: list, first: dict[str, np.ndarray]
) -> dict[str, np.ndarray] | None:
    """One-FFI-call decode of the whole batch; None = take the fallback."""
    import ctypes

    n = len(payloads)
    buf = b"".join(payloads)
    offsets = (ctypes.c_uint64 * (n + 1))()
    pos = 0
    for i, p in enumerate(payloads):
        offsets[i] = pos
        pos += len(p)
    offsets[n] = pos
    return _native_decode_concat(buf, offsets, n, first)


def decode_concat_batch(
    buf, lengths, template: dict[str, np.ndarray]
) -> dict[str, np.ndarray] | None:
    """Decode records already CONCATENATED in ``buf`` (record ``i`` is
    ``lengths[i]`` bytes) against ``template``'s schema — the zero-copy
    half of the fused scan+decode path: ``buf``/``lengths`` are exactly
    what the scanner's ``next_chunk`` returns, so a task's records go
    disk -> chunk buffer -> batched arrays with no per-record Python
    objects at any point.  ``None`` = native codec unavailable or schema
    mismatch (caller falls back to the per-record decoder)."""
    import ctypes

    n = len(lengths)
    if n == 0:
        return {}
    offs = np.empty(n + 1, dtype=np.uint64)
    offs[0] = 0
    np.cumsum(np.asarray(lengths, dtype=np.uint64), out=offs[1:])
    if isinstance(buf, np.ndarray):
        buf = buf.ctypes.data  # zero-copy: pass the buffer's address
    return _native_decode_concat(
        buf, offs.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)), n, template
    )


def _native_decode_concat(
    buf, offsets, n: int, first: dict[str, np.ndarray]
) -> dict[str, np.ndarray] | None:
    import ctypes

    from elasticdl_tpu.data import recordio

    lib = recordio.native_lib()
    decode = getattr(lib, "edl_decode_batch", None) if lib else None
    if decode is None or len(first) == 0 or len(first) > 64:
        return None

    # the SAME naming the frame headers were written with — any drift
    # between writer and matcher silently forces the slow path, so share
    # the function instead of duplicating it
    from elasticdl_tpu.utils.tensor import _dtype_name

    names = list(first)
    try:
        dtypes = [_dtype_name(first[k].dtype) for k in names]
    except ValueError:  # a dtype outside the wire format
        return None

    c_names = (ctypes.c_char_p * len(names))(
        *[k.encode("utf-8") for k in names]
    )
    c_dtypes = (ctypes.c_char_p * len(names))(
        *[d.encode("utf-8") for d in dtypes]
    )
    flat_shapes = [d for k in names for d in first[k].shape]
    c_shapes = (ctypes.c_int64 * max(1, len(flat_shapes)))(*flat_shapes)
    c_ndims = (ctypes.c_int32 * len(names))(
        *[first[k].ndim for k in names]
    )
    c_row_bytes = (ctypes.c_uint64 * len(names))(
        *[first[k].nbytes for k in names]
    )
    out = {
        k: np.empty((n,) + first[k].shape, dtype=first[k].dtype)
        for k in names
    }
    c_outs = (ctypes.c_void_p * len(names))(
        *[out[k].ctypes.data for k in names]
    )
    rc = decode(
        buf,
        offsets,
        n,
        len(names),
        c_names,
        c_dtypes,
        c_shapes,
        c_ndims,
        c_row_bytes,
        c_outs,
    )
    return out if rc == 0 else None
