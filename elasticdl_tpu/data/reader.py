"""Data reader contract + example record codec.

Reference: ``elasticdl/python/data/reader/data_reader.py`` — the ABC every
reader implements (``read_records(task)``, ``create_shards()``,
``records_output_types``, ``Metadata``) that ties the data layer to the
task dispatcher: ``create_shards()`` output is exactly the shard dict the
dispatcher slices into tasks.
"""

from __future__ import annotations

import abc
from dataclasses import dataclass, field
from typing import Any, Iterator

import numpy as np

from elasticdl_tpu.utils.tensor import (
    deserialize_tensors,
    ndarray_to_tensor,
    serialize_tensors,
)


@dataclass
class Metadata:
    """Schema info a reader can surface to ``dataset_fn``
    (reference data_reader.py:40-49)."""

    column_names: list[str] = field(default_factory=list)
    column_dtypes: dict[str, Any] = field(default_factory=dict)
    extra: dict = field(default_factory=dict)


class AbstractDataReader(abc.ABC):
    def __init__(self, **kwargs):
        self._kwargs = kwargs

    @abc.abstractmethod
    def read_records(self, task) -> Iterator:
        """Yield the raw records of ``task``'s range [task.start, task.end)."""

    @abc.abstractmethod
    def create_shards(self) -> dict[str, tuple[int, int]]:
        """Map shard_name -> (start_index, num_records)."""

    @property
    def records_output_types(self):
        """Dtype hint for the record stream (bytes by default)."""
        return bytes

    @property
    def metadata(self) -> Metadata:
        return Metadata()


def encode_example(features: dict[str, np.ndarray]) -> bytes:
    """Standard record payload: a named-tensor dict (framework codec used by
    the synthetic dataset generators and the built-in model zoo).

    Replaces the reference's TF Example/RecordIO payloads with the
    framework's own tensor frames — no TF proto dependency.
    """
    return serialize_tensors(
        {k: ndarray_to_tensor(k, v) for k, v in features.items()}
    )


def decode_example(payload: bytes) -> dict[str, np.ndarray]:
    return {k: t.values for k, t in deserialize_tensors(payload).items()}
