"""Data reader factory.

Reference: ``elasticdl/python/data/reader/data_reader_factory.py`` —
ODPS when env-configured, CSV by extension, else RecordIO.  A model module
can override with ``custom_data_reader`` (reference
model_utils.py:94-150).
"""

from __future__ import annotations

from elasticdl_tpu.data.csv_reader import CSVDataReader
from elasticdl_tpu.data.reader import AbstractDataReader
from elasticdl_tpu.data.recordio_reader import RecordIODataReader


def create_data_reader(
    data_origin: str,
    records_per_task: int | None = None,
    custom_reader=None,
    **kwargs,
) -> AbstractDataReader:
    if custom_reader is not None:
        return custom_reader(
            data_origin=data_origin,
            records_per_task=records_per_task,
            **kwargs,
        )
    if data_origin.startswith("stream://"):
        from elasticdl_tpu.streaming.reader import StreamDataReader

        return StreamDataReader(data_origin=data_origin, **kwargs)
    from elasticdl_tpu.data.odps_reader import is_odps_configured

    if data_origin.startswith("odps://") or is_odps_configured():
        from elasticdl_tpu.data.odps_reader import ODPSDataReader

        return ODPSDataReader(table=data_origin, **kwargs)
    if data_origin.endswith(".csv") or kwargs.get("reader_type") == "CSV":
        return CSVDataReader(data_path=data_origin, **kwargs)
    return RecordIODataReader(data_dir=data_origin, **kwargs)
