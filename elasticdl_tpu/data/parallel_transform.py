"""Order-preserving parallel record transforms.

Reference: ``elasticdl/python/data/parallel_transform.py`` — a
multiprocessing pool that applies a transform to records while preserving
input order.  On the 1-core CI machine this degrades gracefully to a
threaded map (still useful for IO-bound decodes releasing the GIL).
"""

from __future__ import annotations

import concurrent.futures
from typing import Callable, Iterable, Iterator


class ParallelTransform:
    def __init__(
        self,
        transform: Callable,
        num_workers: int = 2,
        use_processes: bool = False,
        window: int = 64,
    ):
        self._transform = transform
        self._num_workers = max(1, num_workers)
        self._use_processes = use_processes
        self._window = window

    def apply(self, records: Iterable) -> Iterator:
        """Yield transform(record) in input order, computed concurrently."""
        pool_cls = (
            concurrent.futures.ProcessPoolExecutor
            if self._use_processes
            else concurrent.futures.ThreadPoolExecutor
        )
        with pool_cls(max_workers=self._num_workers) as pool:
            pending: list = []
            it = iter(records)
            try:
                for _ in range(self._window):
                    pending.append(pool.submit(self._transform, next(it)))
            except StopIteration:
                it = None
            while pending:
                fut = pending.pop(0)
                yield fut.result()
                if it is not None:
                    try:
                        pending.append(pool.submit(self._transform, next(it)))
                    except StopIteration:
                        it = None
