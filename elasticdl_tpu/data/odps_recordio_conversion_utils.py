"""Convert ODPS/MaxCompute table rows into EDLIO shard files.

Reference: ``elasticdl/python/data/odps_recordio_conversion_utils.py``
(``write_recordio_shards_from_iterator`` at :80-136, per-type feature
index helpers at :9-79).  The reference serializes each row into a
``tf.train.Example`` proto and writes Go-recordio shards; the TPU build
has no TF protos on the data path — rows become the same feature dicts
every other generator emits (``encode_example``), written through the
C++ EDLIO codec, so the converted tables are readable by the standard
``RecordIODataReader`` + per-model ``dataset_fn``/``batch_parse``.
"""

from __future__ import annotations

import os

import numpy as np

from elasticdl_tpu.data import recordio
from elasticdl_tpu.data.reader import encode_example


def _classify_feature_types(record) -> dict[int, str]:
    """Index -> kind ('int' / 'float' / 'bytes') from one row's Python
    types (reference ``_find_feature_indices_from_record`` :68-79).
    Unknown types raise rather than silently dropping a column."""
    kinds: dict[int, str] = {}
    for i, value in enumerate(record):
        if isinstance(value, bool):
            kinds[i] = "int"
        elif isinstance(value, (int, np.integer)):
            kinds[i] = "int"
        elif isinstance(value, (float, np.floating)):
            kinds[i] = "float"
        elif isinstance(value, (str, bytes)):
            kinds[i] = "bytes"
        else:
            raise TypeError(
                f"column {i}: unsupported ODPS value type {type(value)!r}"
            )
    return kinds


def _row_to_example(record, features_list, kinds) -> dict:
    """One row -> feature dict (reference ``_parse_row_to_example``
    :28-58, minus the proto).  Missing values coerce to the type's zero
    the way the reference's ``or 0`` / ``or 0.0`` fallbacks do."""
    example = {}
    for i, name in enumerate(features_list):
        kind = kinds.get(i, "bytes")
        value = record[i]
        if kind == "int":
            example[name] = np.int64(int(value or 0))
        elif kind == "float":
            example[name] = np.float32(float(value or 0.0))
        else:
            if isinstance(value, str):
                value = value.strip().encode("utf-8")
            example[name] = np.frombuffer(
                value or b"", dtype=np.uint8
            ).copy()
    return example


def write_recordio_shards_from_iterator(
    records_iter,
    features_list,
    output_dir,
    records_per_shard,
):
    """Write EDLIO shards from an iterator of rows (or row batches).

    Accepts the same shapes the reference does (:80-136): the iterator
    may yield single rows or lists of rows (ODPS tunnel readers batch);
    shards are ``data-00000``-style files of ``records_per_shard``
    records each.  Returns the number of records written.
    """
    os.makedirs(output_dir, exist_ok=True)
    writer = None
    rows_written = 0
    shards_written = 0
    kinds = None

    try:
        for record_batch in records_iter:
            is_multi = any(
                isinstance(item, (list, tuple, np.ndarray))
                for item in record_batch
            )
            batch = record_batch if is_multi else [record_batch]
            for record in batch:
                if kinds is None:
                    kinds = _classify_feature_types(record)
                if rows_written % records_per_shard == 0:
                    if writer is not None:
                        writer.close()
                    writer = recordio.Writer(
                        os.path.join(
                            output_dir, f"data-{shards_written:05d}.edlio"
                        )
                    )
                    shards_written += 1
                writer.write(
                    encode_example(
                        _row_to_example(record, features_list, kinds)
                    )
                )
                rows_written += 1
    finally:
        if writer is not None:
            writer.close()
    return rows_written
