"""MaxCompute (ODPS) table reader — gated on the optional odps SDK.

Reference: ``elasticdl/python/data/reader/odps_reader.py`` +
``data/odps_io.py`` — table scans with shard = row range, threaded chunked
download.  The TPU build keeps the same shard semantics; the SDK is not in
the base image, so construction raises a clear error unless ``odps`` is
importable.
"""

from __future__ import annotations

import os
from typing import Iterator

from elasticdl_tpu.data.reader import AbstractDataReader, Metadata

try:  # pragma: no cover - exercised only with the SDK installed
    from odps import ODPS  # type: ignore

    _ODPS_AVAILABLE = True
except ImportError:
    ODPS = None
    _ODPS_AVAILABLE = False


class ODPSDataReader(AbstractDataReader):
    def __init__(
        self,
        project: str = "",
        access_id: str = "",
        access_key: str = "",
        endpoint: str = "",
        table: str = "",
        partition: str | None = None,
        columns: list[str] | None = None,
        records_per_shard: int = 16384,
        **kwargs,
    ):
        super().__init__(**kwargs)
        if not _ODPS_AVAILABLE:
            raise ImportError(
                "ODPSDataReader requires the 'odps' SDK, which is not "
                "installed in this image; use RecordIO or CSV readers, or "
                "install pyodps"
            )
        self._project = project or os.environ.get("ODPS_PROJECT_NAME", "")
        self._table = table
        self._partition = partition
        self._columns = columns
        self._records_per_shard = records_per_shard
        self._client = ODPS(
            access_id or os.environ.get("ODPS_ACCESS_ID", ""),
            access_key or os.environ.get("ODPS_ACCESS_KEY", ""),
            self._project,
            endpoint=endpoint or os.environ.get("ODPS_ENDPOINT", ""),
        )
        from elasticdl_tpu.data.odps_io import ODPSTableReader

        # all table round trips go through the retrying chunk reader
        self._io = ODPSTableReader(
            self._client, self._table, partition=self._partition
        )

    def _table_size(self) -> int:
        return self._io.get_table_size()

    # rows per ranged read: bounds memory and retry re-download for large
    # tasks (a task range streams as a sequence of chunk reads, not one
    # monolithic download)
    _READ_CHUNK_ROWS = 4096

    def read_records(self, task) -> Iterator[list]:
        for start in range(task.start, task.end, self._READ_CHUNK_ROWS):
            end = min(start + self._READ_CHUNK_ROWS, task.end)
            yield from self._io.read_batch(start, end, self._columns)

    def create_shards(self) -> dict[str, tuple[int, int]]:
        total = self._table_size()
        shards = {}
        for start in range(0, total, self._records_per_shard):
            count = min(self._records_per_shard, total - start)
            shards[f"odps://{self._project}/{self._table}:{start}"] = (
                start,
                count,
            )
        return shards

    @property
    def metadata(self) -> Metadata:
        return Metadata(column_names=list(self._columns or []))


def is_odps_configured() -> bool:
    """Env-based detection (reference data_reader_factory.py checks the
    same variables)."""
    return _ODPS_AVAILABLE and all(
        os.environ.get(k)
        for k in ("ODPS_PROJECT_NAME", "ODPS_ACCESS_ID", "ODPS_ACCESS_KEY")
    )
