"""EDLIO-backed data reader.

Reference: ``elasticdl/python/data/reader/recordio_reader.py`` — a scanner
per task over the record range, and shard creation by walking a directory
and reading each file's record count from its index.
"""

from __future__ import annotations

import os
from typing import Iterator

from elasticdl_tpu.data import recordio
from elasticdl_tpu.data.reader import AbstractDataReader, Metadata


class RecordIODataReader(AbstractDataReader):
    def __init__(self, data_dir: str = "", **kwargs):
        super().__init__(**kwargs)
        self._data_dir = data_dir or kwargs.get("data_dir", "")

    def read_records(self, task) -> Iterator[bytes]:
        with recordio.Scanner(
            task.shard_name, task.start, task.end - task.start
        ) as scanner:
            yield from scanner

    def read_record_chunks(self, task) -> Iterator:
        """Yield ``(concat_buf, lengths)`` chunks of the task's range —
        the raw-batch form feeding the fused scan+decode fast path
        (``data/fast_pipeline.py``).  The yielded views may alias a
        reusable buffer: consume each chunk before advancing."""
        with recordio.Scanner(
            task.shard_name, task.start, task.end - task.start
        ) as scanner:
            while True:
                chunk = scanner.next_chunk()
                if chunk is None:
                    return
                yield chunk

    def create_shards(self) -> dict[str, tuple[int, int]]:
        if not self._data_dir:
            return {}
        shards = {}
        for name in sorted(os.listdir(self._data_dir)):
            path = os.path.join(self._data_dir, name)
            if os.path.isfile(path):
                shards[path] = (0, recordio.num_records(path))
        return shards

    @property
    def metadata(self) -> Metadata:
        return Metadata(extra={"format": "edlio"})
