"""CSV data reader (local/debug use).

Reference: ``elasticdl/python/data/reader/csv_reader.py`` — line-oriented
records; unlike EDLIO there is no index, so ranged reads re-scan from the
top (same limitation as the reference, csv_reader.py:13-21).
"""

from __future__ import annotations

import csv
import os
from typing import Iterator

from elasticdl_tpu.data.reader import AbstractDataReader, Metadata


class CSVDataReader(AbstractDataReader):
    def __init__(self, data_path: str = "", sep: str = ",", **kwargs):
        super().__init__(**kwargs)
        self._path = data_path or kwargs.get("data_dir", "")
        self._sep = sep
        self._columns: list[str] | None = None

    def _files(self) -> list[str]:
        if os.path.isdir(self._path):
            return [
                os.path.join(self._path, f)
                for f in sorted(os.listdir(self._path))
                if f.endswith(".csv")
            ]
        return [self._path]

    def read_records(self, task) -> Iterator[list[str]]:
        with open(task.shard_name, newline="") as f:
            reader = csv.reader(f, delimiter=self._sep)
            header = next(reader, None)
            if header is not None:
                self._columns = header
            for i, row in enumerate(reader):
                if i >= task.end:
                    break
                if i >= task.start:
                    yield row

    def create_shards(self) -> dict[str, tuple[int, int]]:
        shards = {}
        for path in self._files():
            with open(path, newline="") as f:
                n = sum(1 for _ in f)
            shards[path] = (0, max(0, n - 1))  # minus header line
        return shards

    @property
    def records_output_types(self):
        return list

    @property
    def metadata(self) -> Metadata:
        if self._columns is None:
            files = self._files()
            if files:
                with open(files[0], newline="") as f:
                    self._columns = next(csv.reader(f, delimiter=self._sep), [])
        return Metadata(column_names=self._columns or [])
