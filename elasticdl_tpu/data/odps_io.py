"""Threaded chunked ODPS/MaxCompute table IO.

Reference: ``elasticdl/python/data/odps_io.py:61-365`` — ``ODPSReader``
streams a table through a windowed thread pool (large chunks downloaded
concurrently, yielded in order, per-chunk retry) and ``ODPSWriter``
uploads from an iterator.  This build reuses the framework's
order-preserving windowed pool (:class:`~elasticdl_tpu.data.parallel_transform.ParallelTransform`)
as the pipeline engine instead of hand-rolling a future queue, and takes
the table client as a constructor argument so the logic tests without the
ODPS SDK (the real client is supplied by ``ODPSDataReader`` when the env
is configured, ``odps_reader.is_odps_configured``).

The table-client contract (duck-typed, a subset of ``odps.ODPS``):

- ``get_table(name)`` -> table with ``open_reader(partition=...)``
  giving ``reader.count`` and ``reader.read(start=, count=)``;
- for writes: ``table.open_writer(partition=..., blocks=...)`` with
  ``writer.write(records)``.
"""

from __future__ import annotations

import time
from typing import Iterable, Iterator

import numpy as np

from elasticdl_tpu.data.parallel_transform import ParallelTransform
from elasticdl_tpu.utils.log_utils import default_logger as logger

# Target bytes resident in the download pipeline, used to derive how many
# batches one chunk should carry (reference _estimate_cache_batch_count,
# odps_io.py:260-288, which aims the same way: keep chunks large enough
# to amortize a round trip without exhausting worker memory).
_TARGET_CHUNK_BYTES = 32 * 1024 * 1024
_SAMPLE_ROWS = 16


class ODPSTableReader:
    """Stream rows of one table (or partition) with concurrent chunk
    downloads, preserving row order within each worker's range."""

    def __init__(
        self,
        client,
        table: str,
        partition: str | None = None,
        num_threads: int = 4,
        max_retries: int = 3,
        retry_backoff_secs: float = 1.0,
    ):
        self._client = client
        self._table = table
        self._partition = partition
        self._num_threads = max(1, num_threads)
        self._max_retries = max_retries
        self._retry_backoff_secs = retry_backoff_secs

    # ---- table access ------------------------------------------------------

    def _with_retries(self, what: str, fn):
        """Every ODPS round trip retries transient failures the same way
        (reference retries only read_batch, odps_io.py:210-241 — but a
        flaky endpoint fails ``count`` reads just as often)."""
        attempt = 0
        while True:
            try:
                return fn()
            except Exception as ex:  # noqa: BLE001 — network/SDK errors
                attempt += 1
                if attempt > self._max_retries:
                    raise
                logger.warning(
                    "ODPS %s failed (attempt %d/%d): %s",
                    what,
                    attempt,
                    self._max_retries,
                    ex,
                )
                time.sleep(self._retry_backoff_secs * attempt)

    def get_table_size(self) -> int:
        def _read():
            t = self._client.get_table(self._table)
            with t.open_reader(partition=self._partition) as reader:
                return reader.count

        return self._with_retries("table size", _read)

    def read_batch(self, start: int, end: int, columns=None) -> list:
        """One ranged chunk read with retry."""

        def _read():
            t = self._client.get_table(self._table)
            with t.open_reader(partition=self._partition) as reader:
                return [
                    [rec[c] for c in (columns or rec.keys())]
                    for rec in reader.read(start=start, count=end - start)
                ]

        return self._with_retries(f"read [{start}, {end})", _read)

    def _estimate_cache_batch_count(
        self, columns, table_size: int, batch_size: int
    ) -> int:
        """Batches per chunk so a chunk is ~_TARGET_CHUNK_BYTES, probed
        from a small sample of real rows."""
        sample = self.read_batch(
            0, min(_SAMPLE_ROWS, table_size), columns
        )
        if not sample:
            return 1
        row_bytes = max(
            1, _nested_size_bytes(sample) // len(sample)
        )
        batches = _TARGET_CHUNK_BYTES // max(1, row_bytes * batch_size)
        return int(max(1, batches))

    # ---- streaming ---------------------------------------------------------

    def to_iterator(
        self,
        num_workers: int = 1,
        worker_index: int = 0,
        batch_size: int = 1,
        epochs: int = 1,
        shuffle: bool = False,
        columns=None,
        cache_batch_count: int | None = None,
        limit: int = -1,
    ) -> Iterator[list]:
        """Yield ``batch_size``-row slices of this worker's share of the
        table, downloading chunks of ``cache_batch_count`` batches
        concurrently (reference to_iterator, odps_io.py:105-208)."""
        if worker_index >= num_workers:
            raise ValueError(
                f"worker_index {worker_index} >= num_workers {num_workers}"
            )
        if batch_size <= 0:
            raise ValueError("batch_size should be positive")
        table_size = self.get_table_size()
        if 0 < limit < table_size:
            table_size = limit
        if table_size == 0:
            return
        if cache_batch_count is None:
            cache_batch_count = self._estimate_cache_batch_count(
                columns, table_size, batch_size
            )
        chunk_rows = batch_size * cache_batch_count

        starts = list(range(0, table_size, chunk_rows))
        if len(starts) < num_workers:
            starts = list(
                range(0, table_size, max(1, table_size // num_workers))
            )
        my_starts = list(
            np.array_split(np.asarray(starts), num_workers)[worker_index]
        )
        if shuffle:
            np.random.shuffle(my_starts)
        my_starts = my_starts * epochs
        if not my_starts:
            return

        pipeline = ParallelTransform(
            lambda start: self.read_batch(
                int(start), int(min(start + chunk_rows, table_size)), columns
            ),
            num_workers=min(self._num_threads, len(my_starts)),
            window=min(self._num_threads, len(my_starts)),
        )
        for records in pipeline.apply(my_starts):
            for i in range(0, len(records), batch_size):
                yield records[i : i + batch_size]


class ODPSTableWriter:
    """Upload records from an iterator in buffered blocks (reference
    ODPSWriter.from_iterator, odps_io.py:290-365)."""

    def __init__(self, client, table: str, partition: str | None = None):
        self._client = client
        self._table = table
        self._partition = partition

    def from_iterator(
        self,
        records: Iterable,
        buffer_rows: int = 10000,
    ) -> int:
        t = self._client.get_table(self._table)
        written = 0
        with t.open_writer(partition=self._partition) as writer:
            buf: list = []
            for rec in records:
                buf.append(rec)
                if len(buf) >= buffer_rows:
                    writer.write(buf)
                    written += len(buf)
                    buf = []
            if buf:
                writer.write(buf)
                written += len(buf)
        logger.info(
            "Wrote %d records to odps table %s", written, self._table
        )
        return written


def _nested_size_bytes(rows: list) -> int:
    total = 0
    for row in rows:
        for value in row:
            if isinstance(value, (bytes, str)):
                total += len(value)
            else:
                total += np.asarray(value).nbytes
    return total
