"""Build the native EDLIO codec: ``python -m elasticdl_tpu.data.recordio.build``.

Compiles ``_native.cc`` into ``_native.so`` next to this file.  The Python
package auto-loads the .so when present and falls back to the pure-Python
codec otherwise, so the build step is optional but recommended for IO-bound
jobs.
"""

from __future__ import annotations

import os
import subprocess
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
SOURCE = os.path.join(_HERE, "_native.cc")
OUTPUT = os.path.join(_HERE, "_native.so")


def build(force: bool = False, quiet: bool = False) -> str | None:
    """Compile the codec; returns the .so path or None on failure."""
    if (
        not force
        and os.path.exists(OUTPUT)
        and os.path.getmtime(OUTPUT) >= os.path.getmtime(SOURCE)
    ):
        return OUTPUT
    cmd = [
        "g++",
        "-O2",
        "-std=c++17",
        "-shared",
        "-fPIC",
        SOURCE,
        "-lz",
        "-o",
        OUTPUT,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=quiet)
    except (subprocess.CalledProcessError, FileNotFoundError) as e:
        if not quiet:
            print(f"EDLIO native build failed: {e}", file=sys.stderr)
        return None
    return OUTPUT


if __name__ == "__main__":
    path = build(force="--force" in sys.argv)
    if path is None:
        sys.exit(1)
    print(path)
