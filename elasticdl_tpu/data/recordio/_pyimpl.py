"""Pure-Python implementation of the EDLIO container (see FORMAT.md).

Used when the C++ codec is not built; byte-for-byte interchangeable.
"""

from __future__ import annotations

import os
import struct
import zlib
from typing import Iterator

_FRAME = struct.Struct("<II")  # payload_len, crc32
_FOOTER = struct.Struct("<QQII")  # index_offset, num_records, version, magic
MAGIC = 0x45444C49
VERSION = 1
FOOTER_SIZE = _FOOTER.size


class CorruptFileError(Exception):
    pass


class Writer:
    def __init__(self, path: str):
        self._path = path
        self._f = open(path, "wb")
        self._offsets: list[int] = []
        self._pos = 0
        self._closed = False

    def write(self, payload: bytes):
        if self._closed:
            raise ValueError("writer is closed")
        if isinstance(payload, str):
            payload = payload.encode("utf-8")
        self._offsets.append(self._pos)
        frame = _FRAME.pack(len(payload), zlib.crc32(payload) & 0xFFFFFFFF)
        self._f.write(frame)
        self._f.write(payload)
        self._pos += len(frame) + len(payload)

    def close(self):
        if self._closed:
            return
        index_offset = self._pos
        for off in self._offsets:
            self._f.write(struct.pack("<Q", off))
        self._f.write(
            _FOOTER.pack(index_offset, len(self._offsets), VERSION, MAGIC)
        )
        self._f.close()
        self._closed = True

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def _read_footer(f) -> tuple[int, int]:
    f.seek(0, os.SEEK_END)
    size = f.tell()
    if size < FOOTER_SIZE:
        raise CorruptFileError("file smaller than footer")
    f.seek(size - FOOTER_SIZE)
    index_offset, num_records, version, magic = _FOOTER.unpack(
        f.read(FOOTER_SIZE)
    )
    if magic != MAGIC:
        raise CorruptFileError("bad magic (not an EDLIO file or truncated)")
    if version != VERSION:
        raise CorruptFileError(f"unsupported EDLIO version {version}")
    return index_offset, num_records


def num_records(path: str) -> int:
    with open(path, "rb") as f:
        return _read_footer(f)[1]


class Scanner:
    """Ranged scan: yields records [start, start+length) of the file.

    ``length < 0`` means 'to the end'.  Mirrors the access pattern of the
    reference's ``recordio.Scanner(shard, start, len)``.
    """

    def __init__(self, path: str, start: int = 0, length: int = -1):
        self._f = open(path, "rb")
        try:
            index_offset, total = _read_footer(self._f)
        except Exception:
            self._f.close()
            raise
        if start < 0 or start > total:
            self._f.close()
            raise IndexError(f"start {start} out of range 0..{total}")
        self._remaining = (total - start) if length < 0 else min(
            length, total - start
        )
        if self._remaining > 0:
            self._f.seek(index_offset + 8 * start)
            (first_off,) = struct.unpack("<Q", self._f.read(8))
            self._f.seek(first_off)

    def record(self) -> bytes | None:
        """Next record payload, or None when the range is exhausted."""
        if self._remaining <= 0:
            return None
        header = self._f.read(_FRAME.size)
        if len(header) < _FRAME.size:
            raise CorruptFileError("truncated frame header")
        length, crc = _FRAME.unpack(header)
        payload = self._f.read(length)
        if len(payload) < length:
            raise CorruptFileError("truncated payload")
        if zlib.crc32(payload) & 0xFFFFFFFF != crc:
            raise CorruptFileError("crc mismatch")
        self._remaining -= 1
        return payload

    def __iter__(self) -> Iterator[bytes]:
        while True:
            rec = self.record()
            if rec is None:
                return
            yield rec

    def next_chunk(self, max_records: int = 4096):
        """``(concat_payload_bytes, lengths)`` for up to ``max_records``
        records, or ``None`` at end — same contract as the native
        scanner's chunk API (there a single FFI call; here assembled
        from per-record reads, correctness-equivalent fallback)."""
        import numpy as np

        recs = []
        while len(recs) < max_records:
            rec = self.record()
            if rec is None:
                break
            recs.append(rec)
        if not recs:
            return None
        buf = np.frombuffer(b"".join(recs), dtype=np.uint8)
        lengths = np.array([len(r) for r in recs], dtype=np.uint64)
        return buf, lengths

    def close(self):
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
