// EDLIO container codec — C++ core with a C ABI for ctypes bindings.
//
// Implements FORMAT.md exactly (interchangeable with _pyimpl.py).  This is
// the TPU build's replacement for the reference's native record dependency
// (Go `pyrecordio`, used via elasticdl/python/data/reader/recordio_reader.py):
// a seekable record container with O(1) num_records and ranged scans, which
// is what task-addressable dynamic data sharding requires.
//
// Build: python -m elasticdl_tpu.data.recordio.build
//
// Design notes:
// - Scanner exposes a *batch* read (fill a caller buffer with as many
//   concatenated payloads as fit) so the Python side pays one FFI call per
//   few thousand records, not per record.
// - Buffered IO with a 1 MiB read-ahead; CRC32 via zlib.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

#include <zlib.h>

namespace {

constexpr uint32_t kMagic = 0x45444C49;  // "EDLI"
constexpr uint32_t kVersion = 1;
constexpr size_t kFooterSize = 8 + 8 + 4 + 4;
constexpr size_t kFrameSize = 4 + 4;

thread_local std::string g_last_error;

void set_error(const std::string& msg) { g_last_error = msg; }

uint32_t load_u32(const uint8_t* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return v;
}

uint64_t load_u64(const uint8_t* p) {
  uint64_t v;
  std::memcpy(&v, p, 8);
  return v;
}

struct Footer {
  uint64_t index_offset;
  uint64_t num_records;
};

bool read_footer(std::FILE* f, Footer* out) {
  if (std::fseek(f, 0, SEEK_END) != 0) {
    set_error("seek to end failed");
    return false;
  }
  long size = std::ftell(f);
  if (size < (long)kFooterSize) {
    set_error("file smaller than footer");
    return false;
  }
  uint8_t buf[kFooterSize];
  if (std::fseek(f, size - (long)kFooterSize, SEEK_SET) != 0 ||
      std::fread(buf, 1, kFooterSize, f) != kFooterSize) {
    set_error("footer read failed");
    return false;
  }
  uint32_t version = load_u32(buf + 16);
  uint32_t magic = load_u32(buf + 20);
  if (magic != kMagic) {
    set_error("bad magic (not an EDLIO file or truncated)");
    return false;
  }
  if (version != kVersion) {
    set_error("unsupported EDLIO version");
    return false;
  }
  out->index_offset = load_u64(buf);
  out->num_records = load_u64(buf + 8);
  return true;
}

struct Writer {
  std::FILE* f;
  std::vector<uint64_t> offsets;
  uint64_t pos = 0;
};

struct Scanner {
  std::FILE* f;
  int64_t remaining = 0;
};

}  // namespace

extern "C" {

const char* edlio_last_error() { return g_last_error.c_str(); }

void* edlio_writer_open(const char* path) {
  std::FILE* f = std::fopen(path, "wb");
  if (!f) {
    set_error(std::string("cannot open for write: ") + path);
    return nullptr;
  }
  auto* w = new Writer();
  w->f = f;
  return w;
}

int edlio_writer_write(void* handle, const uint8_t* data, uint64_t len) {
  auto* w = static_cast<Writer*>(handle);
  uint32_t len32 = (uint32_t)len;
  uint32_t crc = (uint32_t)crc32(0L, data, (uInt)len);
  w->offsets.push_back(w->pos);
  uint8_t frame[kFrameSize];
  std::memcpy(frame, &len32, 4);
  std::memcpy(frame + 4, &crc, 4);
  if (std::fwrite(frame, 1, kFrameSize, w->f) != kFrameSize ||
      std::fwrite(data, 1, len, w->f) != len) {
    set_error("write failed");
    return -1;
  }
  w->pos += kFrameSize + len;
  return 0;
}

int edlio_writer_close(void* handle) {
  auto* w = static_cast<Writer*>(handle);
  int rc = 0;
  uint64_t index_offset = w->pos;
  for (uint64_t off : w->offsets) {
    if (std::fwrite(&off, 1, 8, w->f) != 8) rc = -1;
  }
  uint64_t n = w->offsets.size();
  uint8_t footer[kFooterSize];
  std::memcpy(footer, &index_offset, 8);
  std::memcpy(footer + 8, &n, 8);
  std::memcpy(footer + 16, &kVersion, 4);
  std::memcpy(footer + 20, &kMagic, 4);
  if (std::fwrite(footer, 1, kFooterSize, w->f) != kFooterSize) rc = -1;
  if (std::fclose(w->f) != 0) rc = -1;
  if (rc != 0) set_error("writer close/flush failed");
  delete w;
  return rc;
}

int64_t edlio_num_records(const char* path) {
  std::FILE* f = std::fopen(path, "rb");
  if (!f) {
    set_error(std::string("cannot open: ") + path);
    return -1;
  }
  Footer footer;
  bool ok = read_footer(f, &footer);
  std::fclose(f);
  return ok ? (int64_t)footer.num_records : -1;
}

void* edlio_scanner_open(const char* path, int64_t start, int64_t length) {
  std::FILE* f = std::fopen(path, "rb");
  if (!f) {
    set_error(std::string("cannot open: ") + path);
    return nullptr;
  }
  Footer footer;
  if (!read_footer(f, &footer)) {
    std::fclose(f);
    return nullptr;
  }
  if (start < 0 || (uint64_t)start > footer.num_records) {
    set_error("start out of range");
    std::fclose(f);
    return nullptr;
  }
  int64_t avail = (int64_t)footer.num_records - start;
  int64_t remaining = length < 0 ? avail : (length < avail ? length : avail);
  if (remaining > 0) {
    uint8_t off_buf[8];
    if (std::fseek(f, (long)(footer.index_offset + 8 * (uint64_t)start),
                   SEEK_SET) != 0 ||
        std::fread(off_buf, 1, 8, f) != 8) {
      set_error("index read failed");
      std::fclose(f);
      return nullptr;
    }
    uint64_t first = load_u64(off_buf);
    if (std::fseek(f, (long)first, SEEK_SET) != 0) {
      set_error("seek to first record failed");
      std::fclose(f);
      return nullptr;
    }
  }
  // large stdio buffer => read-ahead without mmap portability questions
  std::setvbuf(f, nullptr, _IOFBF, 1 << 20);
  auto* s = new Scanner();
  s->f = f;
  s->remaining = remaining;
  return s;
}

// Fill `buf` (capacity `buf_cap`) with concatenated payloads; write each
// payload's length into `lengths` (capacity `max_records`).  Returns the
// number of records read; 0 at end of range; -1 on error.  A record larger
// than buf_cap is an error (caller sizes the buffer generously).
int64_t edlio_scanner_next_batch(void* handle, uint8_t* buf, uint64_t buf_cap,
                                 uint64_t* lengths, int64_t max_records) {
  auto* s = static_cast<Scanner*>(handle);
  int64_t count = 0;
  uint64_t used = 0;
  while (count < max_records && s->remaining > 0) {
    uint8_t frame[kFrameSize];
    long before = std::ftell(s->f);
    if (std::fread(frame, 1, kFrameSize, s->f) != kFrameSize) {
      set_error("truncated frame header");
      return -1;
    }
    uint32_t len = load_u32(frame);
    uint32_t crc = load_u32(frame + 4);
    if (used + len > buf_cap) {
      if (count == 0) {
        set_error("record larger than batch buffer");
        return -1;
      }
      // rewind to frame start; deliver what we have
      std::fseek(s->f, before, SEEK_SET);
      break;
    }
    if (std::fread(buf + used, 1, len, s->f) != len) {
      set_error("truncated payload");
      return -1;
    }
    if ((uint32_t)crc32(0L, buf + used, (uInt)len) != crc) {
      set_error("crc mismatch");
      return -1;
    }
    lengths[count] = len;
    used += len;
    ++count;
    --s->remaining;
  }
  return count;
}

void edlio_scanner_close(void* handle) {
  auto* s = static_cast<Scanner*>(handle);
  std::fclose(s->f);
  delete s;
}

}  // extern "C"

// ---- fused batch decode of example payloads --------------------------------
//
// The vectorized half of the data loader (the role tf.data's C++ runtime
// plays for the reference, SURVEY §2.9): decode N example payloads — each a
// tensor-frame collection produced by utils/tensor.py serialize_tensors —
// straight into caller-allocated (N, ...) batch arrays, one memcpy per
// (record, feature), no per-record Python objects.
//
// Payload layout (utils/tensor.py): [u32 nframes] ([u32 flen] frame)*
//   frame = [u32 hdr_len] header_json data [indices?]
//   header_json (canonical json.dumps order, space separators):
//     {"name": "...", "dtype": "...", "shape": [a, b], "sparse": false}
//
// The parser accepts exactly the canonical layout; anything else (sparse
// tensors, escaped names, re-ordered keys from a foreign writer) returns a
// negative code and the Python caller falls back to the per-record path —
// correctness never depends on this fast path.

namespace {

struct HdrCursor {
  const uint8_t* p;
  const uint8_t* end;
};

bool expect(HdrCursor* c, const char* lit) {
  size_t n = std::strlen(lit);
  if ((size_t)(c->end - c->p) < n || std::memcmp(c->p, lit, n) != 0) {
    return false;
  }
  c->p += n;
  return true;
}

// Parse a JSON string value with no escapes; returns false on escape/EOF.
bool parse_plain_string(HdrCursor* c, const uint8_t** out, size_t* out_len) {
  const uint8_t* start = c->p;
  while (c->p < c->end && *c->p != '"') {
    if (*c->p == '\\') return false;
    ++c->p;
  }
  if (c->p >= c->end) return false;
  *out = start;
  *out_len = (size_t)(c->p - start);
  ++c->p;  // closing quote
  return true;
}

bool parse_int(HdrCursor* c, int64_t* out) {
  if (c->p >= c->end || *c->p < '0' || *c->p > '9') return false;
  int64_t v = 0;
  int digits = 0;
  while (c->p < c->end && *c->p >= '0' && *c->p <= '9') {
    if (++digits > 18) return false;  // corrupt header: would overflow i64
    v = v * 10 + (*c->p - '0');
    ++c->p;
  }
  *out = v;
  return true;
}

}  // namespace

extern "C" {

// Decode n_records payloads (concatenated in buf, record i spanning
// [offsets[i], offsets[i+1])) into n_features batch arrays.  Feature k of
// record i lands at outs[k] + i * row_bytes[k].  Every record must carry
// exactly the expected features (any order), each matching the expected
// dtype / shape / byte count.  Returns 0 on success, negative on any
// mismatch (caller falls back to the per-record Python decoder).
int64_t edl_decode_batch(const uint8_t* buf, const uint64_t* offsets,
                         int64_t n_records, int32_t n_features,
                         const char** names, const char** dtypes,
                         const int64_t* shapes, const int32_t* ndims,
                         const uint64_t* row_bytes, uint8_t** outs) {
  if (n_features <= 0 || n_features > 64) return -1;  // seen-mask is u64
  // per-feature offset into the flattened expected-shape array
  std::vector<int32_t> shape_off(n_features);
  int32_t off = 0;
  for (int32_t k = 0; k < n_features; ++k) {
    shape_off[k] = off;
    off += ndims[k];
  }
  for (int64_t i = 0; i < n_records; ++i) {
    const uint8_t* p = buf + offsets[i];
    const uint8_t* rec_end = buf + offsets[i + 1];
    if (rec_end - p < 4) return -2;
    uint32_t nframes = load_u32(p);
    p += 4;
    if ((int64_t)nframes != n_features) return -3;
    uint64_t seen = 0;
    for (uint32_t f = 0; f < nframes; ++f) {
      if (rec_end - p < 8) return -4;
      uint32_t flen = load_u32(p);
      uint32_t hdr_len = load_u32(p + 4);
      p += 8;
      if ((uint64_t)(rec_end - p) + 4 < (uint64_t)flen ||
          (uint64_t)hdr_len + 4 > (uint64_t)flen) {
        return -5;
      }
      const uint8_t* frame_end = p + (flen - 4);
      HdrCursor c{p, p + hdr_len};
      p += hdr_len;
      // canonical header walk
      const uint8_t* name;
      size_t name_len;
      const uint8_t* dtype;
      size_t dtype_len;
      if (!expect(&c, "{\"name\": \"") ||
          !parse_plain_string(&c, &name, &name_len) ||
          !expect(&c, ", \"dtype\": \"") ||
          !parse_plain_string(&c, &dtype, &dtype_len) ||
          !expect(&c, ", \"shape\": [")) {
        return -6;
      }
      // match the feature by name
      int32_t k = -1;
      for (int32_t j = 0; j < n_features; ++j) {
        if (std::strlen(names[j]) == name_len &&
            std::memcmp(names[j], name, name_len) == 0) {
          k = j;
          break;
        }
      }
      if (k < 0 || (seen >> k) & 1) return -7;
      if (std::strlen(dtypes[k]) != dtype_len ||
          std::memcmp(dtypes[k], dtype, dtype_len) != 0) {
        return -8;
      }
      // shape must equal the expected per-record shape exactly
      for (int32_t d = 0; d < ndims[k]; ++d) {
        if (d > 0 && !expect(&c, ", ")) return -9;
        int64_t v;
        if (!parse_int(&c, &v) || v != shapes[shape_off[k] + d]) return -9;
      }
      if (!expect(&c, "]") || !expect(&c, ", \"sparse\": false}")) {
        return -10;  // sparse or trailing keys: not batchable here
      }
      if ((uint64_t)(frame_end - p) != row_bytes[k]) return -11;
      std::memcpy(outs[k] + (uint64_t)i * row_bytes[k], p, row_bytes[k]);
      p = frame_end;
      seen |= (uint64_t)1 << k;
    }
  }
  return 0;
}

}  // extern "C"
