"""EDLIO: seekable record container (see FORMAT.md).

Public API mirrors the access pattern the reference gets from the external
``pyrecordio`` package (``recordio_reader.py:20-40``): ``Writer``,
``Scanner(path, start, length)``, ``num_records(path)``.

Backend selection: the C++ codec (``_native.so``, built by ``build.py``) is
used when available; otherwise the pure-Python implementation.  Both emit
and read the identical on-disk format.
"""

from __future__ import annotations

import ctypes
import os

from elasticdl_tpu.data.recordio import _pyimpl
from elasticdl_tpu.data.recordio._pyimpl import CorruptFileError

__all__ = [
    "Writer",
    "Scanner",
    "num_records",
    "CorruptFileError",
    "native_available",
    "ensure_native_codec",
]

_NATIVE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)), "_native.so")
_lib = None


def _load_native():
    global _lib
    if _lib is not None or not os.path.exists(_NATIVE_PATH):
        return _lib
    lib = ctypes.CDLL(_NATIVE_PATH)
    lib.edlio_writer_open.restype = ctypes.c_void_p
    lib.edlio_writer_open.argtypes = [ctypes.c_char_p]
    lib.edlio_writer_write.restype = ctypes.c_int
    lib.edlio_writer_write.argtypes = [
        ctypes.c_void_p,
        ctypes.c_char_p,
        ctypes.c_uint64,
    ]
    lib.edlio_writer_close.restype = ctypes.c_int
    lib.edlio_writer_close.argtypes = [ctypes.c_void_p]
    lib.edlio_num_records.restype = ctypes.c_int64
    lib.edlio_num_records.argtypes = [ctypes.c_char_p]
    lib.edlio_scanner_open.restype = ctypes.c_void_p
    lib.edlio_scanner_open.argtypes = [
        ctypes.c_char_p,
        ctypes.c_int64,
        ctypes.c_int64,
    ]
    lib.edlio_scanner_next_batch.restype = ctypes.c_int64
    # buf is c_void_p (not c_char_p) so callers can pass a numpy buffer's
    # .ctypes.data and read records into it with zero intermediate copies
    lib.edlio_scanner_next_batch.argtypes = [
        ctypes.c_void_p,
        ctypes.c_void_p,
        ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.c_int64,
    ]
    lib.edlio_scanner_close.restype = None
    lib.edlio_scanner_close.argtypes = [ctypes.c_void_p]
    lib.edlio_last_error.restype = ctypes.c_char_p
    try:
        decode = lib.edl_decode_batch
    except AttributeError:  # stale .so built before the batch decoder
        decode = None
    if decode is not None:
        _register_decode(decode)
    _lib = lib
    return _lib


def _register_decode(decode):
    decode.restype = ctypes.c_int64
    decode.argtypes = [
        ctypes.c_void_p,                    # concatenated payloads
        # (void* not char*: accepts both Python bytes and a numpy
        # buffer's .ctypes.data, so the scanner's chunk buffer decodes
        # with no intermediate copy)
        ctypes.POINTER(ctypes.c_uint64),    # n+1 offsets
        ctypes.c_int64,                     # n_records
        ctypes.c_int32,                     # n_features
        ctypes.POINTER(ctypes.c_char_p),    # names
        ctypes.POINTER(ctypes.c_char_p),    # dtypes
        ctypes.POINTER(ctypes.c_int64),     # flattened shapes
        ctypes.POINTER(ctypes.c_int32),     # ndims
        ctypes.POINTER(ctypes.c_uint64),    # row_bytes
        ctypes.POINTER(ctypes.c_void_p),    # out base pointers
    ]


def native_available() -> bool:
    return _load_native() is not None


def ensure_native_codec() -> str:
    """Make the native codec available or fail FAST with one actionable
    line.  Lockstep worlds require it (a host missing it would silently
    shuffle different batches than its peers — ``build_task_batches``
    raises per-worker), so harness entry points call this BEFORE
    spawning workers: one clear error beats a worker crash-loop that
    burns the whole reform budget on a missing .so.  Attempts the build
    in place first (the common case: fresh checkout, compiler
    present)."""
    if native_available():
        return _NATIVE_PATH
    from elasticdl_tpu.data.recordio import build as build_mod

    built = build_mod.build(quiet=True)
    if built is not None and native_available():
        return built
    raise RuntimeError(
        "native EDLIO codec missing and unbuildable: run "
        "`python -m elasticdl_tpu.data.recordio.build` (needs g++ and "
        "zlib) before starting lockstep jobs"
    )


def native_lib():
    """The loaded C library (or None) — shared by the example batch
    decoder (``data/reader.py``), which lives in the same .so."""
    return _load_native()


def _native_error(lib) -> str:
    return lib.edlio_last_error().decode("utf-8", "replace")


class _NativeWriter:
    def __init__(self, path: str):
        lib = _load_native()
        self._lib = lib
        self._h = lib.edlio_writer_open(path.encode())
        if not self._h:
            raise IOError(_native_error(lib))

    def write(self, payload: bytes):
        if isinstance(payload, str):
            payload = payload.encode("utf-8")
        if self._lib.edlio_writer_write(self._h, payload, len(payload)) != 0:
            raise IOError(_native_error(self._lib))

    def close(self):
        if self._h:
            rc = self._lib.edlio_writer_close(self._h)
            self._h = None
            if rc != 0:
                raise IOError(_native_error(self._lib))

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class _NativeScanner:
    """Batch-reading scanner over the C++ codec.

    One FFI call fetches up to ``batch_records`` payloads into a reusable
    numpy buffer; ``record()``/iteration then slice bytes out of it, and
    :meth:`next_chunk` exposes the raw ``(buffer, lengths)`` pair directly
    — the zero-per-record-object input of ``edl_decode_batch`` (the fused
    scan+decode fast path, ``data/fast_pipeline.py``).
    """

    _BUF_CAP = 8 << 20  # 8 MiB
    _BATCH_RECORDS = 4096

    def __init__(self, path: str, start: int = 0, length: int = -1):
        import numpy as np

        lib = _load_native()
        self._lib = lib
        self._h = lib.edlio_scanner_open(path.encode(), start, length)
        if not self._h:
            raise (
                IndexError(_native_error(lib))
                if "out of range" in _native_error(lib)
                else CorruptFileError(_native_error(lib))
            )
        self._buf = np.empty(self._BUF_CAP, dtype=np.uint8)
        self._lengths = np.empty(self._BATCH_RECORDS, dtype=np.uint64)
        self._pending: list[bytes] = []
        self._pending_idx = 0
        self._exhausted = False

    def next_chunk(self):
        """Read the next chunk of records in ONE FFI call; returns
        ``(buf, lengths)`` — numpy views of the concatenated payload
        bytes and per-record lengths — or ``None`` at end of range.

        The views alias a reusable buffer: they are valid only until the
        next ``next_chunk``/``record`` call (callers decode immediately;
        ``data/fast_pipeline.py`` does)."""
        if self._exhausted:
            return None
        n = self._lib.edlio_scanner_next_batch(
            self._h,
            self._buf.ctypes.data,
            self._BUF_CAP,
            self._lengths.ctypes.data_as(
                ctypes.POINTER(ctypes.c_uint64)
            ),
            self._BATCH_RECORDS,
        )
        if n < 0:
            raise CorruptFileError(_native_error(self._lib))
        if n == 0:
            self._exhausted = True
            return None
        used = int(self._lengths[:n].sum())
        return self._buf[:used], self._lengths[:n]

    def _refill(self) -> bool:
        chunk = self.next_chunk()
        if chunk is None:
            return False
        buf, lengths = chunk
        # one copy of only the FILLED region (the previous implementation
        # copied the whole 8 MiB capacity per refill via ctypes .raw)
        raw = buf.tobytes()
        out, off = [], 0
        for ln in lengths:
            ln = int(ln)
            out.append(raw[off : off + ln])
            off += ln
        self._pending = out
        self._pending_idx = 0
        return True

    def record(self) -> bytes | None:
        if self._pending_idx >= len(self._pending):
            if self._exhausted or not self._refill():
                return None
        rec = self._pending[self._pending_idx]
        self._pending_idx += 1
        return rec

    def __iter__(self):
        while True:
            rec = self.record()
            if rec is None:
                return
            yield rec

    def close(self):
        if self._h:
            self._lib.edlio_scanner_close(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def Writer(path: str):
    if native_available():
        return _NativeWriter(path)
    return _pyimpl.Writer(path)


def Scanner(path: str, start: int = 0, length: int = -1):
    if native_available():
        return _NativeScanner(path, start, length)
    return _pyimpl.Scanner(path, start, length)


def num_records(path: str) -> int:
    lib = _load_native()
    if lib is not None:
        n = lib.edlio_num_records(path.encode())
        if n < 0:
            raise CorruptFileError(_native_error(lib))
        return n
    return _pyimpl.num_records(path)
