"""Process-wide XLA compile counting (shape-canonical batching's gauge).

The whole point of canonicalizing batch shapes
(docs/designs/shape_canonicalization.md) is that the steady-state step
stream executes exactly ONE train-step program (plus one stacked-scan
variant) — so the number of backend compiles is the regression signal
worth watching.  This module makes it observable:

- a **counter**: every XLA backend compile in this process increments a
  process-wide total (:func:`compile_count`); the master mirrors it —
  plus the ``compile_count`` exec counters lockstep chiefs ship with
  task reports — onto ``/metrics`` as ``elasticdl_compile_total``.
- a **span**: each compile lands in the trace timeline as a ``compile``
  span (duration = the backend compile), so ``trace analyze``'s
  ``warmup_compile`` reform phase shows measured compile time instead of
  inferring it from the uncovered remainder.

Mechanism: :func:`install` registers a ``jax.monitoring`` duration
listener for the ``/jax/core/compile/backend_compile_duration`` event
(one firing per program actually handed to XLA — cache hits and traces
don't fire it).  When the monitoring API is unavailable the installer
falls back to wrapping ``jax._src.compiler.compile_or_get_cached`` (the
funnel every jitted lower/compile path goes through) — an APPROXIMATION:
unlike the monitoring event, the wrap also counts persistent-compile-
cache lookups that hit, so wrap-mode totals are an upper bound.  If
neither hook exists the tracker stays disabled and
:func:`compile_count` returns 0.

Install is idempotent and the disabled cost is zero: nothing here sits
on the step path — compiles are the rare event being counted.
"""

from __future__ import annotations

import threading
import time

# the exec-counter key lockstep chiefs report compile DELTAS under
# (summed by the TaskDispatcher, mirrored by MasterTelemetry._collect)
COMPILE_COUNT_KEY = "compile_count"

_BACKEND_COMPILE_SUFFIX = "backend_compile_duration"

_lock = threading.Lock()
_count = 0
_secs_total = 0.0
_installed = False
_mode: str | None = None


def _record(duration_secs: float):
    global _count, _secs_total
    with _lock:
        _count += 1
        _secs_total += max(0.0, float(duration_secs))
    # retroactive trace span: recorded on whatever thread compiled; the
    # tracer is thread-safe and lifecycle spans are never sampled away
    from elasticdl_tpu.telemetry import tracing

    tracer = tracing.get_tracer()
    if tracer is not None:
        now = time.monotonic()
        tracer.record_span(
            tracing.SPAN_COMPILE, now - max(0.0, float(duration_secs)), now
        )


def _on_event_duration(event: str, duration_secs: float, **_kwargs):
    if event.endswith(_BACKEND_COMPILE_SUFFIX):
        _record(duration_secs)


def install() -> bool:
    """Register the compile listener once per process; returns whether a
    hook was installed (False only on a JAX without monitoring or a
    compile funnel to wrap)."""
    global _installed, _mode
    with _lock:
        if _installed:
            return _mode is not None
        _installed = True
    try:
        from jax import monitoring

        monitoring.register_event_duration_secs_listener(_on_event_duration)
        _mode = "monitoring"
        return True
    except Exception:  # noqa: BLE001 — fall through to the wrap
        pass
    try:  # fallback: wrap the one funnel every lower/compile path uses
        from jax._src import compiler as _jax_compiler

        wrapped = _jax_compiler.compile_or_get_cached

        def counting(*args, **kwargs):
            t0 = time.perf_counter()
            try:
                return wrapped(*args, **kwargs)
            finally:
                _record(time.perf_counter() - t0)

        _jax_compiler.compile_or_get_cached = counting
        _mode = "wrap"
        return True
    except Exception:  # noqa: BLE001 — tracker stays disabled
        _mode = None
        return False


def compile_count() -> int:
    """XLA programs compiled by THIS process since install (0 before)."""
    return _count


def compile_secs_total() -> float:
    """Total seconds this process spent in backend compiles."""
    return _secs_total


def installed_mode() -> str | None:
    """``'monitoring'`` / ``'wrap'`` / ``None`` (diagnostics only)."""
    return _mode


class ExecCounterReporter:
    """THE one implementation of shipping compile deltas with task
    reports (both worker runtimes use it, so the contract cannot drift):
    :meth:`attach` stages the unreported delta into the report's exec
    counters, and the watermark advances only in :meth:`commit` AFTER
    the report RPC succeeded — a failed report re-ships the delta with
    the next one instead of silently dropping it."""

    def __init__(self):
        self._reported = compile_count()

    def attach(self, counters: dict) -> int:
        """Stage the pending delta under ``COMPILE_COUNT_KEY`` (when
        nonzero); returns the total to pass to :meth:`commit` once the
        report went through."""
        total = compile_count()
        delta = total - self._reported
        if delta > 0:
            counters[COMPILE_COUNT_KEY] = delta
        return total

    def commit(self, total: int):
        self._reported = max(self._reported, total)


def _reset_for_tests():
    """Zero the totals (tests simulating a fresh process / generation;
    the listener registration itself is process-permanent)."""
    global _count, _secs_total
    with _lock:
        _count = 0
        _secs_total = 0.0
