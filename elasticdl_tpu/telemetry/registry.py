"""Process-local metrics registry with Prometheus text exposition.

Zero-dependency by design: the master must be able to expose metrics in
the same minimal container the workers run in, so the registry is plain
Python — no ``prometheus_client``.  Three metric kinds cover the
framework's needs:

- :class:`Counter` — monotonically increasing totals (tasks, records,
  re-formations).  ``set_total`` exists ONLY for mirroring an external
  monotone aggregate (the task dispatcher's exec-counter sums) into the
  exposition; normal code calls ``inc``.
- :class:`Gauge` — point-in-time values (live workers, model version,
  cluster generation).
- :class:`Histogram` — cumulative-bucket distributions with fixed
  log-spaced step-latency buckets (1ms .. 60s) by default, matching the
  range from a sub-millisecond CPU step to a reform-stalled one.

Families may carry labels: registering the same name again with a
different label set returns a new child of the same family (the
Prometheus data model); registering it as a different KIND is an error.
The exposition format is the Prometheus text format 0.0.4 (``# HELP`` /
``# TYPE`` + samples), which is also what the ``/metrics`` endpoint
serves.

Overhead contract: metric updates take one small per-metric lock (the
hot step path does not touch the registry at all when telemetry is
disabled — see :mod:`elasticdl_tpu.telemetry.worker_hooks`).
"""

from __future__ import annotations

import math
import re
import threading

_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")

# log-spaced step-latency buckets (seconds): 1-2.5-5 per decade, 1ms-60s
STEP_LATENCY_BUCKETS = (
    0.001,
    0.0025,
    0.005,
    0.01,
    0.025,
    0.05,
    0.1,
    0.25,
    0.5,
    1.0,
    2.5,
    5.0,
    10.0,
    30.0,
    60.0,
)

# log-spaced ONLINE-SERVING latency buckets (seconds): the step buckets
# floor at 1ms, which is where a warm in-process predict dispatch LIVES —
# every serving observation would land in the first one or two slots and
# a p99 would be unreadable.  Serving extends the same 1-2.5-5 ladder two
# decades down (100us resolution) and caps at 10s (anything slower than
# that is an outage, not a latency).  Existing step histograms keep
# STEP_LATENCY_BUCKETS unchanged (boundary-pinned by
# tests/test_serving.py): a bucket change there would desynchronize the
# monotone set_totals mirror between old and new processes mid-run.
SERVING_LATENCY_BUCKETS = (
    0.0001,
    0.00025,
    0.0005,
    0.001,
    0.0025,
    0.005,
    0.01,
    0.025,
    0.05,
    0.1,
    0.25,
    0.5,
    1.0,
    2.5,
    5.0,
    10.0,
)


def _validate_name(name: str) -> str:
    if not _NAME_RE.match(name):
        raise ValueError(
            f"metric name {name!r} must be snake_case "
            "([a-z][a-z0-9_]*; see scripts/check_telemetry_names.py)"
        )
    return name


def _format_labels(labels: tuple[tuple[str, str], ...]) -> str:
    if not labels:
        return ""
    parts = []
    for key, value in labels:
        value = str(value).replace("\\", r"\\").replace('"', r"\"")
        parts.append(f'{key}="{value}"')
    return "{" + ",".join(parts) + "}"


def _format_value(value: float) -> str:
    if value == math.inf:
        return "+Inf"
    if value == int(value):
        return str(int(value))
    return repr(value)


class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, amount: float = 1.0):
        if amount < 0:
            raise ValueError("counters only go up")
        with self._lock:
            self._value += amount

    def set_total(self, total: float):
        """Mirror an externally-accumulated monotone total (never lower
        the exposed value — scrapes must stay monotone)."""
        with self._lock:
            self._value = max(self._value, float(total))

    @property
    def value(self) -> float:
        return self._value


class Gauge:
    def __init__(self):
        self._value = 0.0

    def set(self, value: float):
        self._value = float(value)

    def inc(self, amount: float = 1.0):
        self._value += amount

    def dec(self, amount: float = 1.0):
        self._value -= amount

    @property
    def value(self) -> float:
        return self._value


class Histogram:
    def __init__(self, buckets=STEP_LATENCY_BUCKETS):
        bounds = tuple(sorted(float(b) for b in buckets))
        if not bounds:
            raise ValueError("histogram needs at least one bucket bound")
        self._lock = threading.Lock()
        self._bounds = bounds
        self._counts = [0] * (len(bounds) + 1)  # +1 for +Inf
        self._sum = 0.0
        self._count = 0

    @property
    def bounds(self) -> tuple[float, ...]:
        return self._bounds

    def observe(self, value: float):
        value = float(value)
        with self._lock:
            self._sum += value
            self._count += 1
            for i, bound in enumerate(self._bounds):
                if value <= bound:
                    self._counts[i] += 1
                    return
            self._counts[-1] += 1

    def set_totals(self, bucket_counts: dict, total_sum: float, total_count: int):
        """Mirror an externally-accumulated distribution (the
        histogram analogue of :meth:`Counter.set_total`): per-bucket
        NON-cumulative counts keyed by upper bound (floats, or the
        string forms a msgpack payload carries; ``inf``/``"inf"`` is
        the overflow slot).  Monotone per slot — a reordered or
        restarted source can never walk the exposed counts backward."""
        parsed: dict[float, int] = {}
        for bound, count in (bucket_counts or {}).items():
            try:
                parsed[float(bound)] = int(count)
            except (TypeError, ValueError):
                continue
        with self._lock:
            for i, bound in enumerate(self._bounds):
                if bound in parsed:
                    self._counts[i] = max(self._counts[i], parsed[bound])
            if math.inf in parsed:
                self._counts[-1] = max(self._counts[-1], parsed[math.inf])
            self._sum = max(self._sum, float(total_sum))
            self._count = max(self._count, int(total_count))

    def snapshot(self) -> dict:
        """Cumulative bucket counts keyed by upper bound, plus sum/count
        (the exposition shape, reusable by tests and the report CLI)."""
        with self._lock:
            counts = list(self._counts)
            total_sum, total_count = self._sum, self._count
        cumulative, acc = {}, 0
        for bound, count in zip(self._bounds, counts[:-1]):
            acc += count
            cumulative[bound] = acc
        cumulative[math.inf] = acc + counts[-1]
        return {"buckets": cumulative, "sum": total_sum, "count": total_count}

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum


_KINDS = {Counter: "counter", Gauge: "gauge", Histogram: "histogram"}


class _Family:
    def __init__(self, name: str, kind: str, help_text: str):
        self.name = name
        self.kind = kind
        self.help = help_text
        # label tuple -> metric instance
        self.children: dict[tuple[tuple[str, str], ...], object] = {}


class MetricsRegistry:
    """Name -> family -> labeled children; renders Prometheus text."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families: dict[str, _Family] = {}
        # run at exposition time so point-in-time gauges (queue depths,
        # mirrored totals) are fresh without any hot-path bookkeeping
        self._collect_callbacks: list = []

    # ---- registration (get-or-create) --------------------------------------

    def _child(self, name, kind, help_text, labels, factory):
        _validate_name(name)
        label_key = tuple(sorted((labels or {}).items()))
        for key, _ in label_key:
            _validate_name(key)
        with self._lock:
            family = self._families.get(name)
            if family is None:
                family = self._families[name] = _Family(name, kind, help_text)
            elif family.kind != kind:
                raise ValueError(
                    f"metric {name!r} already registered as {family.kind}, "
                    f"cannot re-register as {kind}"
                )
            child = family.children.get(label_key)
            if child is None:
                child = family.children[label_key] = factory()
            return child

    def counter(self, name: str, help_text: str = "", labels=None) -> Counter:
        return self._child(name, "counter", help_text, labels, Counter)

    def gauge(self, name: str, help_text: str = "", labels=None) -> Gauge:
        return self._child(name, "gauge", help_text, labels, Gauge)

    def histogram(
        self,
        name: str,
        help_text: str = "",
        labels=None,
        buckets=STEP_LATENCY_BUCKETS,
    ) -> Histogram:
        return self._child(
            name, "histogram", help_text, labels, lambda: Histogram(buckets)
        )

    def add_collect_callback(self, callback):
        """``callback(registry)`` runs before every exposition."""
        self._collect_callbacks.append(callback)

    def prune_children(self, name: str, keep_labels) -> int:
        """Drop every child of family ``name`` whose label set is not in
        ``keep_labels`` (an iterable of label dicts); returns how many
        were dropped.  This exists for CARDINALITY-BOUNDED families
        (per-worker series): when the fleet outgrows the series budget
        the per-worker children are replaced by aggregate ones, and the
        stale individual series must leave the exposition — Prometheus
        would otherwise keep scraping a thousand frozen gauges."""
        keep = {
            tuple(sorted((labels or {}).items())) for labels in keep_labels
        }
        with self._lock:
            family = self._families.get(name)
            if family is None:
                return 0
            drop = [key for key in family.children if key not in keep]
            for key in drop:
                del family.children[key]
            return len(drop)

    # ---- exposition --------------------------------------------------------

    def family_names(self) -> list[str]:
        with self._lock:
            return sorted(self._families)

    def exposition(self) -> str:
        """Prometheus text format 0.0.4."""
        for callback in list(self._collect_callbacks):
            try:
                callback(self)
            except Exception:  # noqa: BLE001 — a scrape must never fail
                pass
        with self._lock:
            families = [
                (f.name, f.kind, f.help, dict(f.children))
                for f in self._families.values()
            ]
        lines = []
        for name, kind, help_text, children in sorted(families):
            if help_text:
                lines.append(f"# HELP {name} {help_text}")
            lines.append(f"# TYPE {name} {kind}")
            for label_key in sorted(children):
                metric = children[label_key]
                if kind == "histogram":
                    snap = metric.snapshot()
                    for bound, cum in snap["buckets"].items():
                        le = label_key + (("le", _format_value(bound)),)
                        lines.append(
                            f"{name}_bucket{_format_labels(le)} {cum}"
                        )
                    labels = _format_labels(label_key)
                    lines.append(
                        f"{name}_sum{labels} {_format_value(snap['sum'])}"
                    )
                    lines.append(f"{name}_count{labels} {snap['count']}")
                else:
                    lines.append(
                        f"{name}{_format_labels(label_key)} "
                        f"{_format_value(metric.value)}"
                    )
        return "\n".join(lines) + "\n"
