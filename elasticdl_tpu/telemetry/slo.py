"""SLO watchdog plane: declarative in-run objectives, judged online.

Every telemetry plane so far MEASURES (events, spans, step anatomy,
memory ledger, rpc counters); nothing JUDGES a live run — a step-time
regression or a goodput collapse is only visible after the fact by
reading ``telemetry.report``.  This module is the judge: a declarative
set of objectives (``--slo_config`` JSON, or the built-in defaults)
evaluated on the heartbeat cadence over signals the master already
holds, using multi-window burn-rate detectors with hysteresis (the
Google SRE Workbook alerting discipline) so a transient spike neither
fires nor flaps.

Detector shape, per objective:

- each evaluation compares the signal against its threshold and appends
  one ``(t, bad)`` sample to a rolling window;
- FIRE requires the bad-share over the FAST window to reach
  ``fire_share`` (default 1.0 — consistently bad) AND the bad-share
  over the SLOW window to reach ``budget_share`` (default 0.25 — a
  real burn of the error budget, not one blip), with at least
  ``min_evals`` samples in the fast window;
- RECOVER requires the fast-window bad-share to fall to
  ``clear_share`` (default 0.0) with at least ``min_evals`` samples —
  the gap between fire and clear conditions is the hysteresis band
  that makes flapping impossible by construction.

A violation emits ``slo_violation`` events, records an ``slo_watch``
span covering the burn window, mirrors onto the ``elasticdl_slo_*``
metric families, flips the ``/healthz`` ``slo`` block, auto-arms the
PR-14 on-demand profiler (``request_profile``) and opens an incident
(:mod:`elasticdl_tpu.telemetry.incident`).

The engine takes an injectable clock: the real master evaluates on
``time.monotonic``; the fleet simulator drives the SAME engine on its
``VirtualClock`` at 1000 workers with the event digest deterministic
(no real-time read may enter evaluation).

:class:`StepTimePercentileTracker` is THE percentile definition site —
the autoscaler's grow/shrink decisions and the watchdog's step-time
objective read the same tracker (moved here from master/autoscaler.py,
semantics pinned identical by test).

Disabled cost: ``--slo_config`` defaults to None — no engine is
constructed, worker argv stays byte-identical, and the module-level
accessor is one global load + None check (``# elastic-lint:
hot-path``, clock-poison contract-tested).
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque

# the master forwards --slo_config to worker envs under this name (the
# step-anatomy env pattern: settings travel by env, never argv, so a
# worker command line is byte-identical whether the plane is on or off)
SLO_CONFIG_ENV = "ELASTICDL_TPU_SLO_CONFIG"

# ---- signal vocabulary (one definition site) ---------------------------------
#
# Every signal the engine can judge.  Producers fill what they have:
# the master derives these from servicer state each tick; the fleet
# simulator feeds only virtual-time-derived signals (a /proc read or a
# wall clock would poison its deterministic digest).

SIGNAL_STEP_TIME_P95_MS = "step_time_p95_ms"
SIGNAL_LAST_STEP_AGE_SECS = "last_step_age_secs"
SIGNAL_REFORM_DOWNTIME_SECS = "reform_downtime_secs"
SIGNAL_E2E_VS_ROOFLINE = "e2e_vs_roofline"
SIGNAL_MEMORY_HEADROOM_SHARE = "memory_headroom_share"
SIGNAL_RPC_OUTAGE_RISE = "rpc_outage_rise"
SIGNAL_QUEUE_WAIT_SHARE = "queue_wait_share"
# serving-fleet signals (derived by serving/watchdog.py from the
# router's probe-beat fan-in; absent on training runs)
SIGNAL_SERVING_LATENCY_P99_MS = "serving_latency_p99_ms"
SIGNAL_SERVING_ERROR_RATE = "serving_error_rate"
SIGNAL_SERVING_LIVE_REPLICAS = "serving_live_replicas"
SIGNAL_SERVING_SWAP_UNREACHABLE = "serving_swap_unreachable"

# outage-class RPC counters whose rise feeds SIGNAL_RPC_OUTAGE_RISE
# (the same classes the /healthz degraded-network flag watches)
OUTAGE_COUNTER_KEYS = ("deadline_exceeded", "unavailable")

# p95 window: enough samples to be a percentile, few enough to track a
# regime change within a handful of tasks (the autoscaler's historical
# window, unchanged)
_PERCENTILE_WINDOW = 128


class StepTimePercentileTracker:
    """Master-side step-time estimator riding the version-report channel.

    The chief reports ``trainer.step`` after every task; consecutive
    reports ``(t1, v1) -> (t2, v2)`` bound the mean per-step wall time
    of the ``v2 - v1`` steps between them at ``(t2 - t1) / (v2 - v1)``.
    Coarser than worker-side step spans, but master-local (no log reads
    on the control path) and it tracks exactly the quantity the dp axis
    changes: wall time per optimizer step.

    THE percentile definition site: the autoscaler
    (master/autoscaler.py) and the SLO engine read the same instance,
    so "p95 step time" can never mean two different computations.  The
    clock is injectable — production passes ``time.monotonic`` (the
    default); the fleet simulator passes its ``VirtualClock`` so the
    p95 is virtual-time-derived and deterministic."""

    def __init__(
        self, window: int = _PERCENTILE_WINDOW, clock=time.monotonic
    ):
        self._lock = threading.Lock()
        self._window = window
        self._clock = clock
        self._samples_ms: list[float] = []  # guarded-by: _lock
        self._last: tuple[float, int] | None = None  # guarded-by: _lock

    def note_version(self, worker_id: int, version: int):
        now = self._clock()
        with self._lock:
            last = self._last
            if last is not None and version > last[1]:
                per_step_ms = (now - last[0]) * 1000.0 / (version - last[1])
                self._samples_ms.append(per_step_ms)
                if len(self._samples_ms) > self._window:
                    del self._samples_ms[: -self._window]
            if last is None or version >= last[1]:
                self._last = (now, version)

    def reset(self):
        """A re-formation invalidates the baseline: the first report of
        the new world would otherwise span the whole outage."""
        with self._lock:
            self._last = None
            self._samples_ms.clear()

    def percentile_ms(self, q: float) -> float | None:
        """Nearest-index percentile over the rolling window (q in
        [0, 100]); None under 4 samples — too few to call a
        percentile.  ``p95_ms`` is this at q=95, byte-for-byte the
        autoscaler's historical computation."""
        with self._lock:
            samples = sorted(self._samples_ms)
        if len(samples) < 4:
            return None
        idx = min(
            len(samples) - 1, int(round(q / 100.0 * (len(samples) - 1)))
        )
        return samples[idx]

    def p95_ms(self) -> float | None:
        return self.percentile_ms(95.0)

    @property
    def sample_count(self) -> int:
        with self._lock:
            return len(self._samples_ms)


# ---- declarative config ------------------------------------------------------

# multi-window burn-rate defaults: the fast window catches a sustained
# regression within ~half a minute, the slow window demands a real
# budget burn so one blip among healthy evals never fires
DEFAULT_WINDOWS = {"fast_secs": 30.0, "slow_secs": 300.0, "min_evals": 3}
DEFAULT_HYSTERESIS = {
    "fire_share": 1.0,
    "budget_share": 0.25,
    "clear_share": 0.0,
}
# auto-baseline: learn the healthy value from this many measured evals
# (median), then judge against baseline * baseline_factor
DEFAULT_BASELINE_EVALS = 5
DEFAULT_PROFILE_STEPS = 5

DEFAULT_OBJECTIVES = (
    # step-time regression vs the run's own healthy baseline: no
    # absolute threshold generalizes across models, so the default
    # learns one (an explicit "threshold" in --slo_config overrides)
    {
        "name": "step_time_p95",
        "signal": SIGNAL_STEP_TIME_P95_MS,
        "comparator": "above",
        "baseline_factor": 2.0,
    },
    {
        "name": "progress_stall",
        "signal": SIGNAL_LAST_STEP_AGE_SECS,
        "comparator": "above",
        "threshold": 120.0,
    },
    {
        "name": "reform_downtime_budget",
        "signal": SIGNAL_REFORM_DOWNTIME_SECS,
        "comparator": "above",
        "threshold": 60.0,
    },
    {
        "name": "goodput_floor",
        "signal": SIGNAL_E2E_VS_ROOFLINE,
        "comparator": "below",
        "threshold": 0.3,
    },
    {
        "name": "memory_headroom",
        "signal": SIGNAL_MEMORY_HEADROOM_SHARE,
        "comparator": "below",
        "threshold": 0.05,
    },
    {
        "name": "rpc_outage",
        "signal": SIGNAL_RPC_OUTAGE_RISE,
        "comparator": "above",
        "threshold": 0.0,
    },
    {
        "name": "serving_queue_wait",
        "signal": SIGNAL_QUEUE_WAIT_SHARE,
        "comparator": "above",
        "threshold": 0.5,
    },
)

_COMPARATORS = ("above", "below")


def parse_slo_config(raw: str | None) -> dict | None:
    """Normalize a ``--slo_config`` value into an engine config.

    ``None``/empty → None (the plane stays off).  ``"default"`` (also
    ``"defaults"``/``"on"``/``"1"``) → the built-in objectives.  A
    string starting with ``{`` → inline JSON.  Anything else → a path
    to a JSON file.  The JSON may carry ``objectives`` (list; each
    entry may override ``windows``/``hysteresis`` per objective),
    top-level ``windows``/``hysteresis`` defaults, and
    ``profile_steps`` for the auto-armed capture window."""
    if not raw:
        return None
    raw = raw.strip()
    if raw.lower() in ("default", "defaults", "on", "1", "true"):
        doc: dict = {}
    elif raw.startswith("{"):
        doc = json.loads(raw)
    else:
        with open(raw, encoding="utf-8") as f:
            doc = json.load(f)
    windows = {**DEFAULT_WINDOWS, **(doc.get("windows") or {})}
    hysteresis = {**DEFAULT_HYSTERESIS, **(doc.get("hysteresis") or {})}
    objectives = []
    for spec in doc.get("objectives") or [dict(o) for o in DEFAULT_OBJECTIVES]:
        spec = dict(spec)
        if not spec.get("name") or not spec.get("signal"):
            raise ValueError(f"slo objective needs name+signal: {spec!r}")
        comparator = spec.get("comparator", "above")
        if comparator not in _COMPARATORS:
            raise ValueError(
                f"slo objective {spec['name']!r}: comparator must be one "
                f"of {_COMPARATORS}, got {comparator!r}"
            )
        spec["comparator"] = comparator
        if spec.get("threshold") is None and not spec.get("baseline_factor"):
            raise ValueError(
                f"slo objective {spec['name']!r} needs a threshold or a "
                "baseline_factor"
            )
        spec["windows"] = {**windows, **(spec.get("windows") or {})}
        spec["hysteresis"] = {**hysteresis, **(spec.get("hysteresis") or {})}
        objectives.append(spec)
    return {
        "objectives": objectives,
        "windows": windows,
        "hysteresis": hysteresis,
        "profile_steps": int(
            doc.get("profile_steps", DEFAULT_PROFILE_STEPS)
        ),
    }


# ---- pure signal derivations -------------------------------------------------
#
# Pure functions from merged servicer state to signal values, so the
# property tests can pin the whole chain: heartbeats → utils/merge.py
# (order/duplication/batch-replay insensitive) → these → the detector.


def signals_from_phase_totals(phase_totals: dict) -> dict:
    """Anatomy-derived signals from the servicer's fleet-wide phase
    totals (``{phase: {"ms", ...}}``): the measured ``e2e_vs_roofline``
    (binding-path busy time over wall — the goodput section's
    definition, over cumulative totals) and the serving router's
    ``queue_wait`` share.  ``{}`` when no phases were reported."""
    if not phase_totals:
        return {}

    def ms(phase: str) -> float:
        try:
            return float((phase_totals.get(phase) or {}).get("ms", 0.0))
        except (TypeError, ValueError):
            return 0.0

    wall = sum(ms(p) for p in phase_totals)
    if wall <= 0:
        return {}
    host = ms("host_fetch")
    device_path = ms("assemble") + ms("h2d_transfer") + ms("device_compute")
    signals: dict = {}
    if host or device_path:
        signals[SIGNAL_E2E_VS_ROOFLINE] = max(host, device_path) / wall
    queue_wait = ms("queue_wait")
    if queue_wait:
        signals[SIGNAL_QUEUE_WAIT_SHARE] = queue_wait / wall
    return signals


def outage_total(rpc_totals: dict) -> int:
    """Sum of the outage-class counters in a fleet-wide RPC totals map
    (max-merged, so order-insensitive by construction)."""
    total = 0
    for key in OUTAGE_COUNTER_KEYS:
        try:
            total += int(rpc_totals.get(key, 0))
        except (TypeError, ValueError):
            continue
    return total


class _ObjectiveState:
    """One objective's rolling burn window + hysteresis latch."""

    def __init__(self, spec: dict):
        self.spec = spec
        self.name = spec["name"]
        self.signal = spec["signal"]
        self.comparator = spec["comparator"]
        self.threshold = spec.get("threshold")
        self.baseline_factor = spec.get("baseline_factor")
        self.baseline_evals = int(
            spec.get("baseline_evals", DEFAULT_BASELINE_EVALS)
        )
        w = spec["windows"]
        self.fast_secs = float(w["fast_secs"])
        self.slow_secs = float(w["slow_secs"])
        self.min_evals = int(w["min_evals"])
        h = spec["hysteresis"]
        self.fire_share = float(h["fire_share"])
        self.budget_share = float(h["budget_share"])
        self.clear_share = float(h["clear_share"])
        self.samples: deque = deque()  # (t, bad)
        self.baseline_samples: list[float] = []
        self.baseline: float | None = None
        self.fired = False
        self.fired_at: float | None = None
        self.bad_since: float | None = None
        self.last_value: float | None = None
        self.burn_fast: float | None = None
        self.burn_slow: float | None = None
        self.violations = 0
        self.evaluations = 0

    def _resolve_threshold(self, value: float) -> float | None:
        if self.threshold is not None:
            return float(self.threshold)
        # auto-baseline: learn the healthy level from the first
        # measured evals (median is spike-robust), then judge against
        # baseline * factor
        if self.baseline is None:
            self.baseline_samples.append(value)
            if len(self.baseline_samples) < self.baseline_evals:
                return None
            ordered = sorted(self.baseline_samples)
            self.baseline = ordered[len(ordered) // 2]
        return self.baseline * float(self.baseline_factor)

    def _is_bad(self, value: float, threshold: float) -> bool:
        if self.comparator == "above":
            return value > threshold
        return value < threshold

    def observe(self, value: float, now: float) -> str | None:
        """One evaluation: returns ``"violation"``/``"recovery"`` on a
        state transition, else None.  Pure detector math — no clocks,
        no emission (the engine owns side effects)."""
        self.evaluations += 1
        self.last_value = value
        threshold = self._resolve_threshold(value)
        if threshold is None:
            return None  # still learning the baseline
        bad = self._is_bad(value, threshold)
        self.samples.append((now, bad))
        # evict past the slow window; the boundary sample (exactly
        # slow_secs old) stays — windows are closed intervals, pinned
        # by the edge-case tests
        cutoff = now - self.slow_secs
        while self.samples and self.samples[0][0] < cutoff:
            self.samples.popleft()
        fast_cutoff = now - self.fast_secs
        fast = [s for s in self.samples if s[0] >= fast_cutoff]
        fast_bad = sum(1 for _t, b in fast if b)
        slow_bad = sum(1 for _t, b in self.samples if b)
        self.burn_fast = fast_bad / len(fast) if fast else None
        self.burn_slow = (
            slow_bad / len(self.samples) if self.samples else None
        )
        if bad and self.bad_since is None:
            self.bad_since = now
        elif not bad:
            self.bad_since = None
        if not self.fired:
            if (
                len(fast) >= self.min_evals
                and self.burn_fast is not None
                and self.burn_fast >= self.fire_share
                and self.burn_slow is not None
                and self.burn_slow >= self.budget_share
            ):
                self.fired = True
                self.fired_at = now
                self.violations += 1
                return "violation"
        else:
            if (
                len(fast) >= self.min_evals
                and self.burn_fast is not None
                and self.burn_fast <= self.clear_share
            ):
                self.fired = False
                self.fired_at = None
                return "recovery"
        return None

    def snapshot(self) -> dict:
        return {
            "ok": not self.fired,
            "signal": self.signal,
            "value": self.last_value,
            "threshold": self.threshold
            if self.threshold is not None
            else (
                self.baseline * float(self.baseline_factor)
                if self.baseline is not None
                else None
            ),
            "comparator": self.comparator,
            "burn_fast": self.burn_fast,
            "burn_slow": self.burn_slow,
            "violations": self.violations,
            "evaluations": self.evaluations,
        }


class SLOEngine:
    """The declarative watchdog: evaluate objectives, emit on
    transitions, arm the profiler, open/close incidents.

    ``emit`` is the event sink (``fn(event, **fields)``), ``tracer`` a
    SpanRecorder (or None), ``arm_profiler`` a zero-result callback
    taking ``num_steps`` (the master binds ``request_profile``),
    ``incidents`` an :class:`~elasticdl_tpu.telemetry.incident.
    IncidentManager` (or None).  All sinks are optional so the
    property tests drive the pure detector directly."""

    def __init__(
        self,
        config: dict,
        clock=time.monotonic,
        emit=None,
        tracer=None,
        arm_profiler=None,
        incidents=None,
    ):
        self._config = config
        self._clock = clock
        self._emit = emit
        self._tracer = tracer
        self._arm_profiler = arm_profiler
        self.incidents = incidents
        self.profile_steps = int(
            config.get("profile_steps", DEFAULT_PROFILE_STEPS)
        )
        self._objectives = [
            _ObjectiveState(spec) for spec in config["objectives"]
        ]
        self.tracker = StepTimePercentileTracker(clock=clock)
        self._lock = threading.Lock()
        # rolling reform-downtime ledger (the budget objective's
        # signal): (t, secs) pairs summed over the slow window
        self._reform_downtimes: deque = deque()  # guarded-by: _lock
        self._prev_outage_total: int | None = None  # guarded-by: _lock
        self.evaluations = 0
        self.transitions: list[dict] = []

    # ---- signal ingestion ---------------------------------------------------

    def note_version(self, worker_id: int, version: int):
        """Version-observer seam (wired when no autoscaler shares the
        tracker)."""
        self.tracker.note_version(worker_id, version)

    def note_reform(self):
        self.tracker.reset()

    def note_reform_downtime(self, secs: float, now: float | None = None):
        now = self._clock() if now is None else now
        with self._lock:
            self._reform_downtimes.append((now, float(secs)))

    def ingest_rpc_totals(self, rpc_totals: dict) -> float:
        """Outage-class counter rise since the previous evaluation
        (totals are max-merged fleet-wide maxima, so any beat
        order/duplication/batching converges to the same rise
        sequence — the merge pin discipline, property-tested)."""
        total = outage_total(rpc_totals or {})
        with self._lock:
            prev = self._prev_outage_total
            self._prev_outage_total = total
        if prev is None:
            return 0.0  # first read seeds silently (the /healthz rule)
        return float(max(0, total - prev))

    def _reform_downtime_window(self, now: float) -> float:
        slow = max(
            (o.slow_secs for o in self._objectives),
            default=DEFAULT_WINDOWS["slow_secs"],
        )
        with self._lock:
            while (
                self._reform_downtimes
                and self._reform_downtimes[0][0] < now - slow
            ):
                self._reform_downtimes.popleft()
            return sum(secs for _t, secs in self._reform_downtimes)

    # ---- evaluation ---------------------------------------------------------

    def evaluate(self, signals: dict, now: float | None = None) -> list[dict]:
        """One watchdog tick.  ``signals`` maps signal names to
        measured values (missing/None = not measured this tick — the
        objective stays dormant, its window does not advance).
        Auto-injects the tracker's p95 and the rolling reform-downtime
        sum when the caller did not.  Returns the transition list
        (empty almost always)."""
        now = self._clock() if now is None else now
        signals = dict(signals)
        if SIGNAL_STEP_TIME_P95_MS not in signals:
            p95 = self.tracker.p95_ms()
            if p95 is not None:
                signals[SIGNAL_STEP_TIME_P95_MS] = p95
        signals.setdefault(
            SIGNAL_REFORM_DOWNTIME_SECS, self._reform_downtime_window(now)
        )
        self.evaluations += 1
        transitions = []
        for state in self._objectives:
            value = signals.get(state.signal)
            if value is None:
                continue
            try:
                value = float(value)
            except (TypeError, ValueError):
                continue
            kind = state.observe(value, now)
            if kind is None:
                continue
            transition = {
                "kind": kind,
                "objective": state.name,
                "signal": state.signal,
                "value": value,
                "threshold": state.snapshot()["threshold"],
                "burn_fast": state.burn_fast,
                "burn_slow": state.burn_slow,
                "at": now,
                "bad_since": state.bad_since,
            }
            transitions.append(transition)
            self.transitions.append(transition)
            self._emit_transition(transition, now)
        return transitions

    def _emit_transition(self, transition: dict, now: float):
        from elasticdl_tpu.telemetry.events import (
            EVENT_SLO_RECOVERED,
            EVENT_SLO_VIOLATION,
        )

        violation = transition["kind"] == "violation"
        if self._emit is not None:
            try:
                self._emit(
                    EVENT_SLO_VIOLATION
                    if violation
                    else EVENT_SLO_RECOVERED,
                    objective=transition["objective"],
                    signal=transition["signal"],
                    value=transition["value"],
                    threshold=transition["threshold"],
                    burn_fast=transition["burn_fast"],
                    burn_slow=transition["burn_slow"],
                )
            except Exception:  # noqa: BLE001 — telemetry never raises
                # into the run loop
                pass
        if violation and self._tracer is not None:
            from elasticdl_tpu.telemetry.tracing import SPAN_SLO_WATCH

            try:
                # the span covers the burn: first bad eval -> fire
                self._tracer.record_span(
                    SPAN_SLO_WATCH,
                    transition.get("bad_since") or now,
                    now,
                    objective=transition["objective"],
                    signal=transition["signal"],
                    value=transition["value"],
                    threshold=transition["threshold"],
                )
            except Exception:  # noqa: BLE001 — same contract
                pass
        if violation:
            # open the incident BEFORE arming: the arm callback attaches
            # the capture window to the open incident
            # (note_profile_window), which must exist by then
            if self.incidents is not None:
                self.incidents.on_violation(transition, now)
            if self._arm_profiler is not None:
                try:
                    self._arm_profiler(self.profile_steps)
                except Exception:  # noqa: BLE001 — a failed arm must
                    # not break detection
                    pass
        elif self.incidents is not None:
            self.incidents.on_recovery(transition, now, self.all_clear())

    def all_clear(self) -> bool:
        return not any(o.fired for o in self._objectives)

    def active_violations(self) -> list[str]:
        return [o.name for o in self._objectives if o.fired]

    # ---- surfaces -----------------------------------------------------------

    def health_block(self) -> dict:
        """The /healthz ``slo`` block: overall verdict + per-objective
        state."""
        objectives = {o.name: o.snapshot() for o in self._objectives}
        return {
            "ok": self.all_clear(),
            "active_violations": self.active_violations(),
            "evaluations": self.evaluations,
            "objectives": objectives,
            "incidents_open": (
                self.incidents.open_count if self.incidents else 0
            ),
            "incidents_total": (
                self.incidents.total_count if self.incidents else 0
            ),
        }

    def mirror_metrics(self, registry):
        """Scrape-time mirror onto the ``elasticdl_slo_*`` families
        (the one registration site of each; telemetry-names contract).
        """
        for state in self._objectives:
            labels = {"objective": state.name}
            registry.counter(
                "elasticdl_slo_violations_total",
                "SLO objective violations (burn-rate detector firings)",
                labels=labels,
            ).set_total(state.violations)
            registry.gauge(
                "elasticdl_slo_objective_ok",
                "1 when the objective is within SLO, 0 while violated",
                labels=labels,
            ).set(0 if state.fired else 1)
            for window, burn in (
                ("fast", state.burn_fast),
                ("slow", state.burn_slow),
            ):
                registry.gauge(
                    "elasticdl_slo_burn_rate",
                    "Bad-evaluation share over the detector window",
                    labels={"objective": state.name, "window": window},
                ).set(burn if burn is not None else 0.0)
        if self.incidents is not None:
            registry.counter(
                "elasticdl_slo_incidents_total",
                "Incidents opened by the SLO watchdog",
            ).set_total(self.incidents.total_count)


# ---- module-level install + zero-cost-when-disabled accessor -----------------

_active: SLOEngine | None = None


def install(config: dict, **kwargs) -> SLOEngine:
    global _active
    _active = SLOEngine(config, **kwargs)
    return _active


def install_if_enabled(raw_config: str | None, **kwargs) -> SLOEngine | None:
    """Install when ``--slo_config`` is set; clears any stale engine
    otherwise (the memory-ledger lifecycle contract: a watchdog-less
    master constructed after an instrumented one inherits nothing)."""
    config = parse_slo_config(raw_config)
    if config is None:
        uninstall()
        return None
    return install(config, **kwargs)


def install_from_env(**kwargs) -> SLOEngine | None:
    return install_if_enabled(os.environ.get(SLO_CONFIG_ENV, ""), **kwargs)


def uninstall():
    global _active
    _active = None


def get_engine() -> SLOEngine | None:  # elastic-lint: hot-path
    """THE disabled-path gate: one global load + None check (clock-
    poison contract-tested — a disabled watchdog reads no clock)."""
    return _active
