"""Causal span tracing across the master↔worker control plane.

Dapper-style distributed tracing with zero dependencies: every span
carries ``trace_id`` / ``span_id`` / ``parent_span_id``, one TASK is one
trace across master and workers (the trace context rides the RPC
messages — :mod:`elasticdl_tpu.rpc.messages`), and the reform state
machine gets its own trace so ``trace analyze`` can break re-formation
downtime into named phases.

Clocks: spans record ``start``/``end`` on the machine-wide
CLOCK_MONOTONIC (same discipline as the event log — single-host runs
subtract across processes) plus a wall-clock ``time`` at span start.

Storage: finished spans accumulate in a bounded in-memory ring buffer
and are spilled as JSONL batches into ``<telemetry_dir>/spans.jsonl``
(O_APPEND, shared by master and worker subprocesses like
``events.jsonl``; size-based rotation via :mod:`.events`).  A span lost
to a SIGKILL'd buffer is an accepted trade — lifecycle emitters call
:func:`flush` at phase boundaries, and the chaos preempt path kills
workers whose spans of record (dispatch, recovery, reform) live on the
master side.

Sampling: hot-path spans (``train_step``, ``heartbeat``) pass
``sampled=True`` and are kept deterministically 1-in-N per name
(``--trace_sample_rate``; the count-based rule is reproducible across
runs, unlike coin flips).  Lifecycle/reform spans are always recorded.

Overhead contract: with no tracer installed every module-level hook is
one global load and a ``None`` check — the same bar as
:mod:`.worker_hooks` (tests poison the clock to prove it).
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time

from elasticdl_tpu.telemetry.events import (
    read_jsonl,
    rotate_if_needed,
)
from elasticdl_tpu.utils.log_utils import default_logger as logger

SPANS_FILENAME = "spans.jsonl"

TRACE_SAMPLE_RATE_ENV = "ELASTICDL_TPU_TRACE_SAMPLE_RATE"
TRACE_PARENT_ENV = "ELASTICDL_TPU_TRACE_PARENT"

DEFAULT_SAMPLE_RATE = 0.05

# ---- span-name vocabulary (one definition site per name; linted) ------------

SPAN_TASK_LIFECYCLE = "task_lifecycle"  # master: lease -> report
SPAN_TASK_EXECUTE = "task_execute"  # worker: fetch + steps of one task
SPAN_GET_TASK = "get_task"  # worker: the lease RPC
SPAN_DATA_FETCH = "data_fetch"  # worker: first-batch host decode
SPAN_TRAIN_STEP = "train_step"  # worker: inter-step interval (sampled)
SPAN_REPORT_TASK = "report_task"  # worker: the report RPC
SPAN_HEARTBEAT = "heartbeat"  # worker: liveness ping (sampled)
SPAN_REFORM = "reform"  # master: whole re-formation
SPAN_REFORM_FENCE = "reform_fence_recover"  # master: fence + task recovery
SPAN_REFORM_RELAUNCH = "reform_relaunch"  # master: kill + respawn world
SPAN_WORLD_JOIN = "world_join"  # worker: process start -> world joined
SPAN_WORLD_INITIALIZE = "world_initialize"  # worker: jax.distributed init
SPAN_TRAINER_BUILD = "trainer_build"  # worker: SPMDTrainer construction
SPAN_CHECKPOINT_SAVE = "checkpoint_save_snapshot"  # device->host snapshot
SPAN_CHECKPOINT_RESTORE = "checkpoint_restore_state"  # restore + re-place
SPAN_PROFILE_WINDOW = "profile_window"  # XLA profiler capture window
SPAN_REPLICA_PUSH = "replica_push"  # worker: snapshot + ring-neighbor push
SPAN_REPLICA_HARVEST = "replica_harvest"  # master: fetch peer shards on reform
SPAN_REPLICA_RESTORE = "replica_restore"  # worker: restore from peer RAM
SPAN_COMPILE = "compile"  # any process: one XLA backend compile
SPAN_MASTER_RESTART = "master_restart"  # master: restore start -> serving
SPAN_JOURNAL_REPLAY = "journal_replay"  # master: journal replay proper
SPAN_WORKER_REHOME = "worker_rehome"  # master: one re-home handshake
SPAN_SLICE_LOSS = "slice_loss"  # master: slice death detect -> re-plan
SPAN_MESH_RESIZE = "mesh_resize"  # master: hybrid mesh re-plan (resize)
SPAN_AUTOSCALE_DECISION = "autoscale_decision"  # master: one SLO decision
SPAN_RPC_DEGRADED = "rpc_degraded"  # netem window: link slow/blackholed
SPAN_STEP_ANATOMY = "step_anatomy"  # one dispatch phase (phase= attr)
SPAN_SERVING_REQUEST = "serving_request"  # serving: one request (sampled)
SPAN_MODEL_SWAP = "model_swap"  # serving: one hot model swap
SPAN_FLEET_FAULT = "fleet_fault"  # fleetsim: one mass-fault injection
SPAN_SLO_WATCH = "slo_watch"  # slo: burn window, first bad eval -> fire
# serving fleet request tracing: one predict request is ONE trace —
# the client's root, the router's (re)route children, the replica's
# queue-vs-engine split, and the shared dispatch group LINKED (not
# parented: one group serves many traces) to every member request
SPAN_PREDICT_REQUEST = "predict_request"  # client: root, send -> response
SPAN_SERVING_ROUTE = "route"  # router: first routing attempt
SPAN_SERVING_REROUTE = "reroute"  # router: retry/eviction re-attempt
SPAN_SERVING_QUEUE = "queue"  # replica: submit -> first dispatch
SPAN_SERVING_ENGINE = "engine"  # replica: first dispatch -> delivered
SPAN_SERVING_DISPATCH = "serving_dispatch"  # replica: one batch group
SPAN_LIVE_PUSH = "live_push"  # master: harvest -> serving swap accepted


def gen_trace_id() -> str:
    """128-bit trace id as 32 hex chars (W3C traceparent width)."""
    return os.urandom(16).hex()


def gen_span_id() -> str:
    """64-bit span id as 16 hex chars."""
    return os.urandom(8).hex()


class Span:
    """One in-flight span; ``end()`` hands it to the recorder.  Usable
    as a context manager (ends on exit, success/error annotated)."""

    __slots__ = (
        "name",
        "trace_id",
        "span_id",
        "parent_span_id",
        "start_time",
        "start",
        "attrs",
        "_recorder",
        "_ended",
    )

    def __init__(self, recorder, name, trace_id, parent_span_id, attrs):
        self.name = name
        self.trace_id = trace_id
        self.span_id = gen_span_id()
        self.parent_span_id = parent_span_id
        self.start_time = time.time()
        self.start = time.monotonic()
        self.attrs = attrs
        self._recorder = recorder
        self._ended = False

    def set(self, **attrs):
        self.attrs.update(attrs)

    @property
    def context(self) -> dict:
        """The propagatable trace context (what rides an RPC field)."""
        return {"trace_id": self.trace_id, "span_id": self.span_id}

    def end(self, **attrs):
        if self._ended:
            return
        self._ended = True
        if attrs:
            self.attrs.update(attrs)
        self._recorder._finish(self, time.monotonic())

    def __enter__(self):
        return self

    def __exit__(self, exc_type, _exc, _tb):
        if exc_type is not None:
            self.attrs.setdefault("error", exc_type.__name__)
        self.end()
        return False


class SpanRecorder:
    """Thread-safe span sink for one process.

    ``path=''`` disables persistence (spans are dropped at ``_finish``)
    but the object stays fully usable, so call sites never branch.
    """

    def __init__(
        self,
        path: str = "",
        role: str = "worker",
        worker_id: int = 0,
        process_id: int = 0,
        generation: int = 0,
        sample_rate: float = DEFAULT_SAMPLE_RATE,
        buffer_spans: int = 64,
    ):
        self._path = path
        self._role = role
        self._worker_id = worker_id
        self._process_id = process_id
        self._generation = generation
        # 1-in-N deterministic sampling; rate >= 1 keeps everything,
        # rate <= 0 drops every sampled-class span
        self._sample_period = (
            1 if sample_rate >= 1.0 else (0 if sample_rate <= 0.0 else round(1.0 / sample_rate))
        )
        self._sample_counts: dict[str, int] = {}  # guarded-by: _lock
        self._buffer: list[dict] = []  # guarded-by: _lock
        self._buffer_spans = max(1, buffer_spans)
        self._lock = threading.Lock()
        self._last_step_at: float | None = None
        self._last_step: int | None = None
        # thread-local context stack: nested spans parent implicitly
        self._tls = threading.local()
        if path:
            parent = os.path.dirname(path)
            if parent:
                os.makedirs(parent, exist_ok=True)

    @property
    def enabled(self) -> bool:
        return bool(self._path)

    @property
    def generation(self) -> int:
        return self._generation

    # ---- context stack -----------------------------------------------------

    def _stack(self) -> list:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def current_context(self) -> dict | None:
        stack = self._stack()
        return stack[-1] if stack else None

    # ---- span creation -----------------------------------------------------

    def _resolve(self, trace_ctx: dict | None) -> tuple[str, str]:
        """(trace_id, parent_span_id) from an explicit context, the
        thread's implicit stack, or a fresh root trace."""
        ctx = trace_ctx if (trace_ctx and trace_ctx.get("trace_id")) else self.current_context()
        if ctx:
            return ctx["trace_id"], ctx.get("span_id", "")
        return gen_trace_id(), ""

    def start_span(self, name: str, trace_ctx: dict | None = None, **attrs) -> Span:
        trace_id, parent = self._resolve(trace_ctx)
        return Span(self, name, trace_id, parent, attrs)

    @contextlib.contextmanager
    def span(self, name: str, trace_ctx: dict | None = None, **attrs):
        """Context-managed span that also pushes itself as the implicit
        parent for spans opened inside the block."""
        sp = self.start_span(name, trace_ctx=trace_ctx, **attrs)
        stack = self._stack()
        stack.append(sp.context)
        try:
            yield sp
        except BaseException as ex:
            sp.attrs.setdefault("error", type(ex).__name__)
            raise
        finally:
            stack.pop()
            sp.end()

    def record_span(
        self,
        name: str,
        start_monotonic: float,
        end_monotonic: float,
        trace_ctx: dict | None = None,
        sampled: bool = False,
        **attrs,
    ) -> bool:
        """Record a RETROACTIVE span from explicit clock readings (the
        per-step and RPC hooks measure first, record after).  Returns
        False when the sampler dropped it."""
        if sampled and not self._sample(name):
            return False
        trace_id, parent = self._resolve(trace_ctx)
        record = self._base_record(name, trace_id, parent)
        record["time"] = time.time() - (time.monotonic() - start_monotonic)
        record["start"] = start_monotonic
        record["end"] = end_monotonic
        if attrs:
            record.update(attrs)
        self._push(record)
        return True

    def on_step(self, step: int):
        """The hot-path step hook: record a sampled ``train_step`` span
        covering the interval since the previous call (the same
        semantics as :func:`worker_hooks.record_step` durations).  A
        generation change resets the interval (new recorder per world,
        but the local executor reuses one)."""
        now = time.monotonic()
        last_at, last_step = self._last_step_at, self._last_step
        self._last_step_at, self._last_step = now, step
        if last_at is None:
            return
        self.record_span(
            SPAN_TRAIN_STEP,
            last_at,
            now,
            sampled=True,
            step=int(last_step) if last_step is not None else None,
        )

    def should_sample(self, name: str) -> bool:
        """Public face of the deterministic 1-in-N sampler for callers
        that make ONE keep/drop decision covering a group of related
        records (the step-anatomy phase spans of one dispatch)."""
        return self._sample(name)

    def _sample(self, name: str) -> bool:
        if self._sample_period == 1:
            return True
        if self._sample_period == 0:
            return False
        with self._lock:
            n = self._sample_counts.get(name, 0)
            self._sample_counts[name] = n + 1
        return n % self._sample_period == 0

    # ---- persistence -------------------------------------------------------

    def _base_record(self, name, trace_id, parent_span_id) -> dict:
        return {
            "span": name,
            "trace_id": trace_id,
            "span_id": gen_span_id(),
            "parent_span_id": parent_span_id,
            "role": self._role,
            "worker_id": self._worker_id,
            "process_id": self._process_id,
            "generation": self._generation,
        }

    def _finish(self, span: Span, end_monotonic: float):
        record = {
            "span": span.name,
            "trace_id": span.trace_id,
            "span_id": span.span_id,
            "parent_span_id": span.parent_span_id,
            "role": self._role,
            "worker_id": self._worker_id,
            "process_id": self._process_id,
            "generation": self._generation,
            "time": span.start_time,
            "start": span.start,
            "end": end_monotonic,
        }
        if span.attrs:
            record.update(span.attrs)
        self._push(record)

    def _push(self, record: dict):
        if not self._path:
            return
        with self._lock:
            self._buffer.append(record)
            if len(self._buffer) < self._buffer_spans:
                return
            batch, self._buffer = self._buffer, []
        self._write(batch)

    def flush(self):
        """Spill everything buffered so far to disk."""
        with self._lock:
            batch, self._buffer = self._buffer, []
        if batch:
            self._write(batch)

    def _write(self, batch: list[dict]):
        try:
            rotate_if_needed(self._path)
            payload = "".join(json.dumps(r) + "\n" for r in batch)
            with open(self._path, "a", encoding="utf-8") as f:
                f.write(payload)
        except OSError:
            logger.exception("Telemetry span log write failed")


def read_spans(path: str) -> list[dict]:
    """Parse one spans.jsonl (plus rotated shards), skipping torn lines."""
    return read_jsonl(path)


# ---- module-level install + zero-cost-when-disabled accessors ---------------

_active: SpanRecorder | None = None


def install(
    telemetry_dir: str,
    role: str = "worker",
    worker_id: int = 0,
    process_id: int = 0,
    generation: int = 0,
    sample_rate: float | None = None,
) -> SpanRecorder | None:
    """Install the process-wide tracer writing to
    ``<telemetry_dir>/spans.jsonl``; returns it (None if no dir)."""
    global _active
    if not telemetry_dir:
        return None
    if sample_rate is None:
        sample_rate = sample_rate_from_env()
    _active = SpanRecorder(
        os.path.join(telemetry_dir, SPANS_FILENAME),
        role=role,
        worker_id=worker_id,
        process_id=process_id,
        generation=generation,
        sample_rate=sample_rate,
    )
    return _active


def install_from_env(
    worker_id: int = 0, process_id: int = 0, generation: int = 0
) -> SpanRecorder | None:
    """Install from ``ELASTICDL_TPU_TELEMETRY_DIR`` (worker subprocess
    entry); no-op when the master did not configure telemetry."""
    from elasticdl_tpu.telemetry.worker_hooks import TELEMETRY_DIR_ENV

    return install(
        os.environ.get(TELEMETRY_DIR_ENV, ""),
        worker_id=worker_id,
        process_id=process_id,
        generation=generation,
    )


def sample_rate_from_env() -> float:
    try:
        return float(os.environ.get(TRACE_SAMPLE_RATE_ENV, DEFAULT_SAMPLE_RATE))
    except ValueError:
        return DEFAULT_SAMPLE_RATE


def parent_from_env() -> dict | None:
    """The trace context the spawner exported (the reform trace for a
    relaunched world), or None."""
    raw = os.environ.get(TRACE_PARENT_ENV, "")
    if not raw:
        return None
    try:
        ctx = json.loads(raw)
    except ValueError:
        return None
    return ctx if isinstance(ctx, dict) and ctx.get("trace_id") else None


def uninstall():
    global _active
    _active = None


def get_tracer() -> SpanRecorder | None:  # elastic-lint: hot-path
    return _active


@contextlib.contextmanager
def trace_span(name: str, trace_ctx: dict | None = None, **attrs):  # elastic-lint: hot-path
    """Context-managed span on the installed tracer; yields None (and
    costs one global load + None check) when tracing is disabled."""
    tracer = _active
    if tracer is None:
        yield None
        return
    with tracer.span(name, trace_ctx=trace_ctx, **attrs) as sp:
        yield sp


def record_step_span(step: int):  # elastic-lint: hot-path
    """THE hot-path hook: one global load + None check when disabled."""
    tracer = _active
    if tracer is None:
        return
    tracer.on_step(step)


def trace_fetches(iterable, trace_ctx: dict | None = None, span=None):  # elastic-lint: hot-path
    """Wrap a batch stream so the FIRST host-side fetch (shard open +
    decode — the serial cost a step actually waits on) becomes a
    ``data_fetch`` span, and the total fetch wall-clock is annotated on
    ``span`` (the task's execute span) when given.  Passthrough when
    tracing is disabled."""
    tracer = _active
    if tracer is None:
        yield from iterable
        return
    it = iter(iterable)
    first = True
    fetch_secs = 0.0
    while True:
        t0 = time.monotonic()
        try:
            item = next(it)
        except StopIteration:
            break
        t1 = time.monotonic()
        fetch_secs += t1 - t0
        if first:
            first = False
            tracer.record_span(
                SPAN_DATA_FETCH, t0, t1, trace_ctx=trace_ctx
            )
        yield item
    if span is not None:
        span.set(data_fetch_secs=round(fetch_secs, 6))


def flush():  # elastic-lint: hot-path
    tracer = _active
    if tracer is not None:
        tracer.flush()
