"""Master telemetry HTTP endpoint: ``/metrics`` + ``/healthz``.

A stdlib ``ThreadingHTTPServer`` on a daemon thread — scrapes must never
touch the control-plane gRPC port or the run loop.  ``/metrics`` serves
the registry's Prometheus text; ``/healthz`` serves a JSON snapshot from
a caller-provided callable (generation, live workers, model version,
quiesce state), so the server itself holds no master state.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from elasticdl_tpu.utils.log_utils import default_logger as logger

PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class TelemetryHTTPServer:
    def __init__(
        self,
        registry,
        health_fn=None,
        port: int = 0,
        host: str = "127.0.0.1",
    ):
        """``host`` defaults to loopback: the endpoint is unauthenticated,
        so exposing it beyond the machine (``--metrics_host 0.0.0.0`` for
        a k8s scrape sidecar) is an explicit operator decision."""
        self._registry = registry
        self._health_fn = health_fn
        self._requested_port = port
        self._host = host
        self._server: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None

    @property
    def port(self) -> int | None:
        return self._server.server_address[1] if self._server else None

    def start(self):
        registry, health_fn = self._registry, self._health_fn

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler API
                path = self.path.split("?", 1)[0]
                if path == "/metrics":
                    body = registry.exposition().encode("utf-8")
                    ctype = PROMETHEUS_CONTENT_TYPE
                elif path == "/healthz":
                    payload = health_fn() if health_fn is not None else {}
                    body = json.dumps(payload).encode("utf-8")
                    ctype = "application/json"
                else:
                    self.send_error(404)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, fmt, *args):
                pass  # scrape noise does not belong in the job log

        self._server = ThreadingHTTPServer(
            (self._host, self._requested_port), Handler
        )
        self._server.daemon_threads = True
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="telemetry-http",
            daemon=True,
        )
        self._thread.start()
        logger.info("Telemetry endpoint on :%d (/metrics, /healthz)", self.port)

    def stop(self):
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
