"""Per-dispatch step anatomy: continuous, sum-exact time attribution.

BENCH_r04 pinned ``mnist_e2e`` at ``e2e_vs_roofline 0.695`` without any
way to say *where inside a dispatch* the missing time goes: the XLA
profiler is a 5-step one-shot window and the ``step`` histogram is one
undifferentiated number.  This module is the always-on decomposition —
every dispatch group's wall time split into named, NON-OVERLAPPING
phases measured on the dispatching thread:

- ``host_fetch``    — waiting on the reader/decode pipeline (the time
  the consumer thread blocked in ``next()``; with a healthy prefetcher
  this is residual stall, not raw decode cost);
- ``assemble``      — pad/stack to the canonical shape (host numpy);
- ``h2d_transfer``  — ``device_put`` / sharded placement of the batch.
  With ``--device_prefetch`` (trainer/device_pipeline.py) assembly and
  placement run on a staging thread while the previous group computes,
  so the CONSUMER-VISIBLE ``h2d_transfer`` becomes the wait for a
  staged group — the residual stall after overlap, whatever its
  upstream cause — and ``host_fetch``/``assemble`` go to ~0 on the
  dispatching thread.  That is the honest consumer view: the goodput
  smoke gates that this share DROPS when the prefetcher is on;
- ``device_compute``— jitted dispatch to ready: the *enqueue* segment
  (the async dispatch call returning) and the *ready-wait* segment
  (``block_until_ready`` on the dispatch's outputs) are recorded
  separately inside the phase, so async-dispatch overlap stays visible;
- ``step_bookkeeping`` — per-step hooks (telemetry samples, profiler),
  reports, checkpoint/eval milestone hooks after the group.

The sum-exact contract (the same discipline ``trace analyze`` enforces
on reform downtime): phases are disjoint intervals inside the dispatch
window, and the residual — loop glue between the timed segments — is
tracked honestly as its own ``untracked`` phase, so

    host_fetch + assemble + h2d_transfer + device_compute
      + step_bookkeeping + untracked  ==  dispatch wall time (exactly).

``scripts/goodput_smoke.py`` gates ``untracked`` < 2% of wall.

Three consumers:

1. ``/metrics`` — workers accumulate monotone per-phase totals and
   log-bucket counts here and ship them on the heartbeat (the PR-8 RPC
   counter pattern: the beat keeps flowing when reports stall); the
   master mirrors them onto ``elasticdl_step_phase_ms_total{phase=}``
   and the ``elasticdl_step_phase_seconds{phase=}`` histogram family
   (telemetry/master_hooks.py — the single registration site).
2. ``telemetry.report`` — every dispatch emits a ``step_anatomy`` event
   (when ``--telemetry_dir`` is configured), from which the report's
   ``goodput`` section computes live ``e2e_vs_roofline``, per-phase
   percentiles, model-FLOPs MFU and per-worker straggler attribution.
3. Perfetto — sampled ``step_anatomy`` spans (one per phase interval,
   ``phase=`` attribute) render the breakdown inside the existing
   ``train_step`` timeline; ``trace analyze`` aggregates them into a
   steady-state section.

Enablement: the master's ``--step_anatomy`` flag, env-forwarded to
workers as ``ELASTICDL_TPU_STEP_ANATOMY`` (never argv — worker command
lines stay byte-identical with the feature off).  Overhead contract:
with no recorder installed the runtimes take ONE branch per dispatch
path (``if anatomy is None: <uninstrumented block>``) — no clock read,
no wrapper allocation (tests poison the clock to prove it).  With the
recorder on, each dispatch additionally blocks on its outputs
(``block_until_ready``), trading a little async-dispatch pipelining for
exact attribution — the documented cost of measuring (see
docs/designs/step_anatomy.md).  ``--device_prefetch``'s retire-behind
window likewise collapses to 1 under anatomy
(``device_pipeline.stage_depth``): the ``enqueue``/``ready_wait``
split stays sum-exact because every phase interval still lives inside
its own group's dispatch window.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time

from elasticdl_tpu.telemetry.registry import STEP_LATENCY_BUCKETS

STEP_ANATOMY_ENV = "ELASTICDL_TPU_STEP_ANATOMY"
PEAK_FLOPS_ENV = "ELASTICDL_TPU_PEAK_FLOPS_PER_CHIP"

# ---- phase vocabulary (one definition site; linted like EVENT_*/SPAN_*) -----

PHASE_HOST_FETCH = "host_fetch"
PHASE_ASSEMBLE = "assemble"
PHASE_H2D_TRANSFER = "h2d_transfer"
PHASE_DEVICE_COMPUTE = "device_compute"
PHASE_STEP_BOOKKEEPING = "step_bookkeeping"
PHASE_UNTRACKED = "untracked"
# serving-plane phases (elasticdl_tpu/serving): a request's latency
# decomposes as queue_wait (submit -> its first dispatch group opens)
# followed by the shared batch phases (assemble/h2d_transfer/
# device_compute) plus d2h_transfer (outputs device -> host) — same
# sum-exact residual discipline, per REQUEST instead of per dispatch
PHASE_QUEUE_WAIT = "queue_wait"
PHASE_D2H_TRANSFER = "d2h_transfer"
# boundary-stall counter (trainer/device_pipeline.py): device-idle time
# between the last retire of task N and the first dispatch of task N+1.
# A COUNTER in the phase vocabulary, not a member of TRACKED_PHASES /
# ALL_PHASES — it spans dispatch windows, so adding it to the per-
# dispatch sum would break the sum-exactness contract
PHASE_BOUNDARY_STALL = "boundary_stall"

# the measured (timer-covered) phases, in pipeline order
TRACKED_PHASES = (
    PHASE_HOST_FETCH,
    PHASE_ASSEMBLE,
    PHASE_H2D_TRANSFER,
    PHASE_DEVICE_COMPUTE,
    PHASE_STEP_BOOKKEEPING,
)
ALL_PHASES = TRACKED_PHASES + (PHASE_UNTRACKED,)

# a serving request's phases, in pipeline order (serving/engine.py is
# the one consumer; defined HERE so the phase vocabulary keeps a single
# linted definition site)
SERVING_REQUEST_PHASES = (
    PHASE_QUEUE_WAIT,
    PHASE_ASSEMBLE,
    PHASE_H2D_TRANSFER,
    PHASE_DEVICE_COMPUTE,
    PHASE_D2H_TRANSFER,
)

# device_compute sub-segments (recorded as extra event fields, not
# phases: they SUM to device_compute, they don't add to it)
SUB_ENQUEUE = "enqueue"
SUB_READY_WAIT = "ready_wait"


def timed_device_dispatch(recorder, dispatch):
    """THE instrumented device dispatch: run ``dispatch()`` with its
    wall attributed to ``device_compute`` as the ``enqueue`` sub-segment
    (the async dispatch call returning) and then block on its outputs
    as ``ready_wait``.  One definition site for the sub-segment split —
    every runtime's anatomy branch (serial flush, device-pipeline
    dispatch, task-stream staged/anatomized steps) calls this, so the
    sum-exactness contract (enqueue + ready_wait == device_compute)
    cannot drift between call sites.  Returns the dispatch outputs."""
    import jax

    with recorder.phase(PHASE_DEVICE_COMPUTE, sub=SUB_ENQUEUE):
        out = dispatch()
    with recorder.phase(PHASE_DEVICE_COMPUTE, sub=SUB_READY_WAIT):
        jax.block_until_ready(out)
    return out

# ---- model-FLOPs table (goodput MFU) ----------------------------------------
#
# Per-record TRAINING FLOPs (forward + backward ~= 3x forward) for zoo
# models whose cost is a closed-form function of their fixed
# architecture.  Keyed by the model module name (the first dotted
# component of --model_def).  Models with data-dependent cost
# (transformer seq length, custom params) return None — the report then
# says WHY mfu is absent instead of inventing a number.
MODEL_FLOPS_PER_RECORD = {
    # Conv(32,3x3)@26x26 + Conv(64,3x3)@24x24 + Dense(9216->10), x3 for
    # fwd+bwd: ~2.2e7 fwd MACs -> ~6.6e7 train FLOPs
    "mnist_functional_api": 6.6e7,
    "mnist_subclass": 6.6e7,
    # ResNet-50 @224: ~4.1 GFLOPs forward -> ~1.23e10 train FLOPs
    "imagenet_resnet50": 1.23e10,
}

# peak dense FLOP/s per chip by device kind (bf16); used only when the
# operator did not pin ELASTICDL_TPU_PEAK_FLOPS_PER_CHIP
_PEAK_FLOPS_BY_DEVICE_KIND = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v6e": 918e12,
}


def model_flops_per_record(model_def: str) -> float | None:
    """Known per-record training FLOPs for ``--model_def``, or None."""
    module = (model_def or "").split(".", 1)[0]
    return MODEL_FLOPS_PER_RECORD.get(module)


def peak_flops_per_chip() -> float | None:
    """Peak FLOP/s of one local device: the env pin wins, else the
    device-kind table, else None (CPU backends have no honest peak)."""
    raw = os.environ.get(PEAK_FLOPS_ENV, "")
    if raw:
        try:
            return float(raw)
        except ValueError:
            pass
    try:
        import jax

        kind = jax.devices()[0].device_kind
    except Exception:  # noqa: BLE001 — no backend is a valid state
        return None
    return _PEAK_FLOPS_BY_DEVICE_KIND.get(kind)


def _bucket_index(secs: float) -> int:
    for i, bound in enumerate(STEP_LATENCY_BUCKETS):
        if secs <= bound:
            return i
    return len(STEP_LATENCY_BUCKETS)  # +Inf slot


class AnatomyRecorder:
    """Per-process phase timer.  One dispatch group at a time: phase
    intervals accumulate on the dispatching thread, :meth:`commit`
    closes the window, derives ``untracked`` as the exact residual, and
    fans out to the event log / cumulative heartbeat totals / sampled
    spans.  The cumulative totals are read concurrently by the
    heartbeat thread, so they sit behind a lock; the open dispatch
    accumulator is dispatch-thread-only.

    Identity (worker/process/generation) is deliberately NOT stored
    here: events are stamped by the installed
    :class:`~elasticdl_tpu.telemetry.worker_hooks.StepRecorder` and
    spans by the installed tracer — one identity source per process,
    nothing to go stale across a reform."""

    def __init__(self, flops_per_record: float | None = None):
        self._flops_per_record = flops_per_record
        self._peak_flops = peak_flops_per_chip()
        try:
            import jax

            self._n_chips = max(1, len(jax.devices()))
        except Exception:  # noqa: BLE001
            self._n_chips = 1
        # open dispatch: [(phase, start, end)] + sub-segment sums
        self._intervals: list[tuple[str, float, float]] = []
        self._subs: dict[str, float] = {}
        # cumulative (heartbeat-shipped) totals: phase -> [secs, count,
        # per-bucket counts over STEP_LATENCY_BUCKETS + Inf]
        self._lock = threading.Lock()
        self._totals: dict[str, list] = {}  # guarded-by: _lock
        self.dispatches = 0

    # ---- per-dispatch measurement (dispatch thread only) -------------------

    def wrap_fetches(self, iterable):
        """Wrap a batch stream so every ``next()`` — the time this
        thread waited on the host pipeline — lands in ``host_fetch`` of
        the dispatch group being accumulated."""
        it = iter(iterable)
        while True:
            t0 = time.monotonic()
            try:
                item = next(it)
            except StopIteration:
                return
            self._intervals.append((PHASE_HOST_FETCH, t0, time.monotonic()))
            yield item

    @contextlib.contextmanager
    def phase(self, name: str, sub: str | None = None):
        """Attribute the block's wall time to ``name``; ``sub`` records
        the same duration under a device_compute sub-segment label."""
        t0 = time.monotonic()
        try:
            yield
        finally:
            t1 = time.monotonic()
            self._intervals.append((name, t0, t1))
            if sub is not None:
                self._subs[sub] = self._subs.get(sub, 0.0) + (t1 - t0)

    def wrapped_hook(self, hook):
        """``pre_batch``-style hooks (telemetry samples, profiler) run
        inside the dispatch window but outside any device phase — time
        them as ``step_bookkeeping`` so they can't leak into
        ``untracked``.  Returns None for a None hook."""
        if hook is None:
            return None

        def timed(*args, **kwargs):
            with self.phase(PHASE_STEP_BOOKKEEPING):
                return hook(*args, **kwargs)

        return timed

    def commit(self, steps: int = 1, records: int = 0, step=None):
        """Close the open dispatch window: wall time is first interval
        start -> now, ``untracked`` is wall minus the tracked phases
        (exact by construction), and the result fans out to the three
        consumers.  A window with no intervals is a no-op."""
        intervals, self._intervals = self._intervals, []
        subs, self._subs = self._subs, {}
        if not intervals:
            return None
        now = time.monotonic()
        window_start = min(t0 for _n, t0, _t1 in intervals)
        wall = now - window_start
        phases = {}
        for name, t0, t1 in intervals:
            phases[name] = phases.get(name, 0.0) + (t1 - t0)
        tracked = sum(phases.values())
        phases[PHASE_UNTRACKED] = max(0.0, wall - tracked)
        self.dispatches += 1
        with self._lock:
            for name, secs in phases.items():
                slot = self._totals.get(name)
                if slot is None:
                    slot = self._totals[name] = [
                        0.0,
                        0,
                        [0] * (len(STEP_LATENCY_BUCKETS) + 1),
                    ]
                slot[0] += secs
                slot[1] += 1
                slot[2][_bucket_index(secs)] += 1
        self._emit_event(phases, subs, wall, steps, records, step)
        self._emit_spans(intervals, step)
        return phases

    def _emit_event(self, phases, subs, wall, steps, records, step):
        from elasticdl_tpu.telemetry import worker_hooks
        from elasticdl_tpu.telemetry.events import EVENT_STEP_ANATOMY

        fields = {
            "steps": int(steps),
            "records": int(records),
            "wall_ms": wall * 1000.0,
        }
        if step is not None:
            fields["step"] = int(step)
        for name, secs in phases.items():
            fields[f"{name}_ms"] = secs * 1000.0
        for name, secs in subs.items():
            fields[f"{name}_ms"] = secs * 1000.0
        if self._flops_per_record is not None:
            fields["flops_per_record"] = self._flops_per_record
        if self._peak_flops is not None:
            fields["peak_flops_per_chip"] = self._peak_flops
        fields["n_chips"] = self._n_chips
        worker_hooks.emit_event(EVENT_STEP_ANATOMY, **fields)

    def _emit_spans(self, intervals, step):
        from elasticdl_tpu.telemetry import tracing

        tracer = tracing.get_tracer()
        if tracer is None or not tracer.should_sample(
            tracing.SPAN_STEP_ANATOMY
        ):
            return
        for name, t0, t1 in intervals:
            tracer.record_span(
                tracing.SPAN_STEP_ANATOMY,
                t0,
                t1,
                phase=name,
                step=int(step) if step is not None else None,
            )

    # ---- heartbeat shipping (any thread) -----------------------------------

    def heartbeat_snapshot(self) -> dict:
        """Monotone per-phase totals for ``HeartbeatRequest.phases``:
        ``{phase: {"ms": float, "count": int, "buckets": {str(secs):
        int}}}`` (bucket keys are strings — the msgpack transport
        rejects non-str map keys; ``"inf"`` is the overflow slot)."""
        with self._lock:
            out = {}
            for name, (secs, count, buckets) in self._totals.items():
                bucket_map = {
                    str(bound): n
                    for bound, n in zip(STEP_LATENCY_BUCKETS, buckets)
                    if n
                }
                if buckets[-1]:
                    bucket_map["inf"] = buckets[-1]
                out[name] = {
                    "ms": secs * 1000.0,
                    "count": count,
                    "buckets": bucket_map,
                }
            return out


# ---- module-level install + zero-cost-when-disabled accessors ---------------

_active: AnatomyRecorder | None = None


def install(model_def: str = "") -> AnatomyRecorder:
    global _active
    _active = AnatomyRecorder(
        flops_per_record=model_flops_per_record(model_def)
    )
    return _active


def install_if_enabled(flag, model_def: str = "") -> AnatomyRecorder | None:
    """Install when the master's ``--step_anatomy`` flag OR the
    env-forwarded ``ELASTICDL_TPU_STEP_ANATOMY`` asks for it; clears
    any stale recorder otherwise — a runtime constructed WITHOUT the
    flag must not inherit a previous in-process install (bench runs
    several configs per process)."""
    if not flag and not os.environ.get(STEP_ANATOMY_ENV, ""):
        uninstall()
        return None
    return install(model_def=model_def)


def install_from_env(model_def: str = "") -> AnatomyRecorder | None:
    """Worker-subprocess entry: install only when the master exported
    the enabling env (the chaos-plan/telemetry-dir pattern)."""
    return install_if_enabled(None, model_def=model_def)


def uninstall():
    global _active
    _active = None


def get_recorder() -> AnatomyRecorder | None:  # elastic-lint: hot-path
    """THE runtime seam: None (one global load, no clock read) unless
    anatomy was installed — the runtimes branch ONCE on this per
    dispatch path."""
    return _active


def heartbeat_snapshot() -> dict:  # elastic-lint: hot-path
    """Phase totals for the heartbeat; {} when disabled (old payloads
    decode the same, so the field is wire-compatible)."""
    recorder = _active
    if recorder is None:
        return {}
    return recorder.heartbeat_snapshot()
