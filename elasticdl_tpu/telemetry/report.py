"""Run-report CLI: join the telemetry event log with chaos artifacts.

::

    python -m elasticdl_tpu.telemetry.report <run_dir> [--json] [--output f]

``<run_dir>`` is any directory tree containing telemetry ``events.jsonl``
files (e.g. a chaos runner ``--workdir``, which holds one run under
``chaos/telemetry/`` and one under ``baseline/telemetry/``).  For each
run the report computes, per world generation:

- step count and p50/p95/p99 step time (from worker ``step`` samples);
- reform downtime — last ``step`` of generation N to first ``step`` of
  generation N+1 — annotated with the chaos fault that caused it (from
  ``chaos_events.jsonl`` / mirrored ``fault_injected`` events) and the
  tasks recovered inside the gap;
- per-worker records/sec (lockstep note: every process steps through the
  full global batch, so per-worker rates describe step cadence, not
  disjoint data slices);
- worker wall-clock bucket totals (``time_<bucket>_ms``) summed from
  ``task_done`` events.

``chaos_result.json`` (written by ``python -m elasticdl_tpu.chaos.runner``)
is surfaced verbatim so CI reads verdicts and numbers from one place.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
from collections import defaultdict

from elasticdl_tpu.telemetry.events import EVENTS_FILENAME, read_events

# a fault can fire slightly before the victim's last recorded step lands
# in the log (the event is written at step START); allow this much skew
# when attributing a downtime gap to a fault
_FAULT_ATTRIBUTION_SLACK_SECS = 5.0


def percentile(samples: list[float], q: float) -> float:
    """Nearest-rank percentile (q in [0, 100]) — exact over raw samples,
    no interpolation surprises in tiny runs."""
    if not samples:
        raise ValueError("no samples")
    ordered = sorted(samples)
    rank = max(1, math.ceil(q / 100.0 * len(ordered)))
    return ordered[min(rank, len(ordered)) - 1]


def _find_files(run_dir: str, filename: str) -> list[str]:
    found = []
    for root, _dirs, files in os.walk(run_dir):
        if filename in files:
            found.append(os.path.join(root, filename))
    return sorted(found)


def _load_fault_events(run_dir: str) -> list[dict]:
    """Fault firings from every chaos event log under the run dir plus
    any mirrored ``fault_injected`` telemetry events (deduplicated by
    fault id + firing time)."""
    faults = []
    for path in _find_files(run_dir, "chaos_events.jsonl"):
        for event in read_events(path):
            if "observation" not in event:
                faults.append(event)
    seen = {(f.get("fault_id"), round(f.get("monotonic", 0), 3)) for f in faults}
    for path in _find_files(run_dir, EVENTS_FILENAME):
        for event in read_events(path):
            if event.get("event") != "fault_injected":
                continue
            key = (event.get("fault_id"), round(event.get("monotonic", 0), 3))
            if key not in seen:
                seen.add(key)
                faults.append(event)
    return sorted(faults, key=lambda f: f.get("monotonic", 0.0))


def _generation_stats(steps: list[dict]) -> dict:
    samples = [
        e["duration_secs"] for e in steps if e.get("duration_secs") is not None
    ]
    workers = sorted({e.get("worker_id", 0) for e in steps})
    stats = {
        "steps": len(steps),
        "workers": workers,
        "records": sum(e.get("records", 0) for e in steps),
        "first_step_at": steps[0]["monotonic"],
        "last_step_at": steps[-1]["monotonic"],
    }
    if samples:
        stats.update(
            {
                "step_time_p50_ms": percentile(samples, 50) * 1000.0,
                "step_time_p95_ms": percentile(samples, 95) * 1000.0,
                "step_time_p99_ms": percentile(samples, 99) * 1000.0,
                "step_time_mean_ms": sum(samples) / len(samples) * 1000.0,
            }
        )
    return stats


def _worker_throughput(steps: list[dict]) -> dict[int, float]:
    """records/sec per worker, summed over the spans the worker was
    actually stepping (gaps between generations excluded because each
    generation's span is measured independently)."""
    spans: dict[int, float] = defaultdict(float)
    records: dict[int, float] = defaultdict(float)
    by_worker_gen: dict[tuple, list[dict]] = defaultdict(list)
    for event in steps:
        key = (event.get("worker_id", 0), event.get("generation", 0))
        by_worker_gen[key].append(event)
    for (worker_id, _gen), events in by_worker_gen.items():
        span = events[-1]["monotonic"] - events[0]["monotonic"]
        if span > 0:
            spans[worker_id] += span
            records[worker_id] += sum(e.get("records", 0) for e in events)
    return {
        w: records[w] / spans[w] for w in sorted(spans) if spans[w] > 0
    }


def _attribute_fault(faults: list[dict], gap_start: float, gap_end: float):
    candidates = [
        f
        for f in faults
        if gap_start - _FAULT_ATTRIBUTION_SLACK_SECS
        <= f.get("monotonic", 0.0)
        <= gap_end
    ]
    return candidates[-1] if candidates else None


def analyze_events(events: list[dict], faults: list[dict]) -> dict:
    """Summarize one run's telemetry event stream (pure function — the
    unit tests drive it with canned logs)."""
    steps = sorted(
        (e for e in events if e.get("event") == "step"),
        key=lambda e: e.get("monotonic", 0.0),
    )
    by_gen: dict[int, list[dict]] = defaultdict(list)
    for event in steps:
        by_gen[event.get("generation", 0)].append(event)

    generations = {
        gen: _generation_stats(by_gen[gen]) for gen in sorted(by_gen)
    }

    recovered = [e for e in events if e.get("event") == "task_recovered"]
    reform_events = [
        e
        for e in events
        if e.get("event") in ("reform_start", "reform_complete", "reform_latency")
    ]

    downtimes = []
    ordered_gens = sorted(by_gen)
    for prev, nxt in zip(ordered_gens, ordered_gens[1:]):
        gap_start = generations[prev]["last_step_at"]
        gap_end = generations[nxt]["first_step_at"]
        downtime = {
            "from_generation": prev,
            "to_generation": nxt,
            "downtime_secs": max(0.0, gap_end - gap_start),
            "tasks_recovered": sum(
                1
                for e in recovered
                if gap_start <= e.get("monotonic", 0.0) <= gap_end
            ),
        }
        fault = _attribute_fault(faults, gap_start, gap_end)
        if fault is not None:
            downtime["cause"] = {
                "fault_id": fault.get("fault_id"),
                "kind": fault.get("kind"),
                "process_id": fault.get("process_id"),
                "at_step": fault.get("step"),
            }
        downtimes.append(downtime)

    # task_done carries per-task DELTAS (lockstep exec counters);
    # worker_timing carries a runtime's cumulative TOTALS (local
    # executor) — sum the former, take max-per-worker of the latter
    time_buckets: dict[str, float] = defaultdict(float)
    cumulative: dict[tuple, float] = {}
    for event in events:
        if event.get("event") == "task_done":
            for key, value in event.items():
                if key.startswith("time_") and key.endswith("_ms"):
                    time_buckets[key[len("time_") : -len("_ms")]] += value
        elif event.get("event") == "worker_timing":
            for key, value in event.items():
                if key.startswith("time_") and key.endswith("_ms"):
                    wk = (event.get("worker_id", 0), key)
                    cumulative[wk] = max(cumulative.get(wk, 0.0), value)
    for (_worker, key), value in cumulative.items():
        time_buckets[key[len("time_") : -len("_ms")]] += value

    out = {
        "generations": generations,
        "reform_downtime": downtimes,
        "records_per_sec_by_worker": _worker_throughput(steps),
        "tasks_recovered_total": len(recovered),
        "reform_event_count": len(reform_events),
        "worker_time_ms": dict(time_buckets),
        "events_total": len(events),
    }
    if not events:
        # explicit "no data" marker: a run dir that exists but has not
        # produced events yet (job starting, rotated-away shards) must
        # report cleanly, never traceback
        out["no_data"] = "event log present but empty — no samples yet"
    goodput = goodput_section(events)
    if goodput is not None:
        out["goodput"] = goodput
    replication = replication_section(events)
    if replication is not None:
        out["replication"] = replication
    multislice = multislice_section(events)
    if multislice is not None:
        out["multislice"] = multislice
    master_ha = master_ha_section(events)
    if master_ha is not None:
        out["master_ha"] = master_ha
    serving = serving_section(events)
    if serving is not None:
        out["serving"] = serving
    memory = memory_section(events)
    if memory is not None:
        out["memory"] = memory
    slo = slo_section(events)
    if slo is not None:
        out["slo"] = slo
    streaming = streaming_section(events)
    if streaming is not None:
        out["streaming"] = streaming
    return out


# step-anatomy goodput: the phase taxonomy the events carry (one
# definition site: telemetry/anatomy.py); device-path = everything the
# dispatch spends on the device side of the pipeline
_GOODPUT_DEVICE_PATH = ("assemble", "h2d_transfer", "device_compute")
_GOODPUT_STRAGGLER_FACTOR = 1.5


def _phase_samples(anat_events: list[dict]) -> dict[str, list[float]]:
    from elasticdl_tpu.telemetry.anatomy import ALL_PHASES

    samples: dict[str, list[float]] = {}
    for event in anat_events:
        for phase in ALL_PHASES:
            value = event.get(f"{phase}_ms")
            if value is not None:
                samples.setdefault(phase, []).append(float(value))
    return samples


def _goodput_generation(anat_events: list[dict]) -> dict:
    """Goodput stats for ONE generation's ``step_anatomy`` events."""
    samples = _phase_samples(anat_events)
    wall_ms = sum(float(e.get("wall_ms", 0.0)) for e in anat_events)
    records = sum(int(e.get("records", 0)) for e in anat_events)
    steps = sum(int(e.get("steps", 0)) for e in anat_events)
    phases = {}
    for phase, values in samples.items():
        total = sum(values)
        phases[phase] = {
            "total_ms": round(total, 3),
            "share": round(total / wall_ms, 4) if wall_ms else None,
            "p50_ms": round(percentile(values, 50), 3),
            "p95_ms": round(percentile(values, 95), 3),
            "p99_ms": round(percentile(values, 99), 3),
        }
    # the sum-exact contract, verified not assumed: the per-event
    # residual between wall and the phase sum (incl. untracked) is
    # float noise only
    residual = max(
        (
            abs(
                float(e.get("wall_ms", 0.0))
                - sum(
                    float(e.get(f"{p}_ms", 0.0))
                    for p in samples
                )
            )
            for e in anat_events
        ),
        default=0.0,
    )
    host_ms = sum(samples.get("host_fetch", []))
    device_path_ms = sum(
        sum(samples.get(p, [])) for p in _GOODPUT_DEVICE_PATH
    )
    untracked_ms = sum(samples.get("untracked", []))
    out = {
        "dispatches": len(anat_events),
        "steps": steps,
        "records": records,
        "wall_ms_total": round(wall_ms, 3),
        "phases": phases,
        "max_sum_residual_ms": round(residual, 6),
        "untracked_share": round(untracked_ms / wall_ms, 4)
        if wall_ms
        else None,
        # live e2e-vs-roofline: the binding path's busy time (host
        # fetch wait vs the device path) over end-to-end wall — 1.0
        # means zero overlap slack, the same meaning as bench.py's
        # budget ratio but MEASURED per dispatch instead of inferred
        # from separate ceiling runs
        "e2e_vs_roofline": round(
            max(host_ms, device_path_ms) / wall_ms, 4
        )
        if wall_ms
        else None,
        "binding": (
            "host_fetch" if host_ms > device_path_ms else "device_path"
        ),
    }
    # async-dispatch overlap visibility: how much of device_compute was
    # the enqueue call vs waiting for results
    enqueue_ms = sum(float(e.get("enqueue_ms", 0.0)) for e in anat_events)
    ready_ms = sum(float(e.get("ready_wait_ms", 0.0)) for e in anat_events)
    if enqueue_ms or ready_ms:
        out["device_compute_split_ms"] = {
            "enqueue": round(enqueue_ms, 3),
            "ready_wait": round(ready_ms, 3),
        }
    # model-FLOPs MFU, when the model cost and the device peak are known
    flops = next(
        (
            e["flops_per_record"]
            for e in anat_events
            if e.get("flops_per_record")
        ),
        None,
    )
    peak = next(
        (
            e["peak_flops_per_chip"]
            for e in anat_events
            if e.get("peak_flops_per_chip")
        ),
        None,
    )
    n_chips = next(
        (e["n_chips"] for e in anat_events if e.get("n_chips")), 1
    )
    device_secs = sum(samples.get("device_compute", [])) / 1000.0
    if flops is None:
        out["mfu"] = None
        out["mfu_reason"] = "model FLOPs unknown (not in the zoo cost table)"
    elif peak is None:
        out["mfu"] = None
        out["mfu_reason"] = (
            "device peak FLOPs unknown "
            "(set ELASTICDL_TPU_PEAK_FLOPS_PER_CHIP)"
        )
    elif device_secs <= 0:
        out["mfu"] = None
        out["mfu_reason"] = "no device_compute time measured"
    else:
        out["mfu"] = round(
            flops * records / (device_secs * peak * n_chips), 4
        )
    # per-host straggler attribution: whose device_compute vs
    # host_fetch lags the fleet — the "which worker, which phase"
    # answer the barrier-wait split alone can't give
    by_worker: dict = defaultdict(list)
    for event in anat_events:
        by_worker[event.get("worker_id", 0)].append(event)
    if len(by_worker) > 1:
        # a straggler is a worker whose dispatch WALL lags the fleet
        # (each phase alone can be bimodal across a healthy fleet);
        # the lagging phase then names WHY — compute-bound vs
        # input-bound — which is the actionable half of the answer
        gen_wall = percentile(
            [float(e.get("wall_ms", 0.0)) for e in anat_events], 50
        )
        gen_compute = percentile(
            samples.get("device_compute", [0.0]), 50
        )
        gen_fetch = percentile(samples.get("host_fetch", [0.0]), 50)
        workers = {}
        for worker_id, worker_events in sorted(by_worker.items()):
            worker_samples = _phase_samples(worker_events)
            wall_p50 = percentile(
                [float(e.get("wall_ms", 0.0)) for e in worker_events], 50
            )
            compute_p50 = percentile(
                worker_samples.get("device_compute", [0.0]), 50
            )
            fetch_p50 = percentile(
                worker_samples.get("host_fetch", [0.0]), 50
            )
            entry = {
                "wall_p50_ms": round(wall_p50, 3),
                "device_compute_p50_ms": round(compute_p50, 3),
                "host_fetch_p50_ms": round(fetch_p50, 3),
                "straggler": bool(
                    gen_wall
                    and wall_p50 > _GOODPUT_STRAGGLER_FACTOR * gen_wall
                ),
            }
            if entry["straggler"]:
                compute_lag = (
                    compute_p50 / gen_compute if gen_compute else 0.0
                )
                fetch_lag = fetch_p50 / gen_fetch if gen_fetch else 0.0
                entry["lagging_phase"] = (
                    "device_compute"
                    if compute_lag >= fetch_lag
                    else "host_fetch"
                )
            workers[worker_id] = entry
        out["workers"] = workers
    return out


def goodput_section(events: list[dict]) -> dict | None:
    """Live goodput ledger from per-dispatch ``step_anatomy`` events
    (telemetry/anatomy.py): per-generation phase percentiles, the
    sum-exact residual check, a MEASURED ``e2e_vs_roofline``, MFU for
    zoo models with known costs, and per-host straggler attribution.
    None (key absent) when the run never recorded anatomy, so
    anatomy-less reports are unchanged."""
    anat = [e for e in events if e.get("event") == "step_anatomy"]
    if not anat:
        return None
    by_gen: dict[int, list[dict]] = defaultdict(list)
    for event in anat:
        by_gen[event.get("generation", 0)].append(event)
    generations = {
        gen: _goodput_generation(by_gen[gen]) for gen in sorted(by_gen)
    }
    overall = _goodput_generation(anat)
    return {"generations": generations, "overall": overall}


def multislice_section(events: list[dict]) -> dict | None:
    """Slice-topology timeline (slice-granular elasticity): every
    whole-slice loss, hybrid-mesh resize and autoscale decision, plus
    per-slice replica-push counts (the cross-slice ring's observable).
    None (key absent) when the run never touched slice machinery, so
    single-slice reports are unchanged."""
    losses = []
    resizes = []
    decisions = []
    pushes_by_slice: dict[str, int] = defaultdict(int)
    for event in events:
        kind = event.get("event")
        if kind == "slice_loss":
            losses.append(
                {
                    "generation": event.get("generation"),
                    "lost_slices": event.get("lost_slices"),
                    "dead_workers": event.get("dead_workers"),
                    "old_slices": event.get("old_slices"),
                    "new_slices": event.get("new_slices"),
                    "parked": event.get("parked"),
                }
            )
        elif kind == "mesh_resize":
            resizes.append(
                {
                    "generation": event.get("generation"),
                    "old_world_size": event.get("old_world_size"),
                    "new_world_size": event.get("new_world_size"),
                    "old_slices": event.get("old_slices"),
                    "new_slices": event.get("new_slices"),
                    "dcn": event.get("dcn"),
                }
            )
        elif kind == "autoscale_decision":
            decisions.append(
                {
                    "generation": event.get("generation"),
                    "action": event.get("action"),
                    "from_slices": event.get("from_slices"),
                    "to_slices": event.get("to_slices"),
                    "reason": event.get("reason"),
                }
            )
        elif (
            kind == "replica_push"
            and int(event.get("num_slices", 1) or 1) > 1
        ):
            pushes_by_slice[str(event.get("source_slice"))] += 1
    if not (losses or resizes or decisions or pushes_by_slice):
        return None
    return {
        "slice_losses": losses,
        "mesh_resizes": resizes,
        "autoscale_decisions": decisions,
        "replica_pushes_by_source_slice": dict(pushes_by_slice),
    }


def master_ha_section(events: list[dict]) -> dict | None:
    """Master-downtime stats (master high availability): one entry per
    ``master_restart`` event — the measured step gap the outage caused
    (last worker ``step`` before the restore began to the first after
    the master served again, mirroring the reform-downtime definition),
    the journal-replay cost, and the lease-reconciliation outcome of
    every ``worker_rehome``.  None (key absent) when the run never
    restarted a master, so HA-less reports are unchanged."""
    restarts = sorted(
        (
            e
            for e in events
            if e.get("event") == "master_restart"
            and e.get("monotonic") is not None
        ),
        key=lambda e: e["monotonic"],
    )
    if not restarts:
        return None
    steps = [
        e["monotonic"]
        for e in events
        if e.get("event") == "step" and e.get("monotonic") is not None
    ]
    replays = sorted(
        (e for e in events if e.get("event") == "journal_replay"),
        key=lambda e: e.get("monotonic", 0.0),
    )
    rehomes = sorted(
        (e for e in events if e.get("event") == "worker_rehome"),
        key=lambda e: e.get("monotonic", 0.0),
    )
    entries = []
    bounds = [r["monotonic"] for r in restarts[1:]] + [float("inf")]
    for restart, until in zip(restarts, bounds):
        at = restart["monotonic"]
        last_before = max((t for t in steps if t <= at), default=None)
        first_after = min((t for t in steps if t >= at), default=None)
        replay = next(
            (e for e in replays if at <= e.get("monotonic", 0.0) < until),
            None,
        )
        mine = [
            e for e in rehomes if at <= e.get("monotonic", 0.0) < until
        ]
        entries.append(
            {
                "generation": restart.get("generation"),
                "downtime_secs": round(first_after - last_before, 6)
                if last_before is not None and first_after is not None
                else None,
                "journal_replay_secs": replay.get("duration_secs")
                if replay
                else None,
                "pending_tasks_restored": replay.get("pending")
                if replay
                else None,
                "active_leases_restored": replay.get("active")
                if replay
                else None,
                "workers_rehomed": sorted(
                    e.get("worker_id") for e in mine
                ),
                "leases_kept": sum(e.get("kept", 0) for e in mine),
                "leases_requeued": sum(e.get("requeued", 0) for e in mine),
            }
        )
    measured = [
        e["downtime_secs"]
        for e in entries
        if e["downtime_secs"] is not None
    ]
    return {
        "restarts": entries,
        "total_downtime_secs": round(sum(measured), 6) if measured else None,
    }


def replication_section(events: list[dict]) -> dict | None:
    """Replica-coverage stats (peer state replication): pushes and hosts
    covered per generation, the freshest shard versions, harvest
    outcomes, and restores served from peer RAM.  None (key absent) when
    the run never replicated, so replication-less reports are unchanged."""
    pushes: dict[int, int] = defaultdict(int)
    hosts: dict[int, set] = defaultdict(set)
    versions: dict[int, int] = {}
    restores = []
    harvests = []
    for event in events:
        kind = event.get("event")
        gen = event.get("generation", 0)
        if kind == "replica_push":
            pushes[gen] += 1
            if event.get("source") is not None:
                hosts[gen].add(event["source"])
            versions[gen] = max(
                versions.get(gen, -1), event.get("step", -1)
            )
        elif kind == "replica_restore":
            restores.append(
                {"generation": gen, "step": event.get("step")}
            )
        elif kind == "replica_harvest":
            harvests.append(
                {
                    "generation": gen,
                    "complete": event.get("complete"),
                    "version": event.get("version"),
                }
            )
    if not (pushes or restores or harvests):
        return None
    return {
        "pushes_by_generation": dict(pushes),
        "hosts_covered_by_generation": {
            g: sorted(h) for g, h in hosts.items()
        },
        "shard_versions_by_generation": versions,
        "restores": restores,
        "harvests": harvests,
    }


def serving_section(events: list[dict]) -> dict | None:
    """Serving-plane aggregate from ``serving_request`` events — the
    way goodput aggregates ``step_anatomy``: per-phase p50/p95/p99 over
    completed requests, shed/error counts (the batcher's overload
    rejections ride the same event stream with ``error`` set), and the
    ``model_swap`` timeline.  None (key absent) when the run never
    served, so training-only reports are unchanged."""
    requests = [e for e in events if e.get("event") == "serving_request"]
    swaps = sorted(
        (e for e in events if e.get("event") == "model_swap"),
        key=lambda e: e.get("monotonic", 0.0),
    )
    if not requests and not swaps:
        return None
    ok = [e for e in requests if not e.get("error")]
    failed = [e for e in requests if e.get("error")]
    sheds = sum(1 for e in failed if e.get("shed"))
    errors_by_kind: dict[str, int] = defaultdict(int)
    for event in failed:
        errors_by_kind[str(event.get("error"))] += 1
    from elasticdl_tpu.telemetry.anatomy import SERVING_REQUEST_PHASES

    phases = {}
    for phase in SERVING_REQUEST_PHASES + ("untracked",):
        values = [
            float(e[f"{phase}_ms"]) for e in ok if f"{phase}_ms" in e
        ]
        if values:
            phases[phase] = {
                "total_ms": round(sum(values), 3),
                "p50_ms": round(percentile(values, 50), 3),
                "p95_ms": round(percentile(values, 95), 3),
                "p99_ms": round(percentile(values, 99), 3),
            }
    totals = [float(e["total_ms"]) for e in ok if "total_ms" in e]
    out = {
        "requests": len(ok),
        "rows": sum(int(e.get("rows", 0)) for e in ok),
        "dispatches": sum(int(e.get("dispatches", 0)) for e in ok),
        "sheds": sheds,
        "errors": len(failed) - sheds,
        "errors_by_kind": dict(errors_by_kind),
        "phases": phases,
        "swaps": [
            {
                "old_version": s.get("old_version"),
                "model_version": s.get("model_version"),
                "replica_id": s.get("replica_id"),
                "source": s.get("source"),
                "swap_ms": s.get("swap_ms"),
                "monotonic": s.get("monotonic"),
            }
            for s in swaps
        ],
    }
    if totals:
        out["latency_p50_ms"] = round(percentile(totals, 50), 3)
        out["latency_p95_ms"] = round(percentile(totals, 95), 3)
        out["latency_p99_ms"] = round(percentile(totals, 99), 3)
    return out


def streaming_section(events: list[dict]) -> dict | None:
    """Streaming-mode aggregate: watermark progression from
    ``stream_watermark``/``stream_lag`` ticks (final watermarks, lag
    percentiles, max lag — the bounded-lag evidence) and the freshness
    ledger from ``live_push`` events — one row per live train->serve
    push with the trained-watermark-at-swap vs source-watermark pair
    (``staleness`` = how many records behind the source the SERVED
    model was the moment it went live).  None (key absent) when the
    run never streamed, so epoch-mode reports are unchanged."""
    ticks = sorted(
        (e for e in events if e.get("event") == "stream_watermark"),
        key=lambda e: e.get("monotonic", 0.0),
    )
    lags = [
        float(e["lag_records"])
        for e in events
        if e.get("event") == "stream_lag" and "lag_records" in e
    ]
    pushes = sorted(
        (e for e in events if e.get("event") == "live_push"),
        key=lambda e: e.get("monotonic", 0.0),
    )
    if not ticks and not lags and not pushes:
        return None
    out: dict = {"watermark_ticks": len(ticks)}
    if ticks:
        last = ticks[-1]
        out["source_watermark"] = int(last.get("source_watermark", 0))
        out["trained_watermark"] = int(last.get("trained_watermark", 0))
        out["closed"] = bool(last.get("closed", False))
    if lags:
        out["lag_records"] = {
            "max": int(max(lags)),
            "p50": round(percentile(lags, 50), 1),
            "p95": round(percentile(lags, 95), 1),
            "last": int(lags[-1]),
        }
    if pushes:
        accepted = [e for e in pushes if e.get("accepted")]
        staleness = [
            int(e.get("staleness", 0)) for e in accepted
        ]
        out["freshness"] = {
            "pushes": len(pushes),
            "accepted": len(accepted),
            "refused": len(pushes) - len(accepted),
            "max_staleness_records": max(staleness) if staleness else None,
            "ledger": [
                {
                    "model_version": e.get("model_version"),
                    "trained_watermark": e.get("trained_watermark"),
                    "source_watermark": e.get("source_watermark"),
                    "staleness": e.get("staleness"),
                    "accepted": bool(e.get("accepted")),
                    "swap_ms": e.get("swap_ms"),
                    "monotonic": e.get("monotonic"),
                }
                for e in pushes
            ],
        }
    return out


def memory_section(events: list[dict]) -> dict | None:
    """Component-level memory ledger aggregate from ``memory_sample``
    events (telemetry/memory.py): per-component last/current and peak
    bytes with shares of the tracked total, the host-RSS residual as an
    explicit ``unaccounted`` line gated against its absolute-bytes
    budget (allocators lie, so the residual is surfaced, never forced
    to zero), and the ``memory_pressure`` crossing timeline.  None
    (key absent) when the run never sampled, so ledger-less reports
    are unchanged.

    Samples are grouped by EMITTING PROCESS (``worker_id`` /
    ``process_id``, riding every worker-hooks emit; the master's own
    ledger forms its own group) and only ordered WITHIN a group —
    ``monotonic`` restarts per process, so a cross-process sort would
    interleave incomparable clocks and make "last sample" one
    arbitrary worker's reading.  Per-process lasts and peaks then SUM
    across groups: currents are the fleet's newest per-process bytes
    (the wire's last-writer-wins, re-derived from the log), peaks the
    sum of per-process watermarks, RSS and the unaccounted residual
    the sums of per-process values."""
    by_process: dict[tuple, list[dict]] = {}
    for event in events:
        if event.get("event") == "memory_sample":
            key = (event.get("worker_id"), event.get("process_id"))
            by_process.setdefault(key, []).append(event)
    pressures = [
        e for e in events if e.get("event") == "memory_pressure"
    ]
    if not by_process and not pressures:
        return None
    components: dict[str, dict] = {}
    n_samples = 0
    last_rss = None
    peak_rss = 0
    device_peak = 0
    for group in by_process.values():
        group.sort(key=lambda e: e.get("monotonic", 0.0))
        n_samples += len(group)
        group_current: dict[str, int] = {}
        group_peak: dict[str, int] = {}
        group_rss = None
        group_rss_peak = 0
        group_device_peak = 0
        for event in group:
            comp = event.get("components")
            if isinstance(comp, dict):
                group_current = {}
                for name, value in comp.items():
                    try:
                        value = int(value)
                    except (TypeError, ValueError):
                        continue
                    group_current[name] = value  # last sample wins
                    if value > group_peak.get(name, 0):
                        group_peak[name] = value
            rss = event.get("host_rss_bytes")
            if isinstance(rss, (int, float)):
                group_rss = int(rss)
                if rss > group_rss_peak:
                    group_rss_peak = int(rss)
            dev = event.get("device_peak_bytes_in_use")
            if isinstance(dev, (int, float)) and dev > group_device_peak:
                group_device_peak = int(dev)
        for name, value in group_current.items():
            slot = components.setdefault(
                name, {"current_bytes": 0, "peak_bytes": 0}
            )
            slot["current_bytes"] += value
        for name, value in group_peak.items():
            slot = components.setdefault(
                name, {"current_bytes": 0, "peak_bytes": 0}
            )
            slot["peak_bytes"] += value
        if group_rss is not None:
            last_rss = (last_rss or 0) + group_rss
            peak_rss += group_rss_peak
        device_peak += group_device_peak
    tracked = sum(c["current_bytes"] for c in components.values())
    for slot in components.values():
        slot["share_of_tracked"] = (
            round(slot["current_bytes"] / tracked, 4) if tracked else None
        )
    from elasticdl_tpu.telemetry.memory import untracked_budget_bytes

    budget = untracked_budget_bytes()
    unaccounted = (
        max(0, last_rss - tracked) if last_rss is not None else None
    )
    out = {
        "samples": n_samples,
        "components": components,
        "tracked_bytes": tracked,
        "host_rss_bytes": last_rss,
        "host_rss_peak_bytes": peak_rss or None,
        "unaccounted_bytes": unaccounted,
        "unaccounted_share_of_rss": round(unaccounted / last_rss, 4)
        if unaccounted is not None and last_rss
        else None,
        "unaccounted_budget_bytes": budget,
        "unaccounted_over_budget": bool(
            unaccounted is not None and unaccounted > budget
        ),
        "pressure_events": [
            {
                "entered": e.get("entered"),
                "host_available_bytes": e.get("host_available_bytes"),
                "monotonic": e.get("monotonic"),
            }
            for e in pressures
        ],
    }
    if device_peak:
        out["device_peak_bytes_in_use"] = device_peak
    if not n_samples:
        # pressure events without samples (a partial log): still a
        # valid report, flagged explicitly — the no_data discipline
        out["no_data"] = "memory_pressure events but no memory samples"
    return out


def slo_section(events: list[dict]) -> dict | None:
    """SLO transition timeline from ``slo_violation`` /
    ``slo_recovered`` events (telemetry/slo.py): every burn-rate
    firing with the measured value vs its threshold, recovery count,
    and which objectives were still firing when the log ended.  None
    (key absent) when the run never fired, so watchdog-less reports
    are unchanged."""
    transitions = sorted(
        (
            e
            for e in events
            if e.get("event") in ("slo_violation", "slo_recovered")
        ),
        key=lambda e: e.get("monotonic", 0.0),
    )
    if not transitions:
        return None
    firing: dict[str, dict] = {}
    violations = []
    recoveries = 0
    for event in transitions:
        objective = str(event.get("objective"))
        if event.get("event") == "slo_violation":
            entry = {
                "objective": objective,
                "signal": event.get("signal"),
                "value": event.get("value"),
                "threshold": event.get("threshold"),
                "burn_fast": event.get("burn_fast"),
                "burn_slow": event.get("burn_slow"),
                "monotonic": event.get("monotonic"),
            }
            violations.append(entry)
            firing[objective] = entry
        else:
            recoveries += 1
            firing.pop(objective, None)
    return {
        "violations": violations,
        "recoveries": recoveries,
        "still_firing": sorted(firing),
    }


def incidents_section(run_dir: str) -> dict | None:
    """Postmortem digest from every ``incidents/incident_<n>.json``
    under the run dir (telemetry/incident.py writes them at close).
    The artifacts are the full causal record; this section carries the
    operator's first-page view — cause, duration, objectives, where
    the profiler captured — plus any incident the event log says is
    STILL open (an ``incident_open`` without a matching close writes
    no artifact).  None (key absent) when the run had no incidents."""
    from elasticdl_tpu.telemetry.incident import read_incidents

    incidents = read_incidents(run_dir)
    entries = [
        {
            "incident": record.get("incident"),
            "suspected_cause": record.get("suspected_cause"),
            "rationale": record.get("rationale"),
            "duration_secs": record.get("duration_secs"),
            "objectives": record.get("objectives", []),
            "violations": len(record.get("violations", [])),
            "profile_windows": [
                w.get("window_id")
                for w in record.get("profile_windows", [])
            ],
            "timeline_entries": len(record.get("timeline", [])),
            "artifact": record.get("_path"),
        }
        for record in incidents
    ]
    # still-open incidents never wrote an artifact — recover them from
    # the event logs (open without close = the run ended unhealthy)
    open_incidents = []
    for path in _find_files(run_dir, EVENTS_FILENAME):
        opens: dict = {}
        for event in read_events(path):
            if event.get("event") == "incident_open":
                opens[event.get("incident")] = event
            elif event.get("event") == "incident_close":
                opens.pop(event.get("incident"), None)
        for number, event in sorted(opens.items(), key=lambda x: str(x[0])):
            open_incidents.append(
                {
                    "incident": number,
                    "objective": event.get("objective"),
                    "signal": event.get("signal"),
                    "log": os.path.relpath(path, run_dir),
                }
            )
    if not entries and not open_incidents:
        return None
    return {
        "total": len(entries) + len(open_incidents),
        "closed": entries,
        "open": open_incidents,
        "causes": {
            cause: sum(
                1 for e in entries if e["suspected_cause"] == cause
            )
            for cause in sorted(
                {e["suspected_cause"] for e in entries if e["suspected_cause"]}
            )
        },
    }


def control_plane_section(run_dir: str) -> dict | None:
    """Control-plane scale: heartbeat fan-in shape, per-event master
    CPU, sweep/fence latency and scrape cost vs world size — read from
    every ``fleetsim_result.json`` under the run dir.  The simulator
    mirrors its ``scale`` section into that artifact, so this section
    and the artifact stay one schema (the chaos_result discipline)."""
    runs = []
    for path in _find_files(run_dir, "fleetsim_result.json"):
        try:
            with open(path, encoding="utf-8") as f:
                result = json.load(f)
        except (OSError, ValueError):
            continue
        runs.append(
            {
                "plan": result.get("plan"),
                "seed": result.get("seed"),
                "world_size": result.get("world_size"),
                "invariants_ok": result.get("invariants_ok"),
                "budgets": result.get("budgets", {}),
                "scale": result.get("scale", {}),
            }
        )
    return {"runs": runs} if runs else None


def build_report(run_dir: str) -> dict:
    from elasticdl_tpu.telemetry.tracing import SPANS_FILENAME
    from elasticdl_tpu.telemetry.trace import analyze_telemetry_dir

    faults = _load_fault_events(run_dir)
    runs = {}
    for path in _find_files(run_dir, EVENTS_FILENAME):
        rel = os.path.relpath(path, run_dir)
        runs[rel] = analyze_events(read_events(path), faults)
        # causal-trace view (reform critical path, stragglers) when the
        # run also wrote a span log
        telemetry_dir = os.path.dirname(path)
        if os.path.exists(os.path.join(telemetry_dir, SPANS_FILENAME)):
            runs[rel]["trace"] = analyze_telemetry_dir(telemetry_dir)
    report = {"run_dir": run_dir, "runs": runs, "faults": faults}
    for path in _find_files(run_dir, "chaos_result.json"):
        try:
            with open(path, encoding="utf-8") as f:
                report["chaos_result"] = json.load(f)
            break
        except (OSError, ValueError):
            continue
    control_plane = control_plane_section(run_dir)
    if control_plane is not None:
        report["control_plane"] = control_plane
    incidents = incidents_section(run_dir)
    if incidents is not None:
        report["incidents"] = incidents
    return report


def _format_text(report: dict) -> str:
    lines = [f"Run report: {report['run_dir']}"]
    chaos = report.get("chaos_result")
    if chaos:
        verdicts = " ".join(
            f"{i['name']}={i['status']}" for i in chaos.get("invariants", [])
        )
        lines.append(
            f"chaos: plan={chaos.get('plan')} seed={chaos.get('seed')} "
            f"ok={chaos.get('invariants_ok')}"
        )
        if verdicts:
            lines.append(f"  invariants: {verdicts}")
    control_plane = report.get("control_plane")
    if control_plane:
        for sim in control_plane["runs"]:
            scale = sim.get("scale", {})
            hb = scale.get("heartbeats", {})
            sweep = scale.get("sweep_ms", {})
            fence = scale.get("fence_ms", {})
            scrape = scale.get("scrape", {})
            lines.append(
                "control plane (fleetsim {}): {} workers  ok={}".format(
                    sim.get("plan"),
                    sim.get("world_size"),
                    sim.get("invariants_ok"),
                )
            )
            lines.append(
                "  heartbeats: {} in {} batches (mean {} max {})  "
                "cpu/call {}ms".format(
                    hb.get("total"),
                    hb.get("batches"),
                    hb.get("mean_batch"),
                    hb.get("max_batch"),
                    hb.get("cpu_ms_per_call"),
                )
            )
            if sweep:
                lines.append(
                    "  sweep: p50={}ms p95={}ms p99={}ms max={}ms  "
                    "fence max={}ms  dead={}".format(
                        sweep.get("p50"),
                        sweep.get("p95"),
                        sweep.get("p99"),
                        sweep.get("max"),
                        fence.get("max"),
                        scale.get("dead_detected"),
                    )
                )
            if scrape:
                lines.append(
                    "  scrape: {}ms, {} bytes, {} worker series".format(
                        scrape.get("ms"),
                        scrape.get("bytes"),
                        scrape.get("worker_series"),
                    )
                )
            for name, budget in sorted(sim.get("budgets", {}).items()):
                lines.append(
                    "  budget {:<24s} {} / {}  [{}]".format(
                        name,
                        budget.get("value"),
                        budget.get("budget"),
                        "ok" if budget.get("ok") else "EXCEEDED",
                    )
                )
    if not report["runs"]:
        lines.append(
            "no telemetry event logs found (run the master with "
            "--telemetry_dir, or the chaos runner with --workdir)"
        )
    for rel, run in report["runs"].items():
        lines.append(f"== {rel} ==")
        if run.get("no_data"):
            lines.append(f"no data: {run['no_data']}")
        for gen, stats in run["generations"].items():
            pct = (
                "  p50={:.1f}ms p95={:.1f}ms p99={:.1f}ms".format(
                    stats["step_time_p50_ms"],
                    stats["step_time_p95_ms"],
                    stats["step_time_p99_ms"],
                )
                if "step_time_p50_ms" in stats
                else ""
            )
            lines.append(
                f"generation {gen}: {stats['steps']} steps{pct}  "
                f"records={stats['records']} workers={stats['workers']}"
            )
        for gap in run["reform_downtime"]:
            cause = gap.get("cause")
            caused_by = (
                "  cause: {} ({}, process {}, step {})".format(
                    cause.get("fault_id"),
                    cause.get("kind"),
                    cause.get("process_id"),
                    cause.get("at_step"),
                )
                if cause
                else "  cause: unattributed"
            )
            lines.append(
                "reform gen{}->gen{}: downtime {:.2f}s  "
                "tasks recovered: {}{}".format(
                    gap["from_generation"],
                    gap["to_generation"],
                    gap["downtime_secs"],
                    gap["tasks_recovered"],
                    caused_by,
                )
            )
        trace = run.get("trace") or {}
        for gap in trace.get("reform_downtime", []):
            for phase, secs in gap.get("phases_secs", {}).items():
                lines.append(
                    "  phase {:<20s} {:8.3f}s  (gen{}->gen{})".format(
                        phase,
                        secs,
                        gap["from_generation"],
                        gap["to_generation"],
                    )
                )
        for gen, stats in (trace.get("stragglers") or {}).items():
            for worker, w in stats.get("workers", {}).items():
                if w.get("straggler"):
                    lines.append(
                        f"straggler: gen {gen} worker {worker}: median "
                        f"{w['median_step_ms']:.1f}ms "
                        f"({w['vs_generation_median']}x gen median)"
                    )
        goodput = run.get("goodput")
        if goodput:
            for gen, g in goodput["generations"].items():
                roofline = g.get("e2e_vs_roofline")
                mfu = g.get("mfu")
                lines.append(
                    "goodput gen {}: e2e_vs_roofline {} (binding: {})  "
                    "untracked {}  mfu {}".format(
                        gen,
                        f"{roofline:.3f}" if roofline is not None else "n/a",
                        g.get("binding"),
                        f"{g['untracked_share'] * 100:.1f}%"
                        if g.get("untracked_share") is not None
                        else "n/a",
                        f"{mfu:.3f}"
                        if mfu is not None
                        else f"n/a ({g.get('mfu_reason')})",
                    )
                )
                for phase, stats in sorted(g["phases"].items()):
                    lines.append(
                        "  phase {:<17s} {:9.1f}ms ({:5.1f}%)  "
                        "p50={:.2f}ms p95={:.2f}ms p99={:.2f}ms".format(
                            phase,
                            stats["total_ms"],
                            (stats["share"] or 0.0) * 100.0,
                            stats["p50_ms"],
                            stats["p95_ms"],
                            stats["p99_ms"],
                        )
                    )
                for worker, w in (g.get("workers") or {}).items():
                    if w.get("straggler"):
                        lines.append(
                            "  straggler: worker {} lags on {} "
                            "(device_compute p50 {:.1f}ms, host_fetch "
                            "p50 {:.1f}ms)".format(
                                worker,
                                w["lagging_phase"],
                                w["device_compute_p50_ms"],
                                w["host_fetch_p50_ms"],
                            )
                        )
        master_ha = run.get("master_ha")
        if master_ha:
            for restart in master_ha["restarts"]:
                downtime = restart["downtime_secs"]
                replay = restart["journal_replay_secs"]
                lines.append(
                    "master restart (gen {}): downtime {}  journal "
                    "replay {}  re-homed workers {}  leases kept {} / "
                    "requeued {}".format(
                        restart["generation"],
                        f"{downtime:.2f}s" if downtime is not None else "n/a",
                        f"{replay * 1000:.0f}ms"
                        if replay is not None
                        else "n/a",
                        restart["workers_rehomed"],
                        restart["leases_kept"],
                        restart["leases_requeued"],
                    )
                )
        replication = run.get("replication")
        if replication:
            for gen, n in sorted(replication["pushes_by_generation"].items()):
                hosts = replication["hosts_covered_by_generation"].get(
                    gen, []
                )
                version = replication["shard_versions_by_generation"].get(
                    gen
                )
                lines.append(
                    f"replication gen {gen}: {n} pushes, hosts {hosts}, "
                    f"freshest shard version {version}"
                )
            for restore in replication["restores"]:
                lines.append(
                    "replica restore: gen {} resumed at step {} "
                    "(peer RAM, no disk read)".format(
                        restore["generation"], restore["step"]
                    )
                )
        multislice = run.get("multislice")
        if multislice:
            for loss in multislice["slice_losses"]:
                lines.append(
                    "slice loss (gen {}): slices {} dead -> {} of {} "
                    "slice(s) survive{}".format(
                        loss["generation"],
                        loss["lost_slices"],
                        loss["new_slices"],
                        loss["old_slices"],
                        "  [PARKED below --min_slices]"
                        if loss.get("parked")
                        else "",
                    )
                )
            for resize in multislice["mesh_resizes"]:
                lines.append(
                    "mesh resize (gen {}): {} procs / {} slice(s) -> "
                    "{} procs / {} slice(s)  dcn={}".format(
                        resize["generation"],
                        resize["old_world_size"],
                        resize["old_slices"],
                        resize["new_world_size"],
                        resize["new_slices"],
                        resize["dcn"],
                    )
                )
            for decision in multislice["autoscale_decisions"]:
                lines.append(
                    "autoscale {} (gen {}): {} -> {} slice(s)  "
                    "({})".format(
                        decision["action"],
                        decision["generation"],
                        decision["from_slices"],
                        decision["to_slices"],
                        decision["reason"],
                    )
                )
            pushes = multislice["replica_pushes_by_source_slice"]
            if pushes:
                per_slice = " ".join(
                    f"slice{s}={n}" for s, n in sorted(pushes.items())
                )
                lines.append(f"cross-slice replica pushes: {per_slice}")
        serving = run.get("serving")
        if serving:
            lines.append(
                "serving: {} requests / {} rows in {} dispatches  "
                "sheds={} errors={}{}".format(
                    serving["requests"],
                    serving["rows"],
                    serving["dispatches"],
                    serving["sheds"],
                    serving["errors"],
                    "  p50={}ms p95={}ms p99={}ms".format(
                        serving["latency_p50_ms"],
                        serving["latency_p95_ms"],
                        serving["latency_p99_ms"],
                    )
                    if "latency_p50_ms" in serving
                    else "",
                )
            )
            for phase, stats in sorted(serving["phases"].items()):
                lines.append(
                    "  phase {:<15s} p50={:.3f}ms p95={:.3f}ms "
                    "p99={:.3f}ms".format(
                        phase,
                        stats["p50_ms"],
                        stats["p95_ms"],
                        stats["p99_ms"],
                    )
                )
            for swap in serving["swaps"]:
                lines.append(
                    "  swap: v{} -> v{} ({}, {:.1f}ms)".format(
                        swap.get("old_version"),
                        swap.get("model_version"),
                        swap.get("source"),
                        float(swap.get("swap_ms") or 0.0),
                    )
                )
        memory = run.get("memory")
        if memory:
            if memory.get("no_data"):
                lines.append(f"memory: no data: {memory['no_data']}")
            rss = memory.get("host_rss_bytes")
            unaccounted = memory.get("unaccounted_bytes")
            lines.append(
                "memory: tracked {:.1f} MB over {} components  "
                "rss {}  unaccounted {}{}".format(
                    memory["tracked_bytes"] / 1e6,
                    len(memory["components"]),
                    f"{rss / 1e6:.1f} MB" if rss is not None else "n/a",
                    f"{unaccounted / 1e6:.1f} MB"
                    if unaccounted is not None
                    else "n/a",
                    "  [OVER BUDGET]"
                    if memory.get("unaccounted_over_budget")
                    else "",
                )
            )
            for name, slot in sorted(memory["components"].items()):
                lines.append(
                    "  component {:<16s} current {:>12.0f} B  "
                    "peak {:>12.0f} B{}".format(
                        name,
                        slot["current_bytes"],
                        slot["peak_bytes"],
                        "  ({:.1f}% of tracked)".format(
                            slot["share_of_tracked"] * 100.0
                        )
                        if slot.get("share_of_tracked") is not None
                        else "",
                    )
                )
            for pressure in memory["pressure_events"]:
                lines.append(
                    "  pressure {}: MemAvailable {}".format(
                        "ENTERED" if pressure.get("entered") else "cleared",
                        pressure.get("host_available_bytes"),
                    )
                )
        slo = run.get("slo")
        if slo:
            lines.append(
                "slo: {} violation(s), {} recovery(ies){}".format(
                    len(slo["violations"]),
                    slo["recoveries"],
                    "  STILL FIRING: " + ", ".join(slo["still_firing"])
                    if slo["still_firing"]
                    else "",
                )
            )
            for violation in slo["violations"]:
                lines.append(
                    "  violated {}: {} = {} (threshold {})".format(
                        violation["objective"],
                        violation["signal"],
                        violation["value"],
                        violation["threshold"],
                    )
                )
        streaming = run.get("streaming")
        if streaming:
            lines.append(
                "streaming: trained watermark {} / source {}{}".format(
                    streaming.get("trained_watermark", "?"),
                    streaming.get("source_watermark", "?"),
                    " (source closed)"
                    if streaming.get("closed")
                    else "",
                )
            )
            lag = streaming.get("lag_records")
            if lag:
                lines.append(
                    "  lag: max {} p50 {} p95 {} last {} record(s)".format(
                        lag["max"], lag["p50"], lag["p95"], lag["last"]
                    )
                )
            fresh = streaming.get("freshness")
            if fresh:
                lines.append(
                    "  freshness: {} push(es), {} accepted, {} refused, "
                    "max staleness {} record(s)".format(
                        fresh["pushes"],
                        fresh["accepted"],
                        fresh["refused"],
                        fresh["max_staleness_records"],
                    )
                )
                for row in fresh["ledger"]:
                    lines.append(
                        "    push v{}: trained {} / source {} "
                        "(staleness {}){}".format(
                            row["model_version"],
                            row["trained_watermark"],
                            row["source_watermark"],
                            row["staleness"],
                            "" if row["accepted"] else "  REFUSED",
                        )
                    )
        for worker, rate in run["records_per_sec_by_worker"].items():
            lines.append(f"throughput: worker {worker}: {rate:.1f} records/s")
        if run["worker_time_ms"]:
            buckets = " ".join(
                f"{name}={total:.0f}ms"
                for name, total in sorted(run["worker_time_ms"].items())
            )
            lines.append(f"worker time buckets: {buckets}")
    incidents = report.get("incidents")
    if incidents:
        lines.append(
            "incidents: {} total ({} closed, {} still open)".format(
                incidents["total"],
                len(incidents["closed"]),
                len(incidents["open"]),
            )
        )
        for entry in incidents["closed"]:
            windows = entry["profile_windows"]
            lines.append(
                "  incident {}: {} for {:.1f}s  objectives: {}  "
                "profile windows: {}  [{}]".format(
                    entry["incident"],
                    entry["suspected_cause"],
                    float(entry["duration_secs"] or 0.0),
                    ", ".join(entry["objectives"]) or "n/a",
                    ", ".join(str(w) for w in windows) if windows else "none",
                    entry["artifact"],
                )
            )
            lines.append(f"    rationale: {entry['rationale']}")
        for entry in incidents["open"]:
            lines.append(
                "  incident {}: STILL OPEN (opened on {}, log {})".format(
                    entry["incident"],
                    entry["objective"],
                    entry["log"],
                )
            )
    return "\n".join(lines)


def summarize_report(report: dict) -> dict:
    """Machine-readable digest of a full report (``--summary-json``):
    a top-level ``verdict`` plus the counts CI actually branches on.
    Pure over the report dict so tests drive it with canned reports.

    Verdict ladder (worst wins): ``fail`` when any chaos/fleetsim
    invariant failed, an incident is still open, or an SLO objective
    was still firing at log end; ``degraded`` when incidents or SLO
    violations occurred but everything recovered; ``no_data`` when
    nothing produced a single event or artifact; ``ok`` otherwise."""
    reasons = []
    slo_violations = 0
    slo_recoveries = 0
    still_firing: list[str] = []
    events_total = 0
    serving_runs = 0
    serving_totals = {"requests": 0, "rows": 0, "sheds": 0, "errors": 0}
    for rel, run in report.get("runs", {}).items():
        events_total += run.get("events_total", 0)
        slo = run.get("slo")
        if slo:
            slo_violations += len(slo["violations"])
            slo_recoveries += slo["recoveries"]
            for objective in slo["still_firing"]:
                still_firing.append(objective)
                reasons.append(
                    f"slo objective {objective} still firing ({rel})"
                )
        serving = run.get("serving")
        if serving:
            serving_runs += 1
            for key in serving_totals:
                serving_totals[key] += int(serving.get(key, 0))
    chaos = report.get("chaos_result")
    if chaos is not None and not chaos.get("invariants_ok", True):
        reasons.append("chaos invariants failed")
    fleetsim_runs = (report.get("control_plane") or {}).get("runs", [])
    for sim in fleetsim_runs:
        if not sim.get("invariants_ok", True):
            reasons.append(
                f"fleetsim invariants failed ({sim.get('plan')})"
            )
    incidents = report.get("incidents") or {}
    for entry in incidents.get("open", []):
        reasons.append(f"incident {entry['incident']} still open")
    if reasons:
        verdict = "fail"
    elif incidents.get("total") or slo_violations:
        verdict = "degraded"
        reasons.append(
            "incidents/slo violations occurred but all recovered"
        )
    elif not report.get("runs") and chaos is None and not fleetsim_runs:
        verdict = "no_data"
        reasons.append("no telemetry, chaos, or fleetsim artifacts found")
    else:
        verdict = "ok"
    return {
        "verdict": verdict,
        "reasons": reasons,
        "run_dir": report.get("run_dir"),
        "runs": len(report.get("runs", {})),
        "events_total": events_total,
        "slo": {
            "violations": slo_violations,
            "recoveries": slo_recoveries,
            "still_firing": sorted(set(still_firing)),
        },
        "incidents": {
            "total": incidents.get("total", 0),
            "open": len(incidents.get("open", [])),
            "causes": incidents.get("causes", {}),
        },
        # serving runs ride the same verdict ladder (their incidents
        # and SLO blocks land via the shared paths above); the digest
        # adds the traffic counts CI asserts on, None when no run served
        "serving": {"runs": serving_runs, **serving_totals}
        if serving_runs
        else None,
        "chaos": {
            "plan": chaos.get("plan"),
            "invariants_ok": chaos.get("invariants_ok"),
        }
        if chaos is not None
        else None,
        "fleetsim": [
            {
                "plan": sim.get("plan"),
                "world_size": sim.get("world_size"),
                "invariants_ok": sim.get("invariants_ok"),
            }
            for sim in fleetsim_runs
        ],
    }


def build_arg_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="python -m elasticdl_tpu.telemetry.report",
        description="Summarize a run's telemetry event logs",
    )
    parser.add_argument("run_dir", help="Directory tree holding events.jsonl")
    parser.add_argument(
        "--json", action="store_true", help="Emit the full report as JSON"
    )
    parser.add_argument(
        "--output", default="", help="Also write the JSON report here"
    )
    parser.add_argument(
        "--summary-json",
        default="",
        dest="summary_json",
        help="Write a machine-readable digest (top-level verdict + the "
        "counts CI branches on) to this path",
    )
    return parser


def main(argv=None) -> int:
    args = build_arg_parser().parse_args(argv)
    if not os.path.isdir(args.run_dir):
        print(f"not a directory: {args.run_dir}", file=sys.stderr)
        return 2
    report = build_report(args.run_dir)
    if args.json:
        print(json.dumps(report, indent=2, default=str))
    else:
        print(_format_text(report))
    if args.output:
        with open(args.output, "w", encoding="utf-8") as f:
            json.dump(report, f, indent=2, default=str)
            f.write("\n")
    if args.summary_json:
        with open(args.summary_json, "w", encoding="utf-8") as f:
            json.dump(summarize_report(report), f, indent=2, default=str)
            f.write("\n")
    # a run dir with no telemetry yet is a VALID state (job starting,
    # telemetry disabled), reported explicitly above — not an error.
    # Only a non-directory argument (rc 2, earlier) is caller misuse.
    # The summary artifact carries the VERDICT; the process rc stays
    # "did the report build", so watch pipelines can read severity
    # without conflating it with tool failure.
    return 0


if __name__ == "__main__":
    sys.exit(main())
