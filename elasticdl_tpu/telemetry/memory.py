"""Component-level host/HBM memory ledger — bytes, attributed.

Every observability layer so far measures TIME (traces, step anatomy,
serving latency, fleet CPU budgets); the failure mode that actually
kills elastic TPU jobs is MEMORY: an autoscale grow, a hot model swap
(transiently double-resident leaves), or the ReplicaStore's
two-versions-per-source retention can walk a host into OOM with no
telemetry warning at all.  This module is the byte-side of the anatomy
discipline: long-lived byte owners register an accounting callback
under a stable component name, and the ledger samples them — plus
device memory via ``jax.Device.memory_stats()`` (``bytes_in_use`` /
``peak_bytes_in_use``; gracefully absent on CPU backends, which return
``None``) and host RSS from ``/proc/self/status`` — periodically (the
worker heartbeat cadence) and at phase edges (reform, model swap,
checkpoint, engine build).

Registered components (each registers itself at construction; the
names below are the single vocabulary site):

- ``model_state``      — trainer params/opt-state/model-state leaf bytes
- ``replica_store``    — retained replica shard payloads (2/source)
- ``device_stager``    — staged dispatch groups waiting on device
- ``task_prefetcher``  — decoded batches buffered by the host pipeline
- ``serving_queue``    — the micro-batcher's pending request rows
- ``serving_model``    — served model leaves (including the swap's
  transient double residency: old + incoming leaves both resident
  between placement and the state-pointer replace)
- ``master_journal``   — the control-plane journal's unflushed buffer

Honesty contract: the ledger does NOT claim sum-exactness the way step
anatomy does — allocators lie (arenas, fragmentation, the interpreter
and the XLA runtime themselves), so the residual between host RSS and
the tracked components is surfaced as an explicit ``unaccounted``
line with its own absolute-bytes budget
(``ELASTICDL_TPU_MEMORY_UNTRACKED_BUDGET_MB``) instead of being
hand-waved or forced to zero.  At toy-model scale the interpreter +
runtime dominate RSS, which is exactly why the budget is absolute
bytes, not a share (docs/designs/memory_ledger.md).

Wire/merge semantics: workers ship ``heartbeat_snapshot()`` on the
beat (``HeartbeatRequest.memory``).  Because memory goes DOWN as well
as up, the master merges current values with
``utils.merge.last_merge_counters`` (timestamped last-writer-wins) —
a max-merge would ratchet and never report a release — while the peak
watermark fields ARE max-merged (a peak is monotone).  The heartbeat
timestamp is the SENDER's wall clock (``time.time()``), comparable
across that worker's process lives.

Disabled cost: every module-level sample site is one global load and a
``None`` check (``# elastic-lint: hot-path``, machine-checked).
Component registration is construction-time, not hot; callbacks only
run when an installed ledger samples.
"""

from __future__ import annotations

import os
import threading
import time

from elasticdl_tpu.utils.log_utils import default_logger as logger

# ---- component vocabulary (one definition site) ------------------------------

COMPONENT_MODEL_STATE = "model_state"
COMPONENT_REPLICA_STORE = "replica_store"
COMPONENT_DEVICE_STAGER = "device_stager"
COMPONENT_TASK_PREFETCHER = "task_prefetcher"
COMPONENT_SERVING_QUEUE = "serving_queue"
COMPONENT_SERVING_MODEL = "serving_model"
COMPONENT_MASTER_JOURNAL = "master_journal"
# sharded embedding subsystem (elasticdl_tpu.embeddings): device-tier
# row shards this process holds, and the host-RAM spill tier's row
# stores + per-step minitable staging
COMPONENT_EMBEDDING_TABLE = "embedding_table"
COMPONENT_EMBEDDING_SPILL = "embedding_spill"

# pseudo-components carried in the same current/peak maps (so /metrics
# renders one elasticdl_memory_bytes family for everything byte-shaped)
KEY_HOST_RSS = "host_rss"
KEY_DEVICE_IN_USE = "device_bytes_in_use"

# the unaccounted-bytes budget (absolute, NOT a share: at toy-model
# scale interpreter + XLA runtime RSS dominates any model, so a share
# budget would be either vacuous or dishonest — see the design doc)
UNTRACKED_BUDGET_MB_ENV = "ELASTICDL_TPU_MEMORY_UNTRACKED_BUDGET_MB"
DEFAULT_UNTRACKED_BUDGET_MB = 8192

# host memory-pressure threshold: MemAvailable below this fraction of
# MemTotal emits a memory_pressure event (once per crossing)
PRESSURE_FRACTION_ENV = "ELASTICDL_TPU_MEMORY_PRESSURE_FRACTION"
DEFAULT_PRESSURE_FRACTION = 0.05


def untracked_budget_bytes() -> int:
    raw = os.environ.get(UNTRACKED_BUDGET_MB_ENV, "")
    try:
        mb = float(raw) if raw else DEFAULT_UNTRACKED_BUDGET_MB
    except ValueError:
        mb = DEFAULT_UNTRACKED_BUDGET_MB
    return int(mb * 1024 * 1024)


def pressure_fraction() -> float:
    raw = os.environ.get(PRESSURE_FRACTION_ENV, "")
    try:
        return float(raw) if raw else DEFAULT_PRESSURE_FRACTION
    except ValueError:
        return DEFAULT_PRESSURE_FRACTION


# ---- byte accounting helpers -------------------------------------------------


def pytree_bytes(tree) -> int:
    """Total leaf bytes of a pytree (numpy and jax arrays both carry
    ``nbytes``; leaves without it contribute 0 — scalars and None are
    not what OOMs a host)."""
    try:
        import jax

        leaves = jax.tree_util.tree_leaves(tree)
    except Exception:  # noqa: BLE001 — accounting must never raise
        return 0
    total = 0
    for leaf in leaves:
        total += int(getattr(leaf, "nbytes", 0) or 0)
    return total


def read_host_rss() -> int | None:
    """Resident set size of THIS process (``/proc/self/status`` VmRSS),
    bytes; None where /proc is unavailable."""
    try:
        with open("/proc/self/status", encoding="ascii") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) * 1024
    except (OSError, ValueError, IndexError):
        return None
    return None


def _read_meminfo(field: str) -> int | None:
    try:
        with open("/proc/meminfo", encoding="ascii") as f:
            for line in f:
                if line.startswith(field + ":"):
                    return int(line.split()[1]) * 1024
    except (OSError, ValueError, IndexError):
        return None
    return None


def read_host_available() -> int | None:
    """Host-wide MemAvailable, bytes (the /healthz headroom source)."""
    return _read_meminfo("MemAvailable")


# MemTotal is constant for the machine's uptime: read it once so the
# per-sample pressure check costs no extra /proc parse (and none while
# holding the ledger lock).  The sentinel distinguishes "never read"
# from "read, unavailable" (non-Linux).
_host_total_cache: list = []


def read_host_total() -> int | None:
    if not _host_total_cache:
        _host_total_cache.append(_read_meminfo("MemTotal"))
    return _host_total_cache[0]


def read_device_memory() -> dict:
    """Accelerator allocator stats summed over local devices:
    ``{"bytes_in_use", "peak_bytes_in_use", "bytes_limit"}`` (the limit
    is 0 where the allocator reports none), or ``{}`` on backends
    without allocator stats (CPU returns ``None`` from
    ``memory_stats()``) — the graceful-None contract.  The limit minus
    in-use is the headroom the device stager's admission control
    budgets against."""
    try:
        import jax

        devices = jax.local_devices()
    except Exception:  # noqa: BLE001 — no backend is a valid state
        return {}
    in_use = peak = limit = 0
    found = False
    for device in devices:
        try:
            stats = device.memory_stats()
        except Exception:  # noqa: BLE001 — per-device stats are optional
            stats = None
        if not stats:
            continue
        found = True
        in_use += int(stats.get("bytes_in_use", 0) or 0)
        peak += int(
            stats.get("peak_bytes_in_use", stats.get("bytes_in_use", 0))
            or 0
        )
        limit += int(stats.get("bytes_limit", 0) or 0)
    if not found:
        return {}
    return {
        "bytes_in_use": in_use,
        "peak_bytes_in_use": peak,
        "bytes_limit": limit,
    }


def host_memory_health() -> dict:
    """The /healthz headroom block: point-in-time host RSS, host-wide
    availability and the headroom share (None-safe on /proc-less
    platforms)."""
    rss = read_host_rss()
    available = read_host_available()
    total = read_host_total()
    return {
        "host_rss_bytes": rss,
        "host_available_bytes": available,
        "headroom_share": round(available / total, 4)
        if available is not None and total
        else None,
    }


# ---- the ledger --------------------------------------------------------------

# component name -> zero-arg bytes callback.  Module-level so byte
# owners can register at construction BEFORE any ledger is installed
# (and independent of whether one ever is); re-registering a name
# replaces the callback (bench runs several configs per process).
_components: dict[str, object] = {}
_components_lock = threading.Lock()


def register_component(component: str, fn):
    """Register (or replace) a component's accounting callback.  ``fn``
    returns the component's CURRENT resident bytes; it must be cheap
    (attribute reads) and must never raise for correctness — a raising
    callback is skipped for that sample."""
    with _components_lock:
        _components[component] = fn


def unregister_component(component: str, fn=None):
    """Drop a component's callback.  Pass the registered callable as
    ``fn`` to make the removal identity-guarded: an owner being torn
    down AFTER a replacement registered under the same name (bench and
    the in-process harnesses build several owners per process) then
    leaves the newer registration alone."""
    with _components_lock:
        if fn is None or _components.get(component) is fn:
            _components.pop(component, None)


def register_trainer_state(get_state):
    """Register the ``model_state`` component from a zero-arg state
    getter (params + optimizer state + mutable collections — the
    trainer's whole carried pytree).  One definition site for the shape
    all three runtimes (local executor, task-stream worker, lockstep)
    register."""

    def _bytes():
        state = get_state()
        return pytree_bytes(state) if state is not None else 0

    register_component(COMPONENT_MODEL_STATE, _bytes)


class MemoryLedger:
    """Per-process byte ledger: samples the component registry, device
    allocator stats and host RSS; maintains current values and peak
    watermarks; emits ``memory_sample``/``memory_pressure`` events.

    ``emit`` is the event sink (``fn(event, **fields)``) — workers pass
    :func:`~elasticdl_tpu.telemetry.worker_hooks.emit_event`, the
    master passes its own event log's emit.  A None sink keeps the
    ledger usable for direct reads (tests, bench)."""

    def __init__(self, emit=None, clock=time.time):
        self._emit = emit
        self._clock = clock
        self._lock = threading.Lock()
        self._current: dict[str, int] = {}  # guarded-by: _lock
        self._peak: dict[str, int] = {}  # guarded-by: _lock
        self._stamp = 0.0  # guarded-by: _lock (writes)
        self._samples = 0  # guarded-by: _lock (writes)
        self._pressure_active = False  # guarded-by: _lock (writes)

    # ---- sampling ----------------------------------------------------------

    def sample(self, phase: str = "periodic") -> dict:
        """One full sample: run every registered callback, read device
        and host memory, roll peaks forward, and emit a
        ``memory_sample`` event.  Returns the sample dict (the report
        section's schema)."""
        with _components_lock:
            callbacks = list(_components.items())
        components: dict[str, int] = {}
        for name, fn in callbacks:
            try:
                value = int(fn())
            except Exception:  # noqa: BLE001 — a broken callback skips
                # its component for this sample, never breaks sampling
                continue
            if value >= 0:
                components[name] = value
        rss = read_host_rss()
        available = read_host_available()
        device = read_device_memory()
        tracked = sum(components.values())
        unaccounted = max(0, rss - tracked) if rss is not None else None
        with self._lock:
            self._samples += 1
            self._stamp = self._clock()
            # whole-map replacement: a component absent from this round
            # (unregistered owner) leaves the current view — the sample
            # IS the truth, matching the wire's last-writer-wins
            self._current = dict(components)
            if rss is not None:
                self._current[KEY_HOST_RSS] = rss
            if device:
                self._current[KEY_DEVICE_IN_USE] = device["bytes_in_use"]
            for name, value in self._current.items():
                if value > self._peak.get(name, 0):
                    self._peak[name] = value
            if device and device["peak_bytes_in_use"] > self._peak.get(
                KEY_DEVICE_IN_USE, 0
            ):
                # the allocator's own high-water mark outranks anything
                # a sampling cadence could have caught
                self._peak[KEY_DEVICE_IN_USE] = device["peak_bytes_in_use"]
            pressure = self._pressure_check_locked(available)
        out = {
            "phase": phase,
            "components": components,
            "tracked_bytes": tracked,
            "host_rss_bytes": rss,
            "host_available_bytes": available,
            "unaccounted_bytes": unaccounted,
        }
        if device:
            out["device_bytes_in_use"] = device["bytes_in_use"]
            out["device_peak_bytes_in_use"] = device["peak_bytes_in_use"]
        if self._emit is not None:
            from elasticdl_tpu.telemetry.events import EVENT_MEMORY_SAMPLE

            try:
                self._emit(EVENT_MEMORY_SAMPLE, **out)
            except Exception:  # noqa: BLE001 — telemetry never raises
                # into the sampling caller (heartbeat thread, swap path)
                logger.exception("Memory sample event emit failed")
        if pressure is not None:
            self._emit_pressure(pressure, available, rss)
        return out

    # lock-holding: _lock
    def _pressure_check_locked(self, available) -> bool | None:
        """Crossing detector: True = entered pressure, False = left it,
        None = no change (one event per crossing, not per sample)."""
        total = read_host_total()
        if available is None or not total:
            return None
        under = (available / total) < pressure_fraction()
        if under == self._pressure_active:
            return None
        self._pressure_active = under
        return under

    def _emit_pressure(self, entered: bool, available, rss):
        if self._emit is None:
            return
        from elasticdl_tpu.telemetry.events import EVENT_MEMORY_PRESSURE

        try:
            self._emit(
                EVENT_MEMORY_PRESSURE,
                entered=bool(entered),
                host_available_bytes=available,
                host_rss_bytes=rss,
            )
        except Exception:  # noqa: BLE001 — telemetry never raises
            logger.exception("Memory pressure event emit failed")

    # ---- reads -------------------------------------------------------------

    def snapshot(self) -> dict:
        """Current + peak maps (copies) — the /metrics mirror's read."""
        with self._lock:
            return {
                "current": dict(self._current),
                "peak": dict(self._peak),
            }

    def heartbeat_snapshot(self) -> dict:
        """The wire shape for ``HeartbeatRequest.memory``: ``{"at":
        <sender wall clock>, "current": {...}, "peak": {...}}``.  ``at``
        orders this worker's samples under the master's last-writer-wins
        merge; peaks merge monotone.  ``{}`` before the first sample so
        an idle worker ships nothing (wire-compatible old payloads)."""
        with self._lock:
            if not self._samples:
                return {}
            return {
                "at": self._stamp,
                "current": dict(self._current),
                "peak": dict(self._peak),
            }

    @property
    def samples(self) -> int:
        return self._samples


# ---- module-level install + zero-cost-when-disabled accessors ---------------

_active: MemoryLedger | None = None


def install(emit=None, clock=time.time) -> MemoryLedger:
    global _active
    _active = MemoryLedger(emit=emit, clock=clock)
    return _active


def install_if_enabled(telemetry_dir: str, emit=None) -> MemoryLedger | None:
    """Install when telemetry is configured (the ledger's surfaces —
    events, heartbeat field, report section — all hang off the
    telemetry dir); clears any stale ledger otherwise, so a
    telemetry-less runtime constructed after an instrumented one (bench
    runs several configs per process) does not inherit it."""
    if not telemetry_dir:
        uninstall()
        return None
    if emit is None:
        from elasticdl_tpu.telemetry import worker_hooks

        emit = worker_hooks.emit_event
    return install(emit=emit)


def install_from_env(emit=None) -> MemoryLedger | None:
    """Worker-subprocess entry: install only when the master exported
    the telemetry dir (the chaos-plan/anatomy env pattern)."""
    from elasticdl_tpu.telemetry.worker_hooks import TELEMETRY_DIR_ENV

    return install_if_enabled(
        os.environ.get(TELEMETRY_DIR_ENV, ""), emit=emit
    )


def uninstall():
    global _active
    _active = None


def get_ledger() -> MemoryLedger | None:  # elastic-lint: hot-path
    return _active


def sample(phase: str = "periodic"):  # elastic-lint: hot-path
    """THE sample site: one global load + None check when disabled."""
    ledger = _active
    if ledger is None:
        return None
    return ledger.sample(phase)


def heartbeat_snapshot() -> dict:  # elastic-lint: hot-path
    """Ledger state for ``HeartbeatRequest.memory``; ``{}`` when
    disabled (old payloads decode the same — wire-compatible)."""
    ledger = _active
    if ledger is None:
        return {}
    return ledger.heartbeat_snapshot()
