"""Telemetry spine: metrics registry, elastic event log, ``/metrics``
endpoint, run-report CLI (docs/designs/telemetry.md).

- :mod:`elasticdl_tpu.telemetry.registry` — process-local counters /
  gauges / histograms with Prometheus text exposition;
- :mod:`elasticdl_tpu.telemetry.events` — append-only JSONL elastic
  lifecycle log shared by master + workers;
- :mod:`elasticdl_tpu.telemetry.master_hooks` — the master's observer
  wiring and health snapshot;
- :mod:`elasticdl_tpu.telemetry.worker_hooks` — per-step samples, free
  (single early-return) when telemetry is not installed;
- :mod:`elasticdl_tpu.telemetry.httpd` — daemon-thread HTTP endpoint;
- :mod:`elasticdl_tpu.telemetry.report` — ``python -m
  elasticdl_tpu.telemetry.report <run_dir>``.
"""

from elasticdl_tpu.telemetry.events import EventLog, read_events
from elasticdl_tpu.telemetry.registry import (
    STEP_LATENCY_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
)

__all__ = [
    "Counter",
    "EventLog",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "STEP_LATENCY_BUCKETS",
    "read_events",
]
