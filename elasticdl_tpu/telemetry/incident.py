"""Incident postmortems for the SLO watchdog.

When the SLO engine (:mod:`elasticdl_tpu.telemetry.slo`) fires, the
interesting question is never "did we violate" — it is "what was
happening around the violation".  This module owns that correlation:
an :class:`IncidentManager` groups violations into incidents (one
incident spans the whole unhealthy episode — a second objective firing
while one is already open JOINS the open incident rather than opening
another, which is how an injected regression produces exactly ONE
incident), and at close time correlates events + spans + step anatomy
+ memory + rpc stats around the violation window into
``incidents/incident_<n>.json``: a causal timeline plus a
suspected-cause classification.

The classification vocabulary is deliberately small — the five regimes
an operator actually pages on:

- ``input-bound``       the host fetch path grew; the device starved
- ``compute-bound``     the device path itself slowed
- ``network-degraded``  outage-class RPC counters rose
- ``memory-pressure``   host/HBM headroom collapsed
- ``control-plane``     reforms / master restarts / progress stalls

Clocks are injectable like everywhere else in the watchdog: the master
correlates against ``monotonic`` stamps in the on-disk event log; the
fleet simulator runs the same manager with its ``VirtualClock`` and an
empty telemetry dir (in-memory timeline only, no file I/O — nothing
nondeterministic may ride the digest path)."""

from __future__ import annotations

import json
import os
import time

from elasticdl_tpu.telemetry import slo as slo_mod
from elasticdl_tpu.telemetry.events import (
    EVENT_INCIDENT_CLOSE,
    EVENT_INCIDENT_OPEN,
    EVENTS_FILENAME,
    read_events,
    read_jsonl,
)

INCIDENTS_DIRNAME = "incidents"

CAUSE_INPUT_BOUND = "input-bound"
CAUSE_COMPUTE_BOUND = "compute-bound"
CAUSE_NETWORK_DEGRADED = "network-degraded"
CAUSE_MEMORY_PRESSURE = "memory-pressure"
CAUSE_CONTROL_PLANE = "control-plane"
# serving-plane causes (classified by serving/watchdog.py's
# classify_serving_cause through the classify_fn seam below)
CAUSE_QUEUE_BOUND = "queue-bound"
CAUSE_REPLICA_DOWN = "replica-down"
CAUSE_SWAP_IN_PROGRESS = "swap-in-progress"

# events whose presence in the window marks control-plane churn
_CONTROL_PLANE_EVENTS = frozenset(
    {
        "reform_start",
        "reform_complete",
        "reform_failed",
        "master_restart",
        "journal_replay",
        "worker_rehome",
        "slice_loss",
        "mesh_resize",
        "autoscale_decision",
        "worker_dead",
    }
)

# how far before the first bad evaluation the timeline reaches back —
# the onset context (what changed just before the burn started)
DEFAULT_LOOKBACK_SECS = 60.0
# artifact bound: a pathological window must not produce a megabyte
# timeline
_TIMELINE_CAP = 400


def _phase_ms(phase_totals: dict, phase: str) -> float:
    try:
        return float((phase_totals.get(phase) or {}).get("ms", 0.0))
    except (TypeError, ValueError):
        return 0.0


def classify_cause(
    violations: list[dict],
    context_open: dict | None,
    context_close: dict | None,
    window_events: list[dict] | None = None,
) -> tuple[str, str]:
    """Pure classification: (suspected_cause, rationale).

    Rule order encodes specificity — a memory or network signal is a
    sharper diagnosis than "the step got slower", and control-plane
    churn explains a stall better than anatomy shares do; only when
    none of those hold do we split input- vs compute-bound on the
    anatomy's phase growth across the incident window."""
    signals = {v.get("signal") for v in violations}
    if slo_mod.SIGNAL_MEMORY_HEADROOM_SHARE in signals:
        return (
            CAUSE_MEMORY_PRESSURE,
            "memory headroom share violated its floor",
        )
    for event in window_events or []:
        if event.get("event") == "memory_pressure":
            return (
                CAUSE_MEMORY_PRESSURE,
                "memory_pressure events inside the violation window",
            )
    if slo_mod.SIGNAL_RPC_OUTAGE_RISE in signals:
        return (
            CAUSE_NETWORK_DEGRADED,
            "outage-class rpc counters rose during the window",
        )
    open_rpc = (context_open or {}).get("rpc") or {}
    close_rpc = (context_close or {}).get("rpc") or {}
    if slo_mod.outage_total(close_rpc) > slo_mod.outage_total(open_rpc):
        return (
            CAUSE_NETWORK_DEGRADED,
            "outage-class rpc counters rose across the incident",
        )
    control_events = sorted(
        {
            event.get("event")
            for event in window_events or []
            if event.get("event") in _CONTROL_PLANE_EVENTS
        }
    )
    if control_events:
        return (
            CAUSE_CONTROL_PLANE,
            "control-plane churn in the window: "
            + ", ".join(str(e) for e in control_events),
        )
    if signals & {
        slo_mod.SIGNAL_LAST_STEP_AGE_SECS,
        slo_mod.SIGNAL_REFORM_DOWNTIME_SECS,
    }:
        return (
            CAUSE_CONTROL_PLANE,
            "progress stalled without matching anatomy/network/memory "
            "signals",
        )
    # anatomy split: which side of the roofline grew across the
    # incident?  Deltas when both snapshots carry phases; otherwise the
    # close snapshot's absolute shares.
    open_phases = (context_open or {}).get("anatomy") or {}
    close_phases = (context_close or {}).get("anatomy") or {}
    host = _phase_ms(close_phases, "host_fetch")
    device = (
        _phase_ms(close_phases, "assemble")
        + _phase_ms(close_phases, "h2d_transfer")
        + _phase_ms(close_phases, "device_compute")
    )
    if open_phases:
        host -= _phase_ms(open_phases, "host_fetch")
        device -= (
            _phase_ms(open_phases, "assemble")
            + _phase_ms(open_phases, "h2d_transfer")
            + _phase_ms(open_phases, "device_compute")
        )
    if host >= device:
        return (
            CAUSE_INPUT_BOUND,
            f"host_fetch grew {host:.1f}ms vs {device:.1f}ms on the "
            "device path across the window",
        )
    return (
        CAUSE_COMPUTE_BOUND,
        f"device path grew {device:.1f}ms vs {host:.1f}ms host_fetch "
        "across the window",
    )


class IncidentManager:
    """Groups violations into incidents and writes the postmortems.

    ``context_fn`` (optional) snapshots the master's correlatable state
    — ``{"anatomy": phase_stats_totals, "memory": ..., "rpc": ...}`` —
    at open and close; ``telemetry_dir`` locates the event/span logs
    for the timeline (empty = in-memory only, the fleetsim mode)."""

    def __init__(
        self,
        telemetry_dir: str = "",
        emit=None,
        clock=time.monotonic,
        context_fn=None,
        lookback_secs: float = DEFAULT_LOOKBACK_SECS,
        classify_fn=None,
    ):
        self._dir = telemetry_dir or ""
        self._emit = emit
        self._clock = clock
        self._context_fn = context_fn
        # cause-classification seam: the training plane's rule set is
        # the default; the serving watchdog swaps in its own (same
        # signature) so serving incidents speak queue-bound /
        # replica-down, not input-bound
        self._classify_fn = classify_fn or classify_cause
        self._lookback_secs = float(lookback_secs)
        self._seq = 0
        self._open: dict | None = None
        self.total_count = 0
        self.closed: list[dict] = []

    @property
    def open_count(self) -> int:
        return 1 if self._open is not None else 0

    @property
    def open_incident(self) -> dict | None:
        return self._open

    def _safe_emit(self, event: str, **fields):
        if self._emit is None:
            return
        try:
            self._emit(event, **fields)
        except Exception:  # noqa: BLE001 — telemetry never raises
            pass

    def _snapshot_context(self) -> dict | None:
        if self._context_fn is None:
            return None
        try:
            return self._context_fn()
        except Exception:  # noqa: BLE001 — a broken snapshot must not
            # kill detection
            return None

    # ---- engine callbacks ---------------------------------------------------

    def on_violation(self, transition: dict, now: float):
        if self._open is not None:
            # the episode is already open: this objective joins it
            self._open["violations"].append(dict(transition))
            return
        self._seq += 1
        self.total_count += 1
        self._open = {
            "incident": self._seq,
            "opened_at": now,
            "onset_at": transition.get("bad_since") or now,
            "violations": [dict(transition)],
            "recoveries": [],
            "context_open": self._snapshot_context(),
            "profile_windows": [],
        }
        self._safe_emit(
            EVENT_INCIDENT_OPEN,
            incident=self._seq,
            objective=transition.get("objective"),
            signal=transition.get("signal"),
            value=transition.get("value"),
        )

    def on_recovery(self, transition: dict, now: float, all_clear: bool):
        if self._open is None:
            return
        self._open["recoveries"].append(dict(transition))
        if all_clear:
            self._close(now)

    def note_profile_window(self, window: dict | None):
        """Attach an auto-armed profiler window ({"window_id", ...}) to
        the open incident so the postmortem points at the capture."""
        if self._open is not None and window:
            self._open["profile_windows"].append(dict(window))

    # ---- close + correlation ------------------------------------------------

    def _window_records(
        self, start: float, end: float
    ) -> tuple[list[dict], list[dict]]:
        """Events and spans whose monotonic stamps overlap the window.
        File reads happen only here — at close, off every hot path —
        and only when a telemetry dir exists."""
        if not self._dir:
            return [], []
        events = []
        try:
            for record in read_events(
                os.path.join(self._dir, EVENTS_FILENAME)
            ):
                t = record.get("monotonic")
                if isinstance(t, (int, float)) and start <= t <= end:
                    events.append(record)
        except Exception:  # noqa: BLE001 — a torn log yields a thinner
            # timeline, never a crash
            pass
        spans = []
        try:
            from elasticdl_tpu.telemetry.tracing import SPANS_FILENAME

            for record in read_jsonl(
                os.path.join(self._dir, SPANS_FILENAME)
            ):
                s, e = record.get("start"), record.get("end")
                if (
                    isinstance(s, (int, float))
                    and isinstance(e, (int, float))
                    and e >= start
                    and s <= end
                ):
                    spans.append(record)
        except Exception:  # noqa: BLE001
            pass
        return events, spans

    def _build_timeline(
        self,
        incident: dict,
        events: list[dict],
        spans: list[dict],
        closed_at: float,
    ) -> list[dict]:
        timeline: list[dict] = []
        for violation in incident["violations"]:
            timeline.append(
                {
                    "t": violation.get("at"),
                    "kind": "slo",
                    "name": "slo_violation",
                    "detail": {
                        "objective": violation.get("objective"),
                        "signal": violation.get("signal"),
                        "value": violation.get("value"),
                        "threshold": violation.get("threshold"),
                    },
                }
            )
        for recovery in incident["recoveries"]:
            timeline.append(
                {
                    "t": recovery.get("at"),
                    "kind": "slo",
                    "name": "slo_recovered",
                    "detail": {"objective": recovery.get("objective")},
                }
            )
        for window in incident["profile_windows"]:
            timeline.append(
                {
                    "t": window.get("at", incident["opened_at"]),
                    "kind": "profile",
                    "name": "profile_window_armed",
                    "detail": {"window_id": window.get("window_id")},
                }
            )
        for record in events:
            name = record.get("event")
            if name in ("slo_violation", "slo_recovered"):
                continue  # already represented from in-memory state
            detail = {
                k: v
                for k, v in record.items()
                if k not in ("time", "monotonic", "event")
            }
            timeline.append(
                {
                    "t": record.get("monotonic"),
                    "kind": "event",
                    "name": name,
                    "detail": detail,
                }
            )
        for record in spans:
            timeline.append(
                {
                    "t": record.get("start"),
                    "kind": "span",
                    "name": record.get("name"),
                    "detail": {
                        "duration_secs": (
                            record.get("end", 0) - record.get("start", 0)
                        )
                    },
                }
            )
        timeline.sort(
            key=lambda entry: (
                entry["t"] if isinstance(entry["t"], (int, float)) else 0.0
            )
        )
        if len(timeline) > _TIMELINE_CAP:
            # keep the edges: onset context and the close are the
            # causal story; the middle of a long burn is repetition
            head = timeline[: _TIMELINE_CAP // 2]
            tail = timeline[-(_TIMELINE_CAP - len(head)) :]
            dropped = len(timeline) - len(head) - len(tail)
            timeline = (
                head
                + [
                    {
                        "t": None,
                        "kind": "elided",
                        "name": "timeline_elided",
                        "detail": {"dropped": dropped},
                    }
                ]
                + tail
            )
        return timeline

    def _close(self, now: float):
        incident = self._open
        self._open = None
        if incident is None:
            return
        context_close = self._snapshot_context()
        start = incident["onset_at"] - self._lookback_secs
        events, spans = self._window_records(start, now)
        cause, rationale = self._classify_fn(
            incident["violations"],
            incident["context_open"],
            context_close,
            events,
        )
        record = {
            "incident": incident["incident"],
            "opened_at": incident["opened_at"],
            "onset_at": incident["onset_at"],
            "closed_at": now,
            "duration_secs": now - incident["onset_at"],
            "objectives": sorted(
                {
                    v.get("objective")
                    for v in incident["violations"]
                    if v.get("objective")
                }
            ),
            "violations": incident["violations"],
            "recoveries": incident["recoveries"],
            "suspected_cause": cause,
            "rationale": rationale,
            "profile_windows": incident["profile_windows"],
            "context_open": incident["context_open"],
            "context_close": context_close,
            "timeline": self._build_timeline(incident, events, spans, now),
        }
        self.closed.append(record)
        path = self._write_artifact(record)
        self._safe_emit(
            EVENT_INCIDENT_CLOSE,
            incident=record["incident"],
            suspected_cause=cause,
            duration_secs=record["duration_secs"],
            objectives=record["objectives"],
            artifact=path or "",
        )

    def _write_artifact(self, record: dict) -> str | None:
        if not self._dir:
            return None
        try:
            incidents_dir = os.path.join(self._dir, INCIDENTS_DIRNAME)
            os.makedirs(incidents_dir, exist_ok=True)
            path = os.path.join(
                incidents_dir, f"incident_{record['incident']}.json"
            )
            with open(path, "w", encoding="utf-8") as f:
                json.dump(record, f, indent=1, sort_keys=True, default=str)
            return path
        except OSError:
            return None


def read_incidents(run_dir: str) -> list[dict]:
    """All incident artifacts under ``run_dir`` (any depth — report
    callers hand the run root, artifacts live under per-run
    ``incidents/`` dirs), ordered by (path, incident number)."""
    found: list[tuple[str, int, dict]] = []
    for dirpath, _dirnames, filenames in os.walk(run_dir):
        if os.path.basename(dirpath) != INCIDENTS_DIRNAME:
            continue
        for filename in sorted(filenames):
            if not (
                filename.startswith("incident_")
                and filename.endswith(".json")
            ):
                continue
            path = os.path.join(dirpath, filename)
            try:
                with open(path, encoding="utf-8") as f:
                    record = json.load(f)
            except (OSError, ValueError):
                continue
            record["_path"] = os.path.relpath(path, run_dir)
            found.append(
                (dirpath, int(record.get("incident", 0)), record)
            )
    return [record for _d, _n, record in sorted(found, key=lambda x: x[:2])]
