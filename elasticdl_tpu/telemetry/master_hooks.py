"""Master-side telemetry: one object owning the registry + event log.

Wired by :class:`~elasticdl_tpu.master.master.Master` as a
``TaskDispatcher`` observer, a servicer version observer and the re-form
path's direct collaborator, so the elastic lifecycle is measured with NO
new plumbing through the hot loop — the observers the chaos checker
already rides (PR 1) are the same ones telemetry rides.

Registry refresh happens at scrape time via a collect callback (queue
depths, epoch, live workers, the workers' ``time_<bucket>_ms`` wall
clock buckets mirrored from the dispatcher's exec-counter sums), so the
run loop pays nothing for ``/metrics`` being up.
"""

from __future__ import annotations

import os
import time

from elasticdl_tpu.telemetry.events import (
    EVENT_JOB_END,
    EVENT_JOB_START,
    EVENT_REFORM_COMPLETE,
    EVENT_REFORM_LATENCY,
    EVENT_REFORM_START,
    EVENT_TASK_DISPATCH,
    EVENT_TASK_DONE,
    EVENT_TASK_RECOVERED,
    EVENT_WORKER_DEAD,
    EVENTS_FILENAME,
    EventLog,
)
from elasticdl_tpu.telemetry.registry import MetricsRegistry
from elasticdl_tpu.telemetry.tracing import (
    SPAN_REFORM,
    SPAN_TASK_LIFECYCLE,
    SPANS_FILENAME,
    SpanRecorder,
    gen_trace_id,
    sample_rate_from_env,
)

# family names referenced from more than one code path live here so each
# is REGISTERED at exactly one call site (scripts/check_telemetry_names.py)
_TASKS_DISPATCHED = "elasticdl_tasks_dispatched_total"
_TASKS_COMPLETED = "elasticdl_tasks_completed_total"
_WORKER_TIME_MS = "elasticdl_worker_time_ms_total"
_WORKER_HB_AGE = "elasticdl_worker_heartbeat_age_secs"
_MEMORY_BYTES = "elasticdl_memory_bytes"

# per-worker label-cardinality budget for /metrics: a fleet at or under
# this size exposes one heartbeat-age series per worker; above it the
# individual series collapse into aggregate children (worker="max" /
# worker="p50") so a 1000-worker scrape renders O(1) series for this
# family instead of O(world_size).  The env override exists for
# deployments whose scrape budget differs from the default.
WORKER_SERIES_MAX_ENV = "ELASTICDL_TPU_WORKER_SERIES_MAX"
DEFAULT_WORKER_SERIES_MAX = 64


def worker_series_budget() -> int:
    raw = os.environ.get(WORKER_SERIES_MAX_ENV, "")
    try:
        return int(raw) if raw else DEFAULT_WORKER_SERIES_MAX
    except ValueError:
        return DEFAULT_WORKER_SERIES_MAX


class MasterTelemetry:
    def __init__(
        self,
        telemetry_dir: str = "",
        registry=None,
        trace_sample_rate: float | None = None,
    ):
        self.registry = registry if registry is not None else MetricsRegistry()
        # async: master emits happen inside TaskDispatcher observer
        # callbacks (under the dispatcher lock) — the control plane must
        # never queue worker RPCs behind a disk write
        self.events = EventLog(
            os.path.join(telemetry_dir, EVENTS_FILENAME)
            if telemetry_dir
            else "",
            async_writes=True,
        )
        # span tracer: buffered in memory (the observer callbacks run
        # under the dispatcher lock, so spans batch to disk, never write
        # inline); path='' disables persistence but keeps the object
        # usable so the reform path never branches
        self.tracer = SpanRecorder(
            os.path.join(telemetry_dir, SPANS_FILENAME)
            if telemetry_dir
            else "",
            role="master",
            sample_rate=trace_sample_rate
            if trace_sample_rate is not None
            else sample_rate_from_env(),
        )
        # task_id -> open dispatch (root) span; id(task) -> the latest
        # root span's context so a RECOVERED task's new span links back
        # into the original trace (the re-queued Task object survives
        # the re-lease, so identity is stable while the task is alive)
        self._task_spans: dict[int, object] = {}
        self._task_trace_links: dict[int, dict] = {}
        r = self.registry

        def per_type(name, help_text):
            # pre-create the training child so every family is visible
            # on /metrics from the first scrape, before any task flows
            return r.counter(name, help_text, labels={"type": "training"})

        per_type(_TASKS_DISPATCHED, "Task leases handed to workers")
        per_type(_TASKS_COMPLETED, "Tasks reported successfully")
        self._tasks_recovered = r.counter(
            "elasticdl_tasks_recovered_total",
            "Tasks re-queued after failure, lease timeout or worker death",
        )
        self._records = r.counter(
            "elasticdl_records_processed_total",
            "Records covered by successfully completed tasks",
        )
        self._model_version = r.gauge(
            "elasticdl_model_version", "Highest model version reported"
        )
        self._generation = r.gauge(
            "elasticdl_cluster_generation",
            "World generation (bumped by every re-formation)",
        )
        self._workers_live = r.gauge(
            "elasticdl_workers_live", "Workers with a live heartbeat"
        )
        self._workers_dead = r.counter(
            "elasticdl_workers_dead_total",
            "Workers declared dead (heartbeat miss or process exit)",
        )
        self._reforms = r.counter(
            "elasticdl_reforms_total", "World re-formations"
        )
        self._reform_downtime = r.histogram(
            "elasticdl_reform_downtime_seconds",
            "Failure detection to first step-task pull of the new world",
        )
        self._tasks_pending = r.gauge(
            "elasticdl_tasks_pending", "Tasks queued, not leased"
        )
        self._tasks_active = r.gauge(
            "elasticdl_tasks_active", "Tasks currently leased"
        )
        self._epoch = r.gauge("elasticdl_epoch", "Current training epoch")
        # shape-canonical batching's regression gauge: XLA programs
        # compiled (this process + worker-reported exec-counter deltas);
        # steady state should be flat after warmup — see
        # telemetry/compile_tracker.py and scripts/compile_smoke.py
        self._compiles = r.counter(
            "elasticdl_compile_total",
            "XLA backend compiles (master process + worker-reported)",
        )
        # gray-failure RPC plane (rpc/stats.py ships the worker-side
        # totals by heartbeat; the dedup counters are master-observed)
        self._rpc_retries = r.counter(
            "elasticdl_rpc_retries_total",
            "Worker RPC backoff re-sends (heartbeat-shipped totals)",
        )
        self._rpc_deadline_exceeded = r.counter(
            "elasticdl_rpc_deadline_exceeded_total",
            "Worker RPC attempts that expired their deadline",
        )
        self._rpc_unavailable = r.counter(
            "elasticdl_rpc_unavailable_total",
            "Worker RPC attempts that failed UNAVAILABLE",
        )
        self._rpc_reports_deduped = r.counter(
            "elasticdl_rpc_reports_deduped_total",
            "Task reports dropped by task-id dedup (duplicate delivery "
            "or stale lease)",
        )
        self._rpc_eval_deduped = r.counter(
            "elasticdl_rpc_eval_reports_deduped_total",
            "Eval-metric reports dropped as duplicate deliveries of a "
            "still-active lease",
        )
        # per-method server-side handler latency; children created
        # lazily per observed method (one family, one registration site)
        self._rpc_latency_children: dict = {}
        from elasticdl_tpu.telemetry import compile_tracker

        compile_tracker.install()
        self._compile_tracker = compile_tracker
        # master-side memory ledger: samples at reform edges + scrape
        # time; its components (master journal buffers) fold into the
        # same elasticdl_memory_bytes family the heartbeat-fed worker
        # components land in.  Enabled exactly when telemetry is
        from elasticdl_tpu.telemetry import memory as memory_mod

        self._memory_mod = memory_mod
        memory_mod.install_if_enabled(telemetry_dir, emit=self.events.emit)

        self._task_d = None
        self._servicer = None
        self._tb_service = None
        self._tb_mirrored_version = -1
        self._reform_span = None
        # last (source, trained) watermark pair emitted, so an idle
        # stream's poll ticks do not flood the event log
        self._last_stream_emit: tuple | None = None
        # the SLO watchdog engine, when --slo_config armed one (set by
        # the master via set_slo_engine; None = plane off, and every
        # surface below skips it so behavior is byte-identical)
        self.slo_engine = None
        r.add_collect_callback(self._collect)

    # ---- wiring ------------------------------------------------------------

    def set_slo_engine(self, engine):
        """Hook the armed SLO engine into the scrape mirror and the
        /healthz ``slo`` block."""
        self.slo_engine = engine

    def attach(self, task_dispatcher, servicer, tb_service=None):
        self._task_d = task_dispatcher
        self._servicer = servicer
        self._tb_service = tb_service
        task_dispatcher.add_observer(self)
        servicer.add_version_observer(self.on_version_report)
        servicer.set_event_sink(self.events.emit)
        servicer.set_trace_provider(self.trace_for_task)
        # per-method handler latency rides the transport's server seam
        # (module-global observer: the latest attached master wins,
        # which is exactly the in-process-harness sequencing)
        from elasticdl_tpu.rpc import service as rpc_service

        rpc_service.set_server_rpc_observer(self.observe_rpc)

    def observe_rpc(self, method: str, seconds: float):
        """Server-seam hook: one handler execution of ``method``."""
        hist = self._rpc_latency_children.get(method)
        if hist is None:
            hist = self.registry.histogram(
                "elasticdl_rpc_latency_seconds",
                "Server-side RPC handler latency by method",
                labels={"method": method},
            )
            self._rpc_latency_children[method] = hist
        hist.observe(seconds)

    def trace_for_task(self, task_id: int) -> dict:
        """The dispatch span's trace context for an active lease — what
        the servicer stamps onto the TaskResponse."""
        span = self._task_spans.get(task_id)
        return span.context if span is not None else {}

    def _collect(self, _registry):
        """Scrape-time refresh of point-in-time values."""
        compiles = self._compile_tracker.compile_count()
        if self._task_d is not None:
            snap = self._task_d.snapshot()
            self._tasks_pending.set(snap["pending"] + snap["pending_eval"])
            self._tasks_active.set(len(snap["active"]))
            self._epoch.set(snap["epoch"])
            from elasticdl_tpu.telemetry.compile_tracker import (
                COMPILE_COUNT_KEY,
            )
            from elasticdl_tpu.utils.constants import TaskType

            # workers ship compile deltas with EVERY report kind, so the
            # mirror sums the exec counters of all task types (keeping
            # the TRAINING snapshot for the time buckets below — one
            # dispatcher-lock copy per type per scrape)
            exec_metrics = {}
            for task_type in TaskType:
                snapshot = self._task_d.exec_metrics_snapshot(task_type)
                compiles += snapshot.get(COMPILE_COUNT_KEY, 0)
                if task_type == TaskType.TRAINING:
                    exec_metrics = snapshot
            for key, value in exec_metrics.items():
                if key.startswith("time_") and key.endswith("_ms"):
                    self.registry.counter(
                        _WORKER_TIME_MS,
                        "Worker wall-clock buckets (utils.timing_utils)",
                        labels={"bucket": key[len("time_") : -len("_ms")]},
                    ).set_total(value)
            # streaming (watermark-lease) backlog signal — the one
            # registration site of the elasticdl_stream_{lag,watermark}
            # gauges; absent entirely in epoch mode
            if getattr(self._task_d, "streaming", False):
                status = self._task_d.stream_status()
                if status is not None:
                    self.registry.gauge(
                        "elasticdl_stream_lag_records",
                        "Streaming backlog: source watermark minus "
                        "trained watermark, in records",
                    ).set(status["lag"])
                    for role in ("source", "trained"):
                        self.registry.gauge(
                            "elasticdl_stream_watermark",
                            "Stream watermarks by role (source=records "
                            "published, trained=gap-free trained prefix)",
                            labels={"role": role},
                        ).set(status[f"{role}_watermark"])
        # set_total is monotone (max), so a re-formed generation's fresh
        # per-process counters can never walk the exposed total backward
        self._compiles.set_total(compiles)
        if self._servicer is not None:
            self._workers_live.set(len(self._servicer.live_workers()))
            self._generation.set(self._servicer.cluster_version)
            # heartbeat-shipped worker RPC outcomes + the servicer's own
            # eval dedup drops (set_total: mirrored monotone aggregates)
            totals = getattr(
                self._servicer, "rpc_stats_totals", lambda: {}
            )()
            self._rpc_retries.set_total(totals.get("retries", 0))
            self._rpc_deadline_exceeded.set_total(
                totals.get("deadline_exceeded", 0)
            )
            self._rpc_unavailable.set_total(totals.get("unavailable", 0))
            self._rpc_eval_deduped.set_total(
                getattr(self._servicer, "duplicate_eval_drops", 0)
            )
            # step-anatomy phase totals (heartbeat-shipped,
            # telemetry/anatomy.py): a monotone ms counter AND a
            # mirrored log-bucket histogram per phase — the one
            # registration site of the elasticdl_step_phase_* families
            phase_totals = getattr(
                self._servicer, "phase_stats_totals", lambda: {}
            )()
            for phase, agg in phase_totals.items():
                self.registry.counter(
                    "elasticdl_step_phase_ms_total",
                    "Per-dispatch phase wall time by phase "
                    "(host_fetch/assemble/h2d_transfer/device_compute/"
                    "step_bookkeeping/untracked)",
                    labels={"phase": phase},
                ).set_total(agg.get("ms", 0.0))
                self.registry.histogram(
                    "elasticdl_step_phase_seconds",
                    "Per-dispatch phase duration distribution by phase",
                    labels={"phase": phase},
                ).set_totals(
                    agg.get("buckets", {}),
                    agg.get("ms", 0.0) / 1000.0,
                    agg.get("count", 0),
                )
            # device-prefetch staging totals (heartbeat-shipped,
            # trainer/device_pipeline.py): the one registration site of
            # the elasticdl_device_prefetch_* counters
            # heartbeat fan-in shape (coalesced drain batches) and the
            # incremental dead-worker sweep cost: the control-plane
            # scale counters the fleetsim budgets gate
            hb = getattr(self._servicer, "heartbeat_stats", lambda: {})()
            if hb:
                self.registry.counter(
                    "elasticdl_heartbeats_total",
                    "Heartbeats applied by the coalesced fan-in",
                ).set_total(hb.get("beats", 0))
                self.registry.counter(
                    "elasticdl_heartbeat_batches_total",
                    "Drain batches (one lock acquisition each)",
                ).set_total(hb.get("batches", 0))
                self.registry.gauge(
                    "elasticdl_heartbeat_batch_max",
                    "Largest heartbeat batch applied in one drain",
                ).set(hb.get("max_batch", 0))
            sweep = getattr(self._servicer, "sweep_stats", lambda: {})()
            if sweep:
                self.registry.counter(
                    "elasticdl_dead_worker_sweeps_total",
                    "Incremental dead-worker sweep invocations",
                ).set_total(sweep.get("count", 0))
                self.registry.counter(
                    "elasticdl_dead_worker_sweep_ms_total",
                    "Cumulative dead-worker sweep wall time",
                ).set_total(sweep.get("ms", 0.0))
                self.registry.gauge(
                    "elasticdl_dead_worker_sweep_max_ms",
                    "Slowest single dead-worker sweep",
                ).set(sweep.get("max_ms", 0.0))
            self._collect_worker_ages()
            self._collect_memory()
            prefetch_totals = getattr(
                self._servicer, "prefetch_stats_totals", lambda: {}
            )()
            if prefetch_totals:
                self.registry.counter(
                    "elasticdl_device_prefetch_groups_total",
                    "Dispatch groups staged onto device by the "
                    "prefetch thread",
                ).set_total(prefetch_totals.get("groups", 0))
                self.registry.counter(
                    "elasticdl_device_prefetch_stall_ms_total",
                    "Consumer-visible wait for a staged group (the "
                    "residual h2d stall after overlap)",
                ).set_total(prefetch_totals.get("stall_ms", 0))
                self.registry.counter(
                    "elasticdl_device_prefetch_stage_ms_total",
                    "Background staging time overlapped with device "
                    "compute",
                ).set_total(prefetch_totals.get("stage_ms", 0))
                self.registry.counter(
                    "elasticdl_boundary_stall_ms_total",
                    "Device-idle time between the last retire of one "
                    "task and the first dispatch of the next",
                ).set_total(prefetch_totals.get("boundary_stall_ms", 0))
        if self.slo_engine is not None:
            # scrape-time mirror of the watchdog's detector state onto
            # the elasticdl_slo_* families (registered inside the
            # engine — the one registration site of each)
            self.slo_engine.mirror_metrics(self.registry)

    def _collect_worker_ages(self):
        """Per-worker heartbeat-age series, cardinality-bounded.

        At or under the series budget every worker gets its own labeled
        gauge (the small-fleet debugging view); above it the family
        collapses to aggregate-above-threshold children — worker="max"
        and worker="p50" — so scrape cost for this family is O(1) at
        any world size.  Stale children (forgotten workers, or the
        whole individual set after crossing the budget) are pruned so
        the exposition never accumulates dead series."""
        ages = getattr(self._servicer, "heartbeat_ages", lambda: {})()
        if len(ages) <= worker_series_budget():
            series = {str(wid): age for wid, age in ages.items()}
        else:
            ordered = sorted(ages.values())
            series = {
                "max": ordered[-1],
                "p50": ordered[len(ordered) // 2],
            }
        self.registry.prune_children(
            _WORKER_HB_AGE, [{"worker": key} for key in series]
        )
        for key, value in series.items():
            self.registry.gauge(
                "elasticdl_worker_heartbeat_age_secs",
                "Seconds since each worker's last heartbeat (per-worker "
                "under the series budget, aggregate max/p50 above it)",
                labels={"worker": key},
            ).set(value)

    def _collect_memory(self):
        """Mirror the memory ledger onto ``elasticdl_memory_bytes
        {component=, kind=current|peak}``: the heartbeat-fed fleet
        aggregates (last-writer-wins currents, max-merged peaks) plus
        this process's own ledger components (master journal buffers).

        Cardinality-bounded like the per-worker age series: component
        names arrive over the wire (untrusted), so above the series
        budget the smallest components collapse into ``component=
        "other"`` and stale children are pruned."""
        totals = getattr(
            self._servicer, "memory_stats_totals", lambda: {}
        )()
        current = dict((totals or {}).get("current") or {})
        peak = dict((totals or {}).get("peak") or {})
        ledger = self._memory_mod.get_ledger()
        if ledger is not None:
            # sample at scrape time so the journal-buffer reading (and
            # master RSS) is fresh without any run-loop bookkeeping
            ledger.sample("scrape")
            own = ledger.snapshot()
            for key, value in own["current"].items():
                current[key] = current.get(key, 0) + value
            for key, value in own["peak"].items():
                peak[key] = peak.get(key, 0) + value
        if not current and not peak:
            return
        budget = worker_series_budget()

        def bounded(values: dict) -> dict:
            if len(values) <= budget:
                return dict(values)
            ordered = sorted(
                values.items(), key=lambda kv: (-kv[1], kv[0])
            )
            kept = dict(ordered[: budget - 1])
            # ADD into the collapse bucket (never assign): component
            # names arrive over the wire, so a real component that is
            # literally named "other" and ranked in the kept set must
            # not have its value overwritten by the tail aggregate
            kept["other"] = kept.get("other", 0) + sum(
                v for _k, v in ordered[budget - 1 :]
            )
            return kept

        current = bounded(current)
        peak = bounded(peak)
        keep = [
            {"component": name, "kind": "current"} for name in current
        ] + [{"component": name, "kind": "peak"} for name in peak]
        self.registry.prune_children(_MEMORY_BYTES, keep)
        for kind, values in (("current", current), ("peak", peak)):
            for name, value in values.items():
                # the literal (not _MEMORY_BYTES) is the telemetry-names
                # checker's registration site; it must match the
                # constant the prune call above targets
                self.registry.gauge(
                    "elasticdl_memory_bytes",
                    "Component-level memory ledger (host/HBM bytes by "
                    "registered owner; kind=current is last-writer-"
                    "wins across beats, kind=peak is the monotone "
                    "watermark)",
                    labels={"component": name, "kind": kind},
                ).set(value)

    def build_health_fn(self, job_type: str, instance_manager_fn=lambda: None):
        """The ``/healthz`` payload closure (also used directly by
        tests): generation, live workers, model version, quiesce."""
        servicer = self._servicer

        def health() -> dict:
            im = instance_manager_fn()
            live = (
                im.worker_ids()
                if im is not None
                else (servicer.live_workers() if servicer else [])
            )
            quiescing = bool(servicer and servicer.is_quiescing)
            # progress-vs-liveness split: a hung-but-alive job keeps
            # heartbeating (live_workers stays full) while
            # last_step_age_secs grows without bound; degraded_network
            # says whether PR-8's outage-class RPC counters moved
            # recently — together they tell "stuck" from "slow link"
            # from "progressing" without reading the event log
            step_age = (
                servicer.last_step_age_secs()
                if servicer is not None
                and hasattr(servicer, "last_step_age_secs")
                else None
            )
            # memory headroom: the master host's point-in-time RSS and
            # availability (telemetry/memory.py; None-safe off-Linux),
            # plus the fleet's tracked byte total when the servicer
            # carries heartbeat-fed ledger aggregates
            from elasticdl_tpu.telemetry.memory import (
                KEY_DEVICE_IN_USE,
                KEY_HOST_RSS,
                host_memory_health,
            )

            memory = host_memory_health()
            if servicer is not None and hasattr(
                servicer, "memory_stats_totals"
            ):
                totals = servicer.memory_stats_totals()
                # tracked COMPONENTS only: the wire map also carries the
                # host_rss/device pseudo-keys, and summing those in
                # would double-count each worker's entire RSS on top of
                # the components it contains
                memory["fleet_tracked_bytes"] = sum(
                    value
                    for key, value in (
                        totals.get("current") or {}
                    ).items()
                    if key not in (KEY_HOST_RSS, KEY_DEVICE_IN_USE)
                )
            payload = {
                "status": "quiescing" if quiescing else "ok",
                "job_type": job_type,
                "generation": servicer.cluster_version if servicer else 0,
                "model_version": (
                    servicer.get_model_version() if servicer else 0
                ),
                "live_workers": sorted(live),
                "num_live_workers": len(live),
                "quiescing": quiescing,
                "last_step_age_secs": round(step_age, 3)
                if step_age is not None
                else None,
                "degraded_network": bool(
                    servicer is not None
                    and hasattr(servicer, "network_degraded")
                    and servicer.network_degraded()
                ),
                "memory": memory,
            }
            # the slo block appears only when the watchdog is armed —
            # an unarmed master's payload stays byte-identical
            if self.slo_engine is not None:
                payload["slo"] = self.slo_engine.health_block()
            return payload

        return health

    # ---- TaskDispatcher observer -------------------------------------------

    def on_task_leased(self, task_id, worker_id, task):
        type_name = task.type.name.lower()
        self.registry.counter(
            _TASKS_DISPATCHED, labels={"type": type_name}
        ).inc()
        # one task = one trace.  First lease opens a fresh root trace; a
        # RE-lease (failure/timeout/worker-death recovery) opens a new
        # root span INSIDE the original trace, parented to the previous
        # attempt's span — the Dapper link that lets `trace analyze`
        # follow a task across a preemption.
        link = self._task_trace_links.get(id(task))
        span = self.tracer.start_span(
            SPAN_TASK_LIFECYCLE,
            trace_ctx=link
            if link is not None
            else {"trace_id": gen_trace_id(), "span_id": ""},
            task_id=task_id,
            worker_id=worker_id,
            type=type_name,
            shard=task.shard_name,
            recovered=link is not None,
        )
        self._task_spans[task_id] = span
        self._task_trace_links[id(task)] = span.context
        self.events.emit(
            EVENT_TASK_DISPATCH,
            task_id=task_id,
            worker_id=worker_id,
            type=type_name,
            shard=task.shard_name,
            records=task.num_records,
            trace_id=span.trace_id,
        )

    def on_task_done(
        self, task_id, task, worker_id, success, exec_counters=None
    ):
        type_name = task.type.name.lower()
        span = self._task_spans.pop(task_id, None)
        if span is not None:
            span.end(success=bool(success))
        if success:
            # the trace is complete: drop the link so the (freed) Task
            # object's identity can never alias a future task's trace
            self._task_trace_links.pop(id(task), None)
            self.registry.counter(
                _TASKS_COMPLETED, labels={"type": type_name}
            ).inc()
            self._records.inc(task.num_records)
            self.events.emit(
                EVENT_TASK_DONE,
                task_id=task_id,
                worker_id=worker_id,
                type=type_name,
                records=task.num_records,
                **{
                    k: v
                    for k, v in (exec_counters or {}).items()
                    if k.startswith("time_")
                },
            )
        else:
            self._tasks_recovered.inc()
            self.events.emit(
                EVENT_TASK_RECOVERED,
                task_id=task_id,
                worker_id=worker_id,
                type=type_name,
                records=task.num_records,
                reason="report_failed",
            )

    def on_task_reported(self, task_id, task, success, counted):
        """Every report outcome, counted or not: a ``counted=False``
        report is a drop by the dispatcher's task-id dedup — a
        duplicate delivery or a stale (reclaimed) lease — the counter
        the duplicate-safety contract is observable through."""
        if not counted:
            self._rpc_reports_deduped.inc()

    def on_task_reclaimed(self, task_id, task):
        span = self._task_spans.pop(task_id, None)
        if span is not None:
            span.end(success=False, reclaimed=True)
        self._tasks_recovered.inc()
        self.events.emit(
            EVENT_TASK_RECOVERED,
            task_id=task_id,
            type=task.type.name.lower(),
            records=task.num_records,
            reason="lease_timeout",
        )

    # ---- servicer / master lifecycle ---------------------------------------

    def on_version_report(self, worker_id, model_version):
        if model_version <= self._model_version.value:
            return
        self._model_version.set(model_version)
        if self._tb_service is not None and (
            model_version > self._tb_mirrored_version
        ):
            # registry scalars mirrored so TB (and metrics.jsonl) keeps
            # carrying the run's health timeline unchanged
            self._tb_mirrored_version = model_version
            self._tb_service.write_dict_to_summary(
                {
                    "telemetry/model_version": model_version,
                    "telemetry/workers_live": self._workers_live.value,
                    "telemetry/records_processed": self._records.value,
                    "telemetry/reforms": self._reforms.value,
                },
                model_version,
            )

    def job_start(self, job_type: str, num_workers: int):
        self.events.emit(
            EVENT_JOB_START, job_type=job_type, num_workers=num_workers
        )

    def job_end(self, rc: int):
        self.events.emit(EVENT_JOB_END, rc=rc)
        self.events.flush()
        self.tracer.flush()

    def worker_dead(self, worker_ids, generation: int):
        self._workers_dead.inc(len(worker_ids))
        for worker_id in worker_ids:
            self.events.emit(
                EVENT_WORKER_DEAD, worker_id=worker_id, generation=generation
            )

    def reform_start(self, generation, dead, reason, old_world_size):
        self._generation.set(generation)
        # phase-edge memory sample: a re-formation is where harvested
        # replica payloads and restore stages spike master RSS
        self._memory_mod.sample("reform")
        # every re-formation is one trace: the root span opens here, the
        # fence/relaunch child spans bracket the phases in
        # Master._reform_lockstep, and the relaunched workers' world_join
        # spans link in via the propagated context (reform_trace_context)
        self._reform_span = self.tracer.start_span(
            SPAN_REFORM,
            trace_ctx={"trace_id": gen_trace_id(), "span_id": ""},
            generation=generation,
            reason=reason,
            dead_workers=sorted(dead),
        )
        self.events.emit(
            EVENT_REFORM_START,
            generation=generation,
            dead_workers=sorted(dead),
            reason=reason,
            old_world_size=old_world_size,
            trace_id=self._reform_span.trace_id,
        )

    def reform_trace_context(self) -> dict:
        """The open re-formation's trace context ({} outside a reform)."""
        span = self._reform_span
        return span.context if span is not None else {}

    def reform_complete(self, generation, old_world_size, new_world_size):
        self._reforms.inc()
        self._memory_mod.sample("reform")
        span, self._reform_span = self._reform_span, None
        if span is not None:
            span.end(new_world_size=new_world_size)
        self.events.emit(
            EVENT_REFORM_COMPLETE,
            generation=generation,
            old_world_size=old_world_size,
            new_world_size=new_world_size,
        )

    def reform_failed(self, generation):
        """The relaunch gave up (reform budget exhausted): close the
        reform trace with the failure recorded."""
        span, self._reform_span = self._reform_span, None
        if span is not None:
            span.end(failed=True)
        self.tracer.flush()

    def master_restart(self, generation: int):
        """The master process is starting RESTORED from the control-plane
        journal (master high availability).  Emitted at restore START so
        the event's timestamp marks the end of the master-down phase in
        downtime attribution."""
        from elasticdl_tpu.telemetry.events import EVENT_MASTER_RESTART

        self.events.emit(EVENT_MASTER_RESTART, generation=generation)

    def journal_replay(
        self,
        generation: int,
        duration_secs: float,
        pending: int,
        active: int,
        epoch: int,
        stage_lost: bool = False,
    ):
        """Journal replay finished; ``duration_secs`` lets event-only
        consumers (telemetry.report) reconstruct the replay interval
        without reading the span log.  ``stage_lost`` marks a staged
        replica set that died with the previous master's RAM."""
        from elasticdl_tpu.telemetry.events import EVENT_JOURNAL_REPLAY

        self.events.emit(
            EVENT_JOURNAL_REPLAY,
            generation=generation,
            duration_secs=duration_secs,
            pending=pending,
            active=active,
            epoch=epoch,
            stage_lost=stage_lost,
        )

    def worker_rehome(
        self,
        worker_id: int,
        generation: int,
        kept: int,
        requeued: int,
        started_at: float,
    ):
        """One worker re-homed onto the restarted master (lease
        reconciliation outcome included)."""
        from elasticdl_tpu.telemetry.events import EVENT_WORKER_REHOME
        from elasticdl_tpu.telemetry.tracing import SPAN_WORKER_REHOME

        self.events.emit(
            EVENT_WORKER_REHOME,
            worker_id=worker_id,
            generation=generation,
            kept=kept,
            requeued=requeued,
        )
        self.tracer.record_span(
            SPAN_WORKER_REHOME,
            started_at,
            time.monotonic(),
            generation=generation,
            worker_id=worker_id,
            kept=kept,
            requeued=requeued,
        )

    def slice_loss(
        self,
        generation: int,
        lost_slices: list,
        dead_workers: list,
        old_slices: int,
        new_slices: int,
        parked: bool,
        started_at: float,
        trace_ctx: dict | None = None,
    ):
        """A whole slice's processes died (slice-granular reform): the
        span covers failure detection to the re-plan decision, inside
        the re-formation's trace."""
        from elasticdl_tpu.telemetry.events import EVENT_SLICE_LOSS
        from elasticdl_tpu.telemetry.tracing import SPAN_SLICE_LOSS

        self.events.emit(
            EVENT_SLICE_LOSS,
            generation=generation,
            lost_slices=list(lost_slices),
            dead_workers=list(dead_workers),
            old_slices=old_slices,
            new_slices=new_slices,
            parked=bool(parked),
        )
        self.tracer.record_span(
            SPAN_SLICE_LOSS,
            started_at,
            time.monotonic(),
            trace_ctx=trace_ctx,
            generation=generation,
            lost_slices=list(lost_slices),
            new_slices=new_slices,
            parked=bool(parked),
        )

    def mesh_resize(
        self,
        generation: int,
        old_world_size: int,
        new_world_size: int,
        old_slices: int,
        new_slices: int,
        dcn: dict | None,
        started_at: float,
        trace_ctx: dict | None = None,
    ):
        """The hybrid mesh was re-planned for a resized world (the dp
        axis grows/shrinks across the DCN slice dimension) — the span
        the multislice smoke gates on."""
        from elasticdl_tpu.telemetry.events import EVENT_MESH_RESIZE
        from elasticdl_tpu.telemetry.tracing import SPAN_MESH_RESIZE

        self.events.emit(
            EVENT_MESH_RESIZE,
            generation=generation,
            old_world_size=old_world_size,
            new_world_size=new_world_size,
            old_slices=old_slices,
            new_slices=new_slices,
            dcn=dict(dcn or {}),
        )
        self.tracer.record_span(
            SPAN_MESH_RESIZE,
            started_at,
            time.monotonic(),
            trace_ctx=trace_ctx,
            generation=generation,
            old_world_size=old_world_size,
            new_world_size=new_world_size,
            old_slices=old_slices,
            new_slices=new_slices,
        )
        self.tracer.flush()

    def autoscale_decision(
        self,
        generation: int,
        started_at: float,
        action: str,
        from_slices: int,
        to_slices: int,
        reason: str,
        p95_step_ms=None,
        backlog=None,
    ):
        """The autoscaler crossed an SLO and requested a resize."""
        from elasticdl_tpu.telemetry.events import EVENT_AUTOSCALE_DECISION
        from elasticdl_tpu.telemetry.tracing import SPAN_AUTOSCALE_DECISION

        self.events.emit(
            EVENT_AUTOSCALE_DECISION,
            generation=generation,
            action=action,
            from_slices=from_slices,
            to_slices=to_slices,
            reason=reason,
            p95_step_ms=p95_step_ms,
            backlog=backlog,
        )
        self.tracer.record_span(
            SPAN_AUTOSCALE_DECISION,
            started_at,
            time.monotonic(),
            generation=generation,
            action=action,
            from_slices=from_slices,
            to_slices=to_slices,
        )

    def stream_tick(self, status: dict):
        """Run-loop tick in watermark-lease mode: emit the watermark
        pair and the derived lag.  Deduped on the (source, trained)
        pair — a tick where neither watermark moved emits nothing, so
        an idle stream costs no event-log growth."""
        from elasticdl_tpu.telemetry.events import (
            EVENT_STREAM_LAG,
            EVENT_STREAM_WATERMARK,
        )

        key = (status["source_watermark"], status["trained_watermark"])
        if key == self._last_stream_emit:
            return
        self._last_stream_emit = key
        self.events.emit(
            EVENT_STREAM_WATERMARK,
            source_watermark=status["source_watermark"],
            trained_watermark=status["trained_watermark"],
            next_offset=status["next_offset"],
            closed=bool(status["closed"]),
        )
        self.events.emit(
            EVENT_STREAM_LAG,
            lag_records=status["lag"],
            source_watermark=status["source_watermark"],
            trained_watermark=status["trained_watermark"],
        )

    def live_push(
        self,
        *,
        model_version: int,
        trained_watermark: int,
        source_watermark: int,
        accepted: bool,
        replica: str,
        swap_ms: float,
        started_at: float,
        reason: str = "",
    ):
        """One live train->serve push: the freshness ledger's row.
        ``staleness`` is records the served model is behind the source
        at the moment of the swap."""
        from elasticdl_tpu.telemetry.events import EVENT_LIVE_PUSH
        from elasticdl_tpu.telemetry.tracing import SPAN_LIVE_PUSH

        self.registry.counter(
            "elasticdl_stream_live_push_total",
            "Live train->serve pushes (replica-ring commit fanned into "
            "serving swap_state_dicts); accepted= marks the stale-"
            "refused ones",
            labels={"accepted": "true" if accepted else "false"},
        ).inc()
        self.events.emit(
            EVENT_LIVE_PUSH,
            model_version=model_version,
            trained_watermark=trained_watermark,
            source_watermark=source_watermark,
            staleness=max(0, source_watermark - trained_watermark),
            accepted=bool(accepted),
            replica=replica,
            swap_ms=swap_ms,
            reason=reason,
        )
        self.tracer.record_span(
            SPAN_LIVE_PUSH,
            started_at,
            time.monotonic(),
            model_version=model_version,
            trained_watermark=trained_watermark,
            accepted=bool(accepted),
            replica=replica,
        )

    def replica_harvest(
        self, generation, complete: bool, version, sources: int
    ):
        """Reform-time replica harvest outcome (replication subsystem):
        ``complete=False`` means the new generation falls back to disk."""
        from elasticdl_tpu.telemetry.events import EVENT_REPLICA_HARVEST

        self.events.emit(
            EVENT_REPLICA_HARVEST,
            generation=generation,
            complete=bool(complete),
            version=version,
            sources=sources,
        )

    def reform_latency(self, generation, latency_secs: float):
        self._reform_downtime.observe(latency_secs)
        self.events.emit(
            EVENT_REFORM_LATENCY,
            generation=generation,
            latency_secs=latency_secs,
        )
        # the reform trace is complete once latency resolves: make the
        # phase spans durable even if the job later dies uncleanly
        self.tracer.flush()
