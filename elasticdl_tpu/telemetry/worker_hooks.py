"""Worker-side telemetry: per-step samples into the shared event log.

Installed once per process when the master exports
``ELASTICDL_TPU_TELEMETRY_DIR`` into the worker environment (the same
env plumbing as the chaos plan), or directly by in-process runtimes
(:class:`~elasticdl_tpu.trainer.local_executor.LocalExecutor`).

Overhead contract (ISSUE 2 acceptance): when telemetry is NOT
installed, the per-step path is a single early-return — one module
global load and a ``None`` check, no clock read, no attribute chase.
``tests/test_telemetry.py`` asserts this by poisoning the clock.

Step-sample semantics: :func:`record_step` is called at each step's
START (the worker runtimes call it from their pre-batch hook).  Each
call emits a ``step`` event stamped with the step/generation/worker and
the measured duration of the PREVIOUS inter-step interval (dispatch +
host work); the first call after install has no interval and emits no
duration.  A re-formed world is a new process with a fresh recorder, so
reform downtime never pollutes step-latency percentiles — the report
CLI instead derives downtime from the gap between the last ``step``
event of generation N and the first of generation N+1.
"""

from __future__ import annotations

import os
import time

from elasticdl_tpu.telemetry.events import EVENT_STEP, EventLog

TELEMETRY_DIR_ENV = "ELASTICDL_TPU_TELEMETRY_DIR"

_active: "StepRecorder | None" = None


class StepRecorder:
    def __init__(
        self,
        events: EventLog,
        worker_id: int = 0,
        process_id: int = 0,
        generation: int = 0,
    ):
        self._events = events
        self._worker_id = worker_id
        self._process_id = process_id
        self._generation = generation
        self._last_at: float | None = None

    @property
    def events(self) -> EventLog:
        return self._events

    def record_step(self, step: int, records: int = 0):
        now = time.monotonic()
        last, self._last_at = self._last_at, now
        fields = dict(
            step=int(step),
            generation=self._generation,
            worker_id=self._worker_id,
            process_id=self._process_id,
            records=int(records),
        )
        if last is not None:
            fields["duration_secs"] = now - last
        self._events.emit(EVENT_STEP, **fields)

    def emit(self, event: str, **fields):
        self._events.emit(
            event,
            generation=self._generation,
            worker_id=self._worker_id,
            process_id=self._process_id,
            **fields,
        )


# ---- module-level install + zero-cost-when-disabled accessors ---------------


def install(
    telemetry_dir: str,
    worker_id: int = 0,
    process_id: int = 0,
    generation: int = 0,
) -> StepRecorder | None:
    """Install the process-wide recorder writing to
    ``<telemetry_dir>/events.jsonl``; returns it (None if no dir)."""
    global _active
    if not telemetry_dir:
        return None
    from elasticdl_tpu.telemetry.events import EVENTS_FILENAME

    _active = StepRecorder(
        EventLog(os.path.join(telemetry_dir, EVENTS_FILENAME)),
        worker_id=worker_id,
        process_id=process_id,
        generation=generation,
    )
    return _active


def install_from_env(
    worker_id: int = 0, process_id: int = 0, generation: int = 0
) -> StepRecorder | None:
    """Install from ``ELASTICDL_TPU_TELEMETRY_DIR`` (worker subprocess
    entry); no-op when the master did not configure telemetry."""
    return install(
        os.environ.get(TELEMETRY_DIR_ENV, ""),
        worker_id=worker_id,
        process_id=process_id,
        generation=generation,
    )


def uninstall():
    global _active
    _active = None


def get_recorder() -> StepRecorder | None:  # elastic-lint: hot-path
    return _active


def record_step(step: int, records: int = 0):  # elastic-lint: hot-path
    """THE hot-path hook: one global load + None check when disabled."""
    recorder = _active
    if recorder is None:
        return
    recorder.record_step(step, records)


def emit_event(event: str, **fields):  # elastic-lint: hot-path
    """Process-scoped lifecycle emission (checkpoint save/restore, chaos
    fault mirror); no-op without an installed recorder."""
    recorder = _active
    if recorder is None:
        return
    recorder.emit(event, **fields)


def publish_timing(timing):  # elastic-lint: hot-path
    """Route :class:`~elasticdl_tpu.utils.timing_utils.Timing` bucket
    totals into the event log (``worker_timing`` event with
    ``time_<bucket>_ms`` fields) so the run report sees wall-clock
    buckets even from runtimes that never send task reports (the local
    executor).  Lockstep workers additionally ship per-task DELTAS to
    the master via exec counters, which the master mirrors into
    ``elasticdl_worker_time_ms_total`` on /metrics."""
    recorder = _active
    if recorder is None:
        return
    from elasticdl_tpu.telemetry.events import EVENT_WORKER_TIMING

    totals = timing.totals_ms()
    if totals:
        recorder.emit(EVENT_WORKER_TIMING, **totals)
