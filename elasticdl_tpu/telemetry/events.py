"""Structured elastic-lifecycle event log (append-only JSONL).

One file per run (``<telemetry_dir>/events.jsonl``), shared by the
master and every worker subprocess via O_APPEND — the same
single-writer-per-line discipline as the chaos event log
(:mod:`elasticdl_tpu.chaos.hooks`), so lines from concurrent writers
never interleave and a torn final line from a SIGKILL'd writer is
skipped on read.

Schema: every record carries ``time`` (wall clock), ``monotonic``
(machine-wide CLOCK_MONOTONIC — single-host runs can subtract across
processes) and ``event``; lifecycle context (``generation``, ``step``,
``worker_id``, ...) rides as flat keys.  Event names are snake_case and
defined once below (scripts/check_telemetry_names.py enforces both).
"""

from __future__ import annotations

import json
import os
import queue
import threading
import time

from elasticdl_tpu.utils.log_utils import default_logger as logger

# ---- event vocabulary (one definition site per name) ------------------------

EVENT_JOB_START = "job_start"
EVENT_JOB_END = "job_end"
EVENT_STEP = "step"
EVENT_TASK_DISPATCH = "task_dispatch"
EVENT_TASK_DONE = "task_done"
EVENT_TASK_RECOVERED = "task_recovered"
EVENT_WORKER_DEAD = "worker_dead"
EVENT_QUIESCE_BEGIN = "quiesce_begin"
EVENT_QUIESCE_END = "quiesce_end"
EVENT_REFORM_START = "reform_start"
EVENT_REFORM_COMPLETE = "reform_complete"
EVENT_REFORM_LATENCY = "reform_latency"
EVENT_WORKER_TIMING = "worker_timing"
EVENT_CHECKPOINT_SAVE = "checkpoint_save"
EVENT_CHECKPOINT_RESTORE = "checkpoint_restore"
EVENT_FAULT_INJECTED = "fault_injected"
EVENT_PROFILE_WINDOW_OPEN = "profile_window_open"
EVENT_PROFILE_WINDOW_CLOSE = "profile_window_close"
# peer state replication (elasticdl_tpu.replication): a worker pushed its
# state shard to its ring neighbor / the master harvested a complete
# replica set during reform / a re-formed world restored from peer RAM
EVENT_REPLICA_PUSH = "replica_push"
EVENT_REPLICA_HARVEST = "replica_harvest"
EVENT_REPLICA_RESTORE = "replica_restore"
# master high availability (master/journal.py): a master process came up
# restored from the control-plane journal / finished replaying it / a
# worker that outlived the outage re-homed onto the restarted master
EVENT_MASTER_RESTART = "master_restart"
EVENT_JOURNAL_REPLAY = "journal_replay"
EVENT_WORKER_REHOME = "worker_rehome"
# slice-granular elasticity: a whole slice's processes died (reform
# shrinks to the survivors, or parks below --min_slices) / the hybrid
# mesh was re-planned for a new slice set (dp axis resized over DCN) /
# the autoscaler requested a grow/shrink on an SLO crossing
EVENT_SLICE_LOSS = "slice_loss"
EVENT_MESH_RESIZE = "mesh_resize"
EVENT_AUTOSCALE_DECISION = "autoscale_decision"
# network chaos (chaos/netem.py): a transport-level fault fired at the
# RPC seam — injected latency window, blackhole, duplicate delivery,
# UNAVAILABLE, or one-way partition (distinct from fault_injected: the
# process lives, only its link degrades)
EVENT_RPC_FAULT_INJECTED = "rpc_fault_injected"
# step anatomy (telemetry/anatomy.py): one event per dispatch group
# carrying the sum-exact phase decomposition (host_fetch / assemble /
# h2d_transfer / device_compute / step_bookkeeping / untracked, in ms)
# — the data the report's goodput section is computed from
EVENT_STEP_ANATOMY = "step_anatomy"
# online serving plane (elasticdl_tpu/serving): one event per completed
# predict request carrying its sum-exact phase decomposition
# (queue_wait / assemble / h2d_transfer / device_compute / d2h_transfer
# / untracked, in ms) / a replica hot-swapped its model state to a new
# version with in-flight requests still draining on the old one
EVENT_SERVING_REQUEST = "serving_request"
EVENT_MODEL_SWAP = "model_swap"
# fleet-scale control-plane simulation (elasticdl_tpu.fleetsim): one
# event per injected mass fault (mass preemption wave, rolling slice
# loss, master kill) with its virtual firing time — the source of the
# report's control-plane scale section fault timeline
EVENT_FLEET_FAULT = "fleet_fault"
# memory observability plane (telemetry/memory.py): one event per
# ledger sample (periodic + phase edges: reform, model swap,
# checkpoint) carrying per-component bytes, peaks, host RSS and the
# explicit unaccounted residual / host MemAvailable crossed below the
# pressure fraction (entered=True) or recovered above it
EVENT_MEMORY_SAMPLE = "memory_sample"
EVENT_MEMORY_PRESSURE = "memory_pressure"
# sharded embedding subsystem (elasticdl_tpu.embeddings): one event per
# host-tier pull of unique rows into the fixed-capacity device
# minitable (the XLA-era pull_embedding_vector) with row/byte counts /
# a table admission FAILED — neither the device budget nor the host-RAM
# headroom (memory ledger) admits it, so the caller must shrink or
# re-place the table rather than walk the host into OOM
EVENT_EMBEDDING_GATHER = "embedding_gather"
EVENT_EMBEDDING_SPILL_FAULT = "embedding_spill_fault"
# SLO watchdog plane (telemetry/slo.py + telemetry/incident.py): a
# burn-rate detector fired (violation) / cleared through the
# hysteresis band (recovered); an incident opened on the first
# violation of an unhealthy episode / closed when every objective
# recovered, pointing at the incidents/incident_<n>.json postmortem
EVENT_SLO_VIOLATION = "slo_violation"
EVENT_SLO_RECOVERED = "slo_recovered"
EVENT_INCIDENT_OPEN = "incident_open"
EVENT_INCIDENT_CLOSE = "incident_close"
# streaming subsystem (elasticdl_tpu.streaming): one event per master
# poll tick in watermark-lease mode carrying the source/trained
# watermark pair (stream_watermark) and the lag derived from it
# (stream_lag — the autoscaler's backlog signal and the bounded-lag
# chaos invariant's evidence); one event per live train->serve push
# (live_push) stamping trained-watermark-at-swap vs source watermark —
# the freshness ledger's rows (staleness = source - trained at push)
EVENT_STREAM_WATERMARK = "stream_watermark"
EVENT_STREAM_LAG = "stream_lag"
EVENT_LIVE_PUSH = "live_push"

EVENTS_FILENAME = "events.jsonl"

# ---- size-based rollover ----------------------------------------------------
#
# Long runs must not fill the disk unbounded: when the active JSONL
# crosses the size cap it is shifted to ``<path>.1`` (older shards move
# to ``.2``, ``.3``, ...; the oldest beyond KEEP_SHARDS is overwritten).
# Shared by the event log and the span log (telemetry/tracing.py).
# Rotation is rename-based so concurrent O_APPEND writers stay correct:
# a writer holding the pre-rotation fd keeps appending into the renamed
# shard, and a racing second rotation just loses the rename (caught).

ROTATE_MAX_BYTES = 64 * 1024 * 1024
ROTATE_KEEP_SHARDS = 3
ROTATE_MAX_MB_ENV = "ELASTICDL_TPU_TELEMETRY_LOG_MAX_MB"


def rotate_if_needed(
    path: str,
    max_bytes: int | None = None,
    keep_shards: int | None = None,
):
    """Shift ``path`` into numbered shards once it crosses the cap."""
    if not path:
        return
    if max_bytes is None:
        try:
            max_bytes = int(
                float(os.environ.get(ROTATE_MAX_MB_ENV, 0)) * 1024 * 1024
            ) or ROTATE_MAX_BYTES
        except ValueError:
            max_bytes = ROTATE_MAX_BYTES
    keep = keep_shards if keep_shards is not None else ROTATE_KEEP_SHARDS
    try:
        if os.path.getsize(path) < max_bytes:
            return
    except OSError:
        return
    try:
        for i in range(keep - 1, 0, -1):
            src = f"{path}.{i}"
            if os.path.exists(src):
                os.replace(src, f"{path}.{i + 1}")
        os.replace(path, f"{path}.1")
    except OSError:
        # a concurrent writer rotated first; its shift already applied
        pass


def _shard_paths(path: str) -> list[str]:
    """All shards of one log, oldest first (highest index), active last."""
    shards = []
    i = 1
    while os.path.exists(f"{path}.{i}"):
        shards.append(f"{path}.{i}")
        i += 1
    shards.reverse()
    if os.path.exists(path):
        shards.append(path)
    return shards


class EventLog:
    """Append-only JSONL writer; a no-path log swallows every emit, so
    callers never branch on whether telemetry is configured.

    ``async_writes=True`` moves the disk write to a daemon thread: the
    master emits from under the TaskDispatcher lock (observer
    callbacks), so a synchronous write there would serialize every
    worker's get-task/report RPC behind file I/O.  Timestamps are taken
    at EMIT time either way; ``flush()`` drains the queue (the master
    calls it at job end).  Workers keep the default synchronous write —
    their emits are on the training thread only, and a SIGKILL'd
    process (chaos preempt) must not lose its final queued events.
    """

    def __init__(self, path: str = "", async_writes: bool = False):
        self._path = path
        self._async = async_writes and bool(path)
        self._queue: queue.SimpleQueue | None = None
        self._thread: threading.Thread | None = None
        if path:
            parent = os.path.dirname(path)
            if parent:
                os.makedirs(parent, exist_ok=True)
        if self._async:
            self._queue = queue.SimpleQueue()
            self._thread = threading.Thread(
                target=self._drain, name="telemetry-events", daemon=True
            )
            self._thread.start()

    @property
    def path(self) -> str:
        return self._path

    @property
    def enabled(self) -> bool:
        return bool(self._path)

    def emit(self, event: str, **fields):
        if not self._path:
            return
        record = {
            "time": time.time(),
            "monotonic": time.monotonic(),
            "event": event,
            **fields,
        }
        if self._async:
            self._queue.put(record)
        else:
            self._write(record)

    def flush(self, timeout: float = 5.0):
        """Block until everything queued so far is on disk (async logs
        only; a synchronous log is always flushed)."""
        if not self._async:
            return
        done = threading.Event()
        self._queue.put(done)
        done.wait(timeout)

    def _drain(self):
        while True:
            item = self._queue.get()
            if isinstance(item, threading.Event):
                item.set()
                continue
            self._write(item)

    def _write(self, record: dict):
        try:
            rotate_if_needed(self._path)
            with open(self._path, "a", encoding="utf-8") as f:
                f.write(json.dumps(record) + "\n")
        except OSError:
            logger.exception("Telemetry event log write failed")


def read_jsonl(path: str) -> list[dict]:
    """Parse one JSONL log INCLUDING its rotated shards (oldest first);
    torn lines (a writer killed mid-write) are skipped, matching the
    chaos log reader."""
    records: list[dict] = []
    for shard in _shard_paths(path):
        try:
            with open(shard, encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        records.append(json.loads(line))
                    except ValueError:
                        continue
        except OSError:
            continue
    return records


def read_events(path: str) -> list[dict]:
    """Back-compat alias: the event log's reader."""
    return read_jsonl(path)
