"""Trace CLI: Perfetto export + reform critical-path analysis.

::

    python -m elasticdl_tpu.telemetry.trace export <run_dir> [--output f]
    python -m elasticdl_tpu.telemetry.trace analyze <run_dir> [--json]

``<run_dir>`` is any directory tree holding telemetry logs (the same
contract as ``telemetry.report``): each ``spans.jsonl`` /
``events.jsonl`` pair written by one run is analyzed independently.

``export`` emits Chrome trace-event JSON (viewable at ui.perfetto.dev or
``chrome://tracing``): every span becomes a complete ("X") event, every
worker ``step`` sample becomes an "X" event on its worker's track, and
lifecycle events become instants — one track per worker per generation,
plus a master track, so a re-formation reads as the old generation's
tracks ending, the master's reform phases, and the new generation's
tracks starting.

``analyze`` computes:

- the **reform-downtime critical path**: each inter-generation gap (the
  downtime ``telemetry.report`` measures: last step of generation N to
  first step of generation N+1) broken into named phases —
  ``death_detection`` (gap start to the reform root span),
  ``quiesce_recover`` (fence + task recovery span), ``world_relaunch``
  (kill + respawn span), ``world_join`` (the new world's
  ``jax.distributed`` handshake spans), ``checkpoint_restore`` (state
  restore spans of the new generation) and ``warmup_compile`` (the
  remainder up to the first step — compile + first dispatch).  Phases
  are attributed by a boundary sweep over the clamped span intervals
  (later pipeline stages win overlaps), so the named phases plus
  ``unattributed`` sum EXACTLY to the downtime and ``coverage`` is the
  attributed fraction.
- a per-generation **straggler report**: each worker's median step time
  vs the generation median (outliers flagged), and the wait-vs-work
  split at the lockstep barrier — for every step index that multiple
  workers executed, the slowest worker bounds the barrier, so
  ``wait = slowest - own`` accumulates the time a worker spent blocked
  on peers rather than computing.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from collections import defaultdict

from elasticdl_tpu.telemetry.events import EVENTS_FILENAME, read_jsonl
from elasticdl_tpu.telemetry.tracing import (
    SPAN_CHECKPOINT_RESTORE,
    SPAN_COMPILE,
    SPAN_JOURNAL_REPLAY,
    SPAN_MASTER_RESTART,
    SPAN_MESH_RESIZE,
    SPAN_PREDICT_REQUEST,
    SPAN_REFORM,
    SPAN_REFORM_FENCE,
    SPAN_REFORM_RELAUNCH,
    SPAN_REPLICA_HARVEST,
    SPAN_REPLICA_RESTORE,
    SPAN_RPC_DEGRADED,
    SPAN_SERVING_DISPATCH,
    SPAN_SERVING_ENGINE,
    SPAN_SERVING_QUEUE,
    SPAN_SERVING_REROUTE,
    SPAN_SERVING_ROUTE,
    SPAN_TRAINER_BUILD,
    SPAN_WORKER_REHOME,
    SPAN_WORLD_INITIALIZE,
    SPAN_WORLD_JOIN,
    SPANS_FILENAME,
)

# a reform span can open marginally before the victim's last step lands
# in the log (step events stamp step START) — tolerate this much skew
# when matching a reform trace to a downtime gap
_GAP_MATCH_SLACK_SECS = 5.0

# a worker whose median step time exceeds the generation median by this
# factor is a straggler
_STRAGGLER_FACTOR = 1.5

TRACE_FILENAME = "trace.json"


def _find_dirs(run_dir: str) -> list[str]:
    """Directories holding at least one telemetry log (each is one run)."""
    found = set()
    for root, _dirs, files in os.walk(run_dir):
        if SPANS_FILENAME in files or EVENTS_FILENAME in files:
            found.add(root)
    return sorted(found)


def _load_run(telemetry_dir: str) -> tuple[list[dict], list[dict]]:
    spans = read_jsonl(os.path.join(telemetry_dir, SPANS_FILENAME))
    events = read_jsonl(os.path.join(telemetry_dir, EVENTS_FILENAME))
    return spans, events


# ---- export -----------------------------------------------------------------


class _Tracks:
    """Stable pid assignment: one Chrome 'process' per (run, actor,
    generation) so Perfetto renders one track per worker per generation."""

    def __init__(self):
        self._pids: dict[tuple, int] = {}
        self.metadata: list[dict] = []

    def pid(self, run: str, role: str, worker_id, generation) -> int:
        prefix = f"{run} " if run else ""
        if role == "master":
            key = (run, "master", None)
            label = f"{prefix}master"
        elif role in ("router", "client"):
            # singleton serving actors: one track each (a router has no
            # generations — its lifetime IS the fleet's)
            key = (run, role, None)
            label = f"{prefix}{role}"
        elif role == "replica":
            # one track per serving replica, so a request's trace reads
            # client -> router -> replica N top to bottom
            key = (run, "replica", worker_id)
            label = f"{prefix}replica {worker_id}"
        else:
            key = (run, worker_id, generation)
            label = f"{prefix}worker {worker_id} gen {generation}"
        pid = self._pids.get(key)
        if pid is None:
            pid = len(self._pids) + 1
            self._pids[key] = pid
            self.metadata.append(
                {
                    "name": "process_name",
                    "ph": "M",
                    "pid": pid,
                    "tid": 0,
                    "args": {"name": label},
                }
            )
            self.metadata.append(
                {
                    "name": "process_sort_index",
                    "ph": "M",
                    "pid": pid,
                    "tid": 0,
                    "args": {"sort_index": pid},
                }
            )
        return pid


def build_chrome_trace(run_dir: str) -> dict:
    """Chrome trace-event JSON for every run under ``run_dir``."""
    tracks = _Tracks()
    trace_events: list[dict] = []
    for telemetry_dir in _find_dirs(run_dir):
        run = os.path.relpath(telemetry_dir, run_dir)
        run = "" if run == "." else run
        spans, events = _load_run(telemetry_dir)
        for span in spans:
            start = span.get("start")
            end = span.get("end")
            if start is None or end is None:
                continue
            role = span.get("role", "worker")
            pid = tracks.pid(
                run, role, span.get("worker_id", 0), span.get("generation", 0)
            )
            args = {
                k: v
                for k, v in span.items()
                if k not in ("span", "start", "end", "time")
            }
            trace_events.append(
                {
                    "name": span.get("span", "span"),
                    "cat": role,
                    "ph": "X",
                    "ts": round(start * 1e6, 3),
                    "dur": round(max(0.0, end - start) * 1e6, 3),
                    "pid": pid,
                    "tid": int(span.get("process_id", 0) or 0),
                    "args": args,
                }
            )
        for event in events:
            name = event.get("event", "")
            at = event.get("monotonic")
            if at is None:
                continue
            if name == "step":
                dur = float(event.get("duration_secs") or 0.0)
                pid = tracks.pid(
                    run,
                    "worker",
                    event.get("worker_id", 0),
                    event.get("generation", 0),
                )
                trace_events.append(
                    {
                        "name": "step",
                        "cat": "step",
                        "ph": "X",
                        # duration measures the PREVIOUS interval; the
                        # slice ends at this sample's timestamp
                        "ts": round((at - dur) * 1e6, 3),
                        "dur": round(dur * 1e6, 3),
                        "pid": pid,
                        "tid": int(event.get("process_id", 0) or 0),
                        "args": {
                            "step": event.get("step"),
                            "records": event.get("records"),
                        },
                    }
                )
            else:
                pid = tracks.pid(run, "master", None, None)
                trace_events.append(
                    {
                        "name": name,
                        "cat": "lifecycle",
                        "ph": "i",
                        "s": "g",
                        "ts": round(at * 1e6, 3),
                        "pid": pid,
                        "tid": 0,
                        "args": {
                            k: v
                            for k, v in event.items()
                            if k not in ("event", "time", "monotonic")
                        },
                    }
                )
    return {
        "displayTimeUnit": "ms",
        "traceEvents": tracks.metadata + trace_events,
    }


# ---- analyze ----------------------------------------------------------------


def _steps_by_generation(events: list[dict]) -> dict[int, list[dict]]:
    by_gen: dict[int, list[dict]] = defaultdict(list)
    for event in events:
        if event.get("event") == "step" and event.get("monotonic") is not None:
            by_gen[event.get("generation", 0)].append(event)
    for steps in by_gen.values():
        steps.sort(key=lambda e: e["monotonic"])
    return by_gen


def _spans_named(spans: list[dict], *names: str) -> list[dict]:
    wanted = set(names)
    return [
        s
        for s in spans
        if s.get("span") in wanted
        and s.get("start") is not None
        and s.get("end") is not None
    ]


def _merged_window(spans: list[dict]) -> tuple[float, float] | None:
    if not spans:
        return None
    return (
        min(s["start"] for s in spans),
        max(s["end"] for s in spans),
    )


def _phase_intervals(
    spans: list[dict], gap_start: float, gap_end: float, to_generation: int
) -> list[tuple[str, float, float]]:
    """Candidate (phase, start, end) intervals for one downtime gap, in
    pipeline order.  Boundaries are clamped later by the sweep."""
    intervals: list[tuple[str, float, float]] = []
    reform = next(
        (
            s
            for s in sorted(
                _spans_named(spans, SPAN_REFORM), key=lambda s: s["start"]
            )
            if gap_start - _GAP_MATCH_SLACK_SECS <= s["start"] <= gap_end
        ),
        None,
    )
    # degraded-network windows (netem rpc_degraded spans): the period a
    # link was injected slow/blackholed.  Listed right after
    # death_detection so it REFINES the detection segment — the sweep's
    # later-stage-wins rule keeps every reform phase on top of it —
    # and clamped to the reform start: the eviction resolves the
    # degradation as far as this gap's pipeline is concerned.
    degraded = _merged_window(
        [
            s
            for s in _spans_named(spans, SPAN_RPC_DEGRADED)
            if s["end"] > gap_start - _GAP_MATCH_SLACK_SECS
            and s["start"] < gap_end
        ]
    )
    if reform is not None:
        intervals.append(("death_detection", gap_start, reform["start"]))
        if degraded:
            intervals.append(
                (
                    "degraded_network",
                    degraded[0],
                    min(degraded[1], reform["start"]),
                )
            )
        children = [
            s
            for s in spans
            if s.get("trace_id") == reform.get("trace_id")
            and s.get("span_id") != reform.get("span_id")
        ]
        # the replica harvest runs between the generation bump and the
        # fence loop (Master._stage_replica_restore), so it slots before
        # quiesce_recover in pipeline order
        harvest = _merged_window(
            _spans_named(children, SPAN_REPLICA_HARVEST)
        )
        if harvest:
            intervals.append(("replica_harvest", harvest[0], harvest[1]))
        fence = _merged_window(_spans_named(children, SPAN_REFORM_FENCE))
        if fence:
            intervals.append(("quiesce_recover", fence[0], fence[1]))
        relaunch = _merged_window(
            _spans_named(children, SPAN_REFORM_RELAUNCH)
        )
        if relaunch:
            intervals.append(("world_relaunch", relaunch[0], relaunch[1]))
    elif degraded:
        # no reform span matched the gap: the degraded window is still
        # the best name for the time it covers
        intervals.append(("degraded_network", degraded[0], degraded[1]))
    join_spans = [
        s
        for s in _spans_named(spans, SPAN_WORLD_JOIN, SPAN_WORLD_INITIALIZE)
        if s.get("generation", -1) == to_generation
        and gap_start - _GAP_MATCH_SLACK_SECS <= s["start"] <= gap_end
    ]
    join = _merged_window(join_spans)
    if join:
        intervals.append(("world_join", join[0], join[1]))
    for phase, span_name in (
        ("trainer_build", SPAN_TRAINER_BUILD),
        ("checkpoint_restore", SPAN_CHECKPOINT_RESTORE),
        # a replica-served reform has this phase INSTEAD of the disk
        # checkpoint_restore — restore came from the master's staged
        # peer-RAM harvest, not from a checkpoint read
        ("replica_restore", SPAN_REPLICA_RESTORE),
        # measured backend compiles (telemetry/compile_tracker.py):
        # listed LAST so the sweep attributes real compile time to
        # warmup_compile even where it overlaps trainer_build/restore —
        # the phase stops being a mere inferred remainder
        ("warmup_compile", SPAN_COMPILE),
    ):
        window = _merged_window(
            [
                s
                for s in _spans_named(spans, span_name)
                if s.get("generation", -1) == to_generation
                and gap_start - _GAP_MATCH_SLACK_SECS
                <= s["start"]
                <= gap_end
            ]
        )
        if window:
            intervals.append((phase, window[0], window[1]))
    return intervals


# uncovered time BETWEEN known phases is named for what the pipeline is
# doing there: after the relaunch span the master is waiting on process
# spawn; after the join the worker is re-initializing (model spec, data
# reader, first lease); after the build/restore it is compiling the step
_BRIDGE_AFTER = {
    "degraded_network": "death_detection",
    "replica_harvest": "quiesce_recover",
    "world_relaunch": "worker_spawn",
    "world_join": "worker_init",
    "trainer_build": "warmup_compile",
    "checkpoint_restore": "warmup_compile",
    "replica_restore": "warmup_compile",
    "warmup_compile": "warmup_compile",
}


def _attribute_gap(
    intervals: list[tuple[str, float, float]],
    gap_start: float,
    gap_end: float,
    tail_name: str = "warmup_compile",
    bridge: dict[str, str] | None = None,
) -> dict[str, float]:
    """Boundary sweep: every instant of the gap goes to the LAST listed
    phase covering it; time after every known phase is ``tail_name``
    (for a reform gap: the new world warming up); time covered by
    nothing before that is bridged via ``bridge`` or ``unattributed``.
    Values sum to the gap exactly."""
    if bridge is None:
        bridge = _BRIDGE_AFTER
    clamped = [
        (name, max(gap_start, lo), min(gap_end, hi))
        for name, lo, hi in intervals
        if min(gap_end, hi) > max(gap_start, lo)
    ]
    phases: dict[str, float] = defaultdict(float)
    # the tail after the last KNOWN phase is the new world warming up —
    # but only when there is at least one known phase; with no span
    # evidence at all the whole gap is honestly unattributed
    last_known_end = (
        max(hi for _n, _lo, hi in clamped) if clamped else None
    )
    bounds = sorted(
        {gap_start, gap_end}
        | ({last_known_end} if last_known_end is not None else set())
        | {b for _n, lo, hi in clamped for b in (lo, hi)}
    )
    for lo, hi in zip(bounds, bounds[1:]):
        mid = (lo + hi) / 2.0
        owner = None
        for name, ilo, ihi in clamped:  # later pipeline stages win
            if ilo <= mid < ihi:
                owner = name
        if owner is None and last_known_end is not None:
            if mid >= last_known_end:
                owner = tail_name
            else:
                # between two known phases: name the segment for what
                # the pipeline is doing after the preceding phase
                preceding = None
                preceding_end = None
                for name, _ilo, ihi in clamped:
                    if ihi <= mid and (
                        preceding_end is None or ihi > preceding_end
                    ):
                        preceding, preceding_end = name, ihi
                owner = bridge.get(preceding)
        if owner is None:
            owner = "unattributed"
        phases[owner] += hi - lo
    return dict(phases)


# uncovered time inside a master outage: after the restore span the
# master is serving but workers have not noticed the new boot id yet
# (heartbeat cadence); after the last re-home the world is re-leasing
# and dispatching again
_MASTER_OUTAGE_BRIDGE = {
    "master_restore": "rehome_wait",
    "journal_replay": "rehome_wait",
    "worker_rehome": "resume_dispatch",
}


def _master_outages(spans: list[dict], events: list[dict]) -> list[dict]:
    """Master-downtime attribution (master high availability): each
    ``master_restart`` span (restore start -> serving) anchors one
    outage.  The measured gap is the worker step stall around it — last
    ``step`` event at/before the restore began to the first at/after
    the master served again, the same measure ``telemetry.report`` uses
    — broken into named phases: ``master_down`` (death to relaunch),
    ``journal_replay``, ``master_restore`` (the rest of coming up),
    ``worker_rehome`` (lease-reconciliation handshakes), ``rehome_wait``
    / ``resume_dispatch`` (bridged idle).  The boundary sweep guarantees
    the phases sum EXACTLY to the measured gap."""
    restarts = sorted(
        _spans_named(spans, SPAN_MASTER_RESTART), key=lambda s: s["start"]
    )
    if not restarts:
        return []
    step_times = sorted(
        e["monotonic"]
        for e in events
        if e.get("event") == "step" and e.get("monotonic") is not None
    )
    outages = []
    for restart in restarts:
        gap_start = next(
            (
                t
                for t in reversed(step_times)
                if t <= restart["start"]
            ),
            restart["start"],
        )
        gap_end = next(
            (t for t in step_times if t >= restart["end"]), restart["end"]
        )
        intervals: list[tuple[str, float, float]] = [
            ("master_down", gap_start, restart["start"]),
            ("master_restore", restart["start"], restart["end"]),
        ]
        for phase, name in (
            ("journal_replay", SPAN_JOURNAL_REPLAY),
            ("worker_rehome", SPAN_WORKER_REHOME),
        ):
            window = _merged_window(
                [
                    s
                    for s in _spans_named(spans, name)
                    if restart["start"] - _GAP_MATCH_SLACK_SECS
                    <= s["start"]
                    <= gap_end
                ]
            )
            if window:
                intervals.append((phase, window[0], window[1]))
        downtime = max(0.0, gap_end - gap_start)
        phases = (
            _attribute_gap(
                intervals,
                gap_start,
                gap_end,
                tail_name="resume_dispatch",
                bridge=_MASTER_OUTAGE_BRIDGE,
            )
            if downtime > 0
            else {}
        )
        attributed = sum(
            v for k, v in phases.items() if k != "unattributed"
        )
        outages.append(
            {
                "generation": restart.get("generation"),
                "downtime_secs": round(downtime, 6),
                "phases_secs": {
                    k: round(v, 6) for k, v in sorted(phases.items())
                },
                "coverage": round(attributed / downtime, 4)
                if downtime
                else None,
            }
        )
    return outages


def _percentile(samples: list[float], q: float) -> float:
    import math

    ordered = sorted(samples)
    rank = max(1, math.ceil(q / 100.0 * len(ordered)))
    return ordered[min(rank, len(ordered)) - 1]


def _straggler_report(steps: list[dict]) -> dict:
    """Per-worker outliers + wait-vs-work split for ONE generation."""
    durations = [
        e["duration_secs"]
        for e in steps
        if e.get("duration_secs") is not None
    ]
    if not durations:
        return {}
    gen_median = _percentile(durations, 50)
    by_worker: dict[int, list[dict]] = defaultdict(list)
    for e in steps:
        if e.get("duration_secs") is not None:
            by_worker[e.get("worker_id", 0)].append(e)
    workers = {}
    for worker_id, events in sorted(by_worker.items()):
        own = [e["duration_secs"] for e in events]
        median = _percentile(own, 50)
        workers[worker_id] = {
            "steps": len(own),
            "median_step_ms": round(median * 1000.0, 3),
            "vs_generation_median": round(median / gen_median, 3)
            if gen_median
            else None,
            "straggler": bool(
                gen_median and median > _STRAGGLER_FACTOR * gen_median
            ),
        }
    # wait-vs-work at the lockstep barrier: for each step index executed
    # by >1 worker, the slowest bounds the barrier — everyone else waited
    by_step: dict[int, list[dict]] = defaultdict(list)
    for e in steps:
        if e.get("duration_secs") is not None and e.get("step") is not None:
            by_step[e["step"]].append(e)
    work: dict[int, float] = defaultdict(float)
    wait: dict[int, float] = defaultdict(float)
    barrier_steps = 0
    for _step, entries in by_step.items():
        if len(entries) < 2:
            continue
        barrier_steps += 1
        slowest = max(e["duration_secs"] for e in entries)
        for e in entries:
            worker = e.get("worker_id", 0)
            work[worker] += e["duration_secs"]
            wait[worker] += slowest - e["duration_secs"]
    for worker_id, stats in workers.items():
        if worker_id in work:
            total = work[worker_id] + wait[worker_id]
            stats["barrier_work_secs"] = round(work[worker_id], 6)
            stats["barrier_wait_secs"] = round(wait[worker_id], 6)
            stats["barrier_wait_pct"] = (
                round(wait[worker_id] / total * 100.0, 2) if total else 0.0
            )
    return {
        "generation_median_step_ms": round(gen_median * 1000.0, 3),
        "barrier_steps_compared": barrier_steps,
        "workers": workers,
    }


def _steady_state(events: list[dict]) -> dict:
    """Per-generation step-anatomy phase totals — where a NORMAL
    (non-reform) step's time goes, with the same sum-exact residual
    contract (`untracked` is tracked, not dropped).  Empty when the run
    never recorded anatomy (--step_anatomy off)."""
    from elasticdl_tpu.telemetry.anatomy import ALL_PHASES

    by_gen: dict[int, list[dict]] = defaultdict(list)
    for event in events:
        if event.get("event") == "step_anatomy":
            by_gen[event.get("generation", 0)].append(event)
    out = {}
    for gen in sorted(by_gen):
        gen_events = by_gen[gen]
        wall_ms = sum(float(e.get("wall_ms", 0.0)) for e in gen_events)
        phases = {}
        for phase in ALL_PHASES:
            total = sum(
                float(e.get(f"{phase}_ms", 0.0)) for e in gen_events
            )
            if total:
                phases[phase] = {
                    "total_ms": round(total, 3),
                    "share": round(total / wall_ms, 4) if wall_ms else None,
                }
        out[gen] = {
            "dispatches": len(gen_events),
            "steps": sum(int(e.get("steps", 0)) for e in gen_events),
            "wall_ms_total": round(wall_ms, 3),
            "phases": phases,
        }
    return out


# uncovered time inside a predict request, named for what the pipeline
# is doing after the preceding phase: after routing the request sits in
# the replica queue, after queueing it computes, after compute the
# response returns through router to client
_SERVING_BRIDGE = {
    "route": "queue_wait",
    "queue_wait": "compute",
    "compute": "response_return",
}


def _serving_critical_path(spans: list[dict]) -> dict:
    """Per-request critical path of the serving plane: each
    ``predict_request`` root's wall is attributed over its trace's
    router/replica child spans with the SAME sum-exact boundary sweep
    the reform analysis uses — route, queue_wait, compute, the
    response's return leg, and honest ``unattributed`` for traces with
    missing children.  Sums (per trace AND in total) equal the measured
    request wall exactly."""
    roots = [
        s
        for s in _spans_named(spans, SPAN_PREDICT_REQUEST)
        if s.get("start") is not None and s.get("end") is not None
    ]
    if not roots:
        return {}
    by_trace: dict[str, list[dict]] = defaultdict(list)
    for span in spans:
        if span.get("trace_id"):
            by_trace[span["trace_id"]].append(span)
    totals: dict[str, float] = defaultdict(float)
    wall_total = 0.0
    reroutes = 0
    for root in sorted(roots, key=lambda s: s["start"]):
        members = [
            s
            for s in by_trace.get(root.get("trace_id"), [])
            if s is not root
            and s.get("start") is not None
            and s.get("end") is not None
        ]
        # pipeline order (later listed wins overlaps): the route span
        # covers the whole downstream RPC, so the replica's finer
        # queue/compute split takes the overlap and "route" keeps only
        # the router's own pick/transport time
        intervals = []
        for span in members:
            if span.get("span") in (
                SPAN_SERVING_ROUTE,
                SPAN_SERVING_REROUTE,
            ):
                intervals.append(("route", span["start"], span["end"]))
                if span.get("span") == SPAN_SERVING_REROUTE:
                    reroutes += 1
        for span in members:
            if span.get("span") == SPAN_SERVING_QUEUE:
                intervals.append(
                    ("queue_wait", span["start"], span["end"])
                )
        for span in members:
            if span.get("span") == SPAN_SERVING_ENGINE:
                intervals.append(("compute", span["start"], span["end"]))
        phases = _attribute_gap(
            intervals,
            root["start"],
            root["end"],
            tail_name="response_return",
            bridge=_SERVING_BRIDGE,
        )
        for name, secs in phases.items():
            totals[name] += secs
        wall_total += max(0.0, root["end"] - root["start"])
    dispatches = _spans_named(spans, SPAN_SERVING_DISPATCH)
    attributed = sum(
        v for k, v in totals.items() if k != "unattributed"
    )
    return {
        "requests": len(roots),
        "reroutes": reroutes,
        "wall_secs_total": round(wall_total, 6),
        "phases_secs": {
            k: round(v, 6) for k, v in sorted(totals.items())
        },
        "coverage": round(attributed / wall_total, 4)
        if wall_total
        else None,
        "dispatch_groups": len(dispatches),
        "linked_dispatch_groups": sum(
            1 for s in dispatches if s.get("links")
        ),
    }


def analyze_telemetry_dir(telemetry_dir: str) -> dict:
    """Analysis of ONE run's spans+events pair (pure function of the
    logs; the unit tests drive it with canned files)."""
    spans, events = _load_run(telemetry_dir)
    by_gen = _steps_by_generation(events)
    ordered = sorted(by_gen)

    reform_downtime = []
    for prev, nxt in zip(ordered, ordered[1:]):
        gap_start = by_gen[prev][-1]["monotonic"]
        gap_end = by_gen[nxt][0]["monotonic"]
        downtime = max(0.0, gap_end - gap_start)
        phases = (
            _attribute_gap(
                _phase_intervals(spans, gap_start, gap_end, nxt),
                gap_start,
                gap_end,
            )
            if downtime > 0
            else {}
        )
        attributed = sum(
            v for k, v in phases.items() if k != "unattributed"
        )
        reform_downtime.append(
            {
                "from_generation": prev,
                "to_generation": nxt,
                "downtime_secs": round(downtime, 6),
                "phases_secs": {
                    k: round(v, 6) for k, v in sorted(phases.items())
                },
                "coverage": round(attributed / downtime, 4)
                if downtime
                else None,
            }
        )

    straggler_reports = (
        (gen, _straggler_report(by_gen[gen])) for gen in ordered
    )
    stragglers = {gen: rep for gen, rep in straggler_reports if rep}

    recovered_links = sum(
        1 for s in spans if s.get("recovered") and s.get("trace_id")
    )
    # steady-state (non-reform) mode: the same phase discipline the
    # reform attribution applies to downtime, applied to NORMAL steps —
    # per-generation step-anatomy phase totals (from the complete
    # per-dispatch events; the sampled step_anatomy spans render the
    # same breakdown on the Perfetto timeline)
    steady_state = _steady_state(events)
    # slice-granular elasticity: every hybrid-mesh resize the run's
    # re-formations performed (a separate listing — the resize re-plan
    # runs inside the reform window, so it is NOT a new downtime phase
    # and the sum-exact phase attribution above is untouched)
    mesh_resizes = [
        {
            "generation": s.get("generation"),
            "old_world_size": s.get("old_world_size"),
            "new_world_size": s.get("new_world_size"),
            "old_slices": s.get("old_slices"),
            "new_slices": s.get("new_slices"),
            "plan_secs": round(s["end"] - s["start"], 6),
        }
        for s in sorted(
            _spans_named(spans, SPAN_MESH_RESIZE),
            key=lambda s: s["start"],
        )
    ]
    out = {
        "spans_total": len(spans),
        "traces_total": len({s.get("trace_id") for s in spans}),
        "recovered_task_spans": recovered_links,
        "reform_downtime": reform_downtime,
        "master_outage": _master_outages(spans, events),
        "mesh_resizes": mesh_resizes,
        "stragglers": stragglers,
    }
    if steady_state:
        out["steady_state"] = steady_state
    serving = _serving_critical_path(spans)
    if serving:
        out["serving"] = serving
    return out


def analyze_run_dir(run_dir: str) -> dict:
    runs = {}
    for telemetry_dir in _find_dirs(run_dir):
        rel = os.path.relpath(telemetry_dir, run_dir)
        runs["." if rel == "." else rel] = analyze_telemetry_dir(
            telemetry_dir
        )
    return {"run_dir": run_dir, "runs": runs}


# ---- CLI --------------------------------------------------------------------


def _format_analysis(report: dict) -> str:
    lines = [f"Trace analysis: {report['run_dir']}"]
    if not report["runs"]:
        lines.append("no telemetry logs found (spans.jsonl / events.jsonl)")
    for rel, run in report["runs"].items():
        lines.append(
            f"== {rel} ==  spans={run['spans_total']} "
            f"traces={run['traces_total']} "
            f"recovered_task_spans={run['recovered_task_spans']}"
        )
        for gap in run["reform_downtime"]:
            lines.append(
                "reform gen{}->gen{}: downtime {:.2f}s  coverage {}".format(
                    gap["from_generation"],
                    gap["to_generation"],
                    gap["downtime_secs"],
                    f"{gap['coverage'] * 100:.0f}%"
                    if gap["coverage"] is not None
                    else "n/a",
                )
            )
            for phase, secs in gap["phases_secs"].items():
                lines.append(f"  {phase:<20s} {secs:8.3f}s")
        for outage in run.get("master_outage", []):
            lines.append(
                "master outage (gen {}): downtime {:.2f}s  coverage "
                "{}".format(
                    outage["generation"],
                    outage["downtime_secs"],
                    f"{outage['coverage'] * 100:.0f}%"
                    if outage["coverage"] is not None
                    else "n/a",
                )
            )
            for phase, secs in outage["phases_secs"].items():
                lines.append(f"  {phase:<20s} {secs:8.3f}s")
        for resize in run.get("mesh_resizes", []):
            lines.append(
                "mesh resize (gen {}): {} procs / {} slice(s) -> {} "
                "procs / {} slice(s)".format(
                    resize["generation"],
                    resize["old_world_size"],
                    resize["old_slices"],
                    resize["new_world_size"],
                    resize["new_slices"],
                )
            )
        for gen, g in (run.get("steady_state") or {}).items():
            lines.append(
                "steady state gen {}: {} dispatches / {} steps, "
                "{:.1f}ms".format(
                    gen,
                    g["dispatches"],
                    g["steps"],
                    g["wall_ms_total"],
                )
            )
            for phase, stats in g["phases"].items():
                lines.append(
                    "  {:<20s} {:9.1f}ms ({:5.1f}%)".format(
                        phase,
                        stats["total_ms"],
                        (stats["share"] or 0.0) * 100.0,
                    )
                )
        serving = run.get("serving")
        if serving:
            lines.append(
                "serving: {} request(s) / {} reroute(s), wall {:.3f}s, "
                "coverage {}".format(
                    serving["requests"],
                    serving["reroutes"],
                    serving["wall_secs_total"],
                    f"{serving['coverage'] * 100:.0f}%"
                    if serving["coverage"] is not None
                    else "n/a",
                )
            )
            for phase, secs in serving["phases_secs"].items():
                lines.append(f"  {phase:<20s} {secs:8.3f}s")
            lines.append(
                "  dispatch groups: {} ({} linked)".format(
                    serving["dispatch_groups"],
                    serving["linked_dispatch_groups"],
                )
            )
        for gen, stats in run["stragglers"].items():
            for worker, w in stats["workers"].items():
                flag = "  STRAGGLER" if w["straggler"] else ""
                wait = (
                    f"  wait {w['barrier_wait_pct']:.0f}%"
                    if "barrier_wait_pct" in w
                    else ""
                )
                lines.append(
                    f"gen {gen} worker {worker}: median "
                    f"{w['median_step_ms']:.1f}ms "
                    f"({w['vs_generation_median']}x gen median)"
                    f"{wait}{flag}"
                )
    return "\n".join(lines)


def build_arg_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="python -m elasticdl_tpu.telemetry.trace",
        description="Export (Perfetto) and analyze distributed traces",
    )
    sub = parser.add_subparsers(dest="command", required=True)
    exp = sub.add_parser(
        "export", help="Emit Chrome trace-event JSON for Perfetto"
    )
    exp.add_argument("run_dir")
    exp.add_argument(
        "--output",
        default="",
        help=f"Output path (default <run_dir>/{TRACE_FILENAME})",
    )
    ana = sub.add_parser(
        "analyze",
        help="Reform critical path + per-generation straggler report",
    )
    ana.add_argument("run_dir")
    ana.add_argument("--json", action="store_true")
    ana.add_argument(
        "--output", default="", help="Also write the JSON report here"
    )
    return parser


def main(argv=None) -> int:
    args = build_arg_parser().parse_args(argv)
    if not os.path.isdir(args.run_dir):
        print(f"not a directory: {args.run_dir}", file=sys.stderr)
        return 2
    if args.command == "export":
        trace = build_chrome_trace(args.run_dir)
        out = args.output or os.path.join(args.run_dir, TRACE_FILENAME)
        with open(out, "w", encoding="utf-8") as f:
            json.dump(trace, f)
            f.write("\n")
        print(
            f"wrote {out} ({len(trace['traceEvents'])} events) — open at "
            "https://ui.perfetto.dev or chrome://tracing"
        )
        return 0
    report = analyze_run_dir(args.run_dir)
    if args.json:
        print(json.dumps(report, indent=2, default=str))
    else:
        print(_format_analysis(report))
    if args.output:
        with open(args.output, "w", encoding="utf-8") as f:
            json.dump(report, f, indent=2, default=str)
            f.write("\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
