"""Control plane: task dispatch, servicer, evaluation, instance management.

Reference: ``elasticdl/python/master/`` (SURVEY.md §2.2).
"""
