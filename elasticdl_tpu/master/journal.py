"""Durable control-plane journal — master high availability's backbone.

A write-ahead record of everything the master would otherwise lose with
its RAM: the task dispatcher's full lifecycle (todo/doing sets, epoch
cursor, counters), the servicer's control state (cluster generation,
model version, memoized lockstep step-stream), consumed deferred
callbacks, the worker-world composition, and replica-stage metadata.

Layout: ``<--master_journal_dir>/journal.jsonl`` — the same append-only
JSONL + rename-based rotation discipline as the telemetry event log
(:mod:`elasticdl_tpu.telemetry.events`; the reader IS that module's
shard-aware ``read_jsonl``).  The file always begins with a full
``snapshot`` record; every subsequent record is one transition delta.
Replay = last snapshot + deltas after it, so rotation dropping old
shards never loses recoverable state as long as a snapshot lands in the
retained window (the writer re-snapshots every ``snapshot_every``
deltas; the master's run loop drives that via :meth:`maybe_snapshot`).

Durability: appends are buffered and fsync-BATCHED (every
``fsync_batch`` records or ``fsync_interval_secs``, whichever first;
generation bumps and snapshots flush inline — losing one is losing the
fence).  The tail of the batch window can die with the master; that is
by design — the worker re-homing handshake (lease reconciliation) and
the dispatcher's drop-unknown-report rule reconcile the window, so the
exactly-once accounting the journal CLAIMS is exactly the accounting
the restored master ENFORCES.

All journal dict keys are strings (JSON would coerce them silently and
replay would then see str where it wrote int; test-pinned like the PR 4
peer map's msgpack ``strict_map_key`` rule).
"""

from __future__ import annotations

import json
import os
import threading
import time

from elasticdl_tpu.utils.log_utils import default_logger as logger

JOURNAL_FILENAME = "journal.jsonl"
MASTER_ADDR_FILENAME = "master_addr"

# env plumbing to workers (set by the master when --master_journal_dir
# is configured; read by worker/main.py and the crash-linger path)
MASTER_ADDR_FILE_ENV = "ELASTICDL_TPU_MASTER_ADDR_FILE"


def journal_path(journal_dir: str) -> str:
    return os.path.join(journal_dir, JOURNAL_FILENAME)


def addr_file_path(journal_dir: str) -> str:
    return os.path.join(journal_dir, MASTER_ADDR_FILENAME)


def write_master_addr(journal_dir: str, addr: str):
    """Publish the (re)started master's control-plane address for worker
    re-resolution — atomic rename so a reader never sees a torn write."""
    tmp = addr_file_path(journal_dir) + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        f.write(addr + "\n")
    os.replace(tmp, addr_file_path(journal_dir))


def read_master_addr(path: str) -> str | None:
    """The re-resolve hook workers install on their RPC client."""
    try:
        with open(path, encoding="utf-8") as f:
            addr = f.read().strip()
        return addr or None
    except OSError:
        return None


class MasterJournal:
    """The writer half: a ``TaskDispatcher`` observer plus direct record
    hooks for the servicer/master.  Attach UNARMED (so the observer
    backlog replay is ignored), seed with :meth:`start` (writes the
    initial snapshot and arms), then every transition self-appends."""

    def __init__(
        self,
        journal_dir: str,
        fsync_batch: int = 16,
        fsync_interval_secs: float = 0.2,
        snapshot_every: int = 512,
    ):
        os.makedirs(journal_dir, exist_ok=True)
        self._dir = journal_dir
        self._path = journal_path(journal_dir)
        self._fsync_batch = max(1, fsync_batch)
        self._fsync_interval = fsync_interval_secs
        self._snapshot_every = max(1, snapshot_every)
        self._lock = threading.Lock()
        # serializes drain+write+fsync: without it a preempted flusher
        # thread could land its (earlier) chunk AFTER an inline critical
        # flush, and replay — which applies records in FILE order —
        # would see effects before their causes
        self._flush_lock = threading.Lock()
        self._flush_wake = threading.Event()
        self._buffer: list[str] = []
        self._armed = False
        self._closed = False
        self._seq = 0
        self._since_snapshot = 0
        self._last_version = -1
        self._callbacks_invoked = 0
        self._snapshot_provider = None
        # memory-ledger accounting: the unflushed append buffer (small
        # by design — the fsync batcher bounds it — but a wedged disk
        # would grow it silently, which is exactly what a ledger is for)
        from elasticdl_tpu.telemetry import memory as memory_mod

        self._ledger_cb = self.buffer_bytes
        memory_mod.register_component(
            memory_mod.COMPONENT_MASTER_JOURNAL, self._ledger_cb
        )
        self._flusher = threading.Thread(
            target=self._flush_loop, name="master-journal", daemon=True
        )
        self._flusher.start()

    def buffer_bytes(self) -> int:
        """Bytes buffered and not yet flushed to disk."""
        with self._lock:
            return sum(len(line) for line in self._buffer)

    # ---- lifecycle ---------------------------------------------------------

    def set_snapshot_provider(self, provider):
        """``provider(append)`` assembles the full snapshot state and
        calls ``append(state)`` with it — from INSIDE whatever critical
        section makes the capture atomic with its journal position.  The
        master captures the dispatcher under the dispatcher transition
        lock (``TaskDispatcher.atomic_state_snapshot``): replay is
        last-snapshot-plus-later-deltas, so a delta journaled between a
        capture and its record would be silently dropped while its
        effect is missing from the captured state (a lost completion).
        The run loop drives snapshots; never call from an observer."""
        self._snapshot_provider = provider

    def start(self):
        """Write the initial snapshot and arm the observer hooks."""
        self.write_snapshot()
        self._armed = True

    def write_snapshot(self):
        if self._snapshot_provider is None:
            return
        try:
            self._snapshot_provider(self._append_snapshot)
        except Exception:  # noqa: BLE001 — a failed snapshot must not
            # take down the control plane; deltas since the LAST good
            # snapshot still replay
            logger.exception("Journal snapshot provider failed")

    def _append_snapshot(self, state: dict):
        """The ``append`` callback handed to the snapshot provider."""
        self._append("snapshot", critical=True, state=state)
        with self._lock:
            self._since_snapshot = 0

    def maybe_snapshot(self):
        """Run-loop hook: re-snapshot once enough deltas accumulated
        (bounds replay work and makes rotation safe)."""
        with self._lock:
            due = self._since_snapshot >= self._snapshot_every
        if due:
            self.write_snapshot()

    def close(self):
        self.flush()
        self._closed = True
        self._unregister_ledger()

    def abort(self):
        """SIGKILL semantics for the in-process chaos harness: drop the
        unflushed buffer tail and stop writing — exactly what a real
        master kill loses (the fsync-batch window the re-homing
        handshake is designed to reconcile)."""
        with self._lock:
            self._buffer.clear()
            self._closed = True
        self._unregister_ledger()

    def _unregister_ledger(self):
        # identity-guarded: a relaunched master's journal (HA harness,
        # fleetsim replays) may already have re-registered the name —
        # this journal's teardown must not drop the live one's callback
        from elasticdl_tpu.telemetry import memory as memory_mod

        memory_mod.unregister_component(
            memory_mod.COMPONENT_MASTER_JOURNAL, self._ledger_cb
        )

    # ---- append machinery --------------------------------------------------

    def _append(self, kind: str, critical: bool = False, **fields):
        if self._closed:
            return
        with self._lock:
            self._seq += 1
            record = {
                "seq": self._seq,
                "kind": kind,
                "time": time.time(),
                "monotonic": time.monotonic(),
                **fields,
            }
            self._buffer.append(json.dumps(record))
            if kind != "snapshot":
                self._since_snapshot += 1
            batch_full = len(self._buffer) >= self._fsync_batch
        if critical:
            self.flush()
        elif batch_full:
            # fsync off the caller's thread: observer appends run under
            # the dispatcher/stream locks, and an inline disk flush there
            # would stall every concurrent lease/report/heartbeat RPC
            self._flush_wake.set()

    def flush(self):
        """Write + fsync everything buffered (reopen per flush so the
        rename-based rotation always lands appends in the ACTIVE file).
        Serialized: concurrent flushes drain and write whole buffer
        generations in order, so file order == seq order."""
        with self._flush_lock:
            with self._lock:
                lines, self._buffer = self._buffer, []
            if not lines:
                return
            from elasticdl_tpu.telemetry.events import rotate_if_needed

            try:
                rotate_if_needed(self._path)
                with open(self._path, "a", encoding="utf-8") as f:
                    f.write("\n".join(lines) + "\n")
                    f.flush()
                    os.fsync(f.fileno())
            except OSError:
                logger.exception("Control-plane journal write failed")

    def _flush_loop(self):
        while not self._closed:
            self._flush_wake.wait(self._fsync_interval)
            self._flush_wake.clear()
            self.flush()

    # ---- TaskDispatcher observer hooks -------------------------------------

    def on_tasks_created(self, tasks):
        if not self._armed or not tasks:
            return
        self._append(
            "tasks_created",
            tasks=[t.to_dict() for t in tasks],
            records=sum(t.num_records for t in tasks),
        )

    def on_epoch_opened(self, epoch: int):
        if self._armed:
            self._append("epoch", epoch=int(epoch))

    def on_task_leased(self, task_id: int, worker_id: int, task):
        if self._armed:
            self._append(
                "lease",
                task_id=int(task_id),
                worker_id=int(worker_id),
                uid=int(task.uid),
                task_type=int(task.type),
            )

    def on_task_done(
        self, task_id, task, worker_id, success, exec_counters=None
    ):
        if self._armed:
            # success reports flush inline: one lost in the batch-window
            # tail would make the restored dispatcher re-run a task whose
            # completion was already COUNTED by the first life — the one
            # loss the re-homing handshake cannot reconcile (the worker,
            # having been acked, no longer presents the lease).  Failure
            # reports just requeue, which a journal-less restart does
            # anyway, so they ride the batch.  Completions are per-task
            # (seconds apart), so the fsync cost is negligible
            self._append(
                "report",
                critical=bool(success),
                task_id=int(task_id),
                uid=int(task.uid),
                worker_id=int(worker_id),
                success=bool(success),
                task_type=int(task.type),
                records=int(task.num_records),
                exec_counters={
                    str(k): v for k, v in (exec_counters or {}).items()
                },
            )

    def on_task_reclaimed(self, task_id, task):
        if self._armed:
            self._append(
                "reclaim",
                task_id=int(task_id),
                uid=int(task.uid),
                task_type=int(task.type),
            )

    def on_callback_invoked(self):
        self._callbacks_invoked += 1
        if self._armed:
            self._append("callback")

    def set_callbacks_invoked(self, count: int):
        """Seed the cumulative consumed-callback counter after a
        restart (replay hands the restored value back so snapshots keep
        counting across master lives)."""
        self._callbacks_invoked = int(count)

    @property
    def callbacks_invoked(self) -> int:
        return self._callbacks_invoked

    # ---- servicer / master record hooks ------------------------------------

    def on_version_report(self, worker_id: int, model_version: int):
        if not self._armed or model_version <= self._last_version:
            return
        self._last_version = model_version
        self._append("version", model_version=int(model_version))

    def record_generation(self, cluster_version: int):
        """Generation bump — the fence itself; flushed inline (a lost
        fence record would let a restarted master resurrect a fenced
        generation)."""
        self._append(
            "generation", critical=True, cluster_version=int(cluster_version)
        )

    def record_stream_snapshot(self, stream: dict):
        """Full stream-memo capture, appended by the servicer UNDER its
        stream lock so the record's file position IS its capture point.
        Written right after each main snapshot: the main snapshot's
        stream field is captured before its (dispatcher-atomic) append,
        so a memo resolved in between would otherwise be lost — this
        record supersedes everything before it on replay."""
        self._append("stream_snapshot", critical=True, stream=stream)

    def record_stream(
        self, seq: int, response: dict, cluster_version: int = -1
    ):
        """One memoized lockstep step-stream resolution; replayed so a
        restarted master answers already-resolved seqs IDENTICALLY —
        the lockstep invariant must span the outage.  ``cluster_version``
        is the generation the resolution was FOR: a record that raced a
        reform lands after the ``generation`` record, and replay uses the
        stamp to drop it (-1 = unstamped legacy record, always applied)."""
        self._append(
            "stream",
            stream_seq=int(seq),
            response=response,
            cluster_version=int(cluster_version),
        )

    def record_world(
        self,
        cluster_version: int,
        worker_ids: list[int],
        world_size: int,
        num_slices: int = 1,
        slices: dict | None = None,
        parked: bool = False,
    ):
        """``num_slices``/``slices`` (worker_id -> slice_id, STRING keys
        — JSON would coerce them anyway) carry the slice topology so a
        restarted master keeps slice-granular reform working for the
        re-homed world; ``parked`` marks a world gracefully degraded
        below --min_slices (the restarted master must stay parked, not
        relaunch a fleet the capacity cannot run)."""
        self._append(
            "world",
            critical=True,
            cluster_version=int(cluster_version),
            worker_ids=sorted(int(w) for w in worker_ids),
            world_size=int(world_size),
            num_slices=int(num_slices),
            slices={str(k): int(v) for k, v in (slices or {}).items()},
            parked=bool(parked),
        )

    def record_stage(self, generation: int, version, complete: bool):
        """Replica-stage METADATA (the payload is RAM and dies with the
        master; a restarted master serves the disk-fallback answer)."""
        self._append(
            "stage",
            generation=int(generation),
            version=version,
            complete=bool(complete),
        )

    def record_stage_released(self, generation: int):
        """Every process of the restoring generation fetched its copy:
        the stage is no longer in flight, so a later restart must NOT
        report it as a lost replica set (a false disk-fallback)."""
        self._append("stage_released", generation=int(generation))

    def record_job_end(self, rc: int):
        self._append("job_end", critical=True, rc=int(rc))
        self.close()


# ---- replay -----------------------------------------------------------------


def _task_list_remove(tasks: list[dict], uid: int) -> dict | None:
    """Pop the task with ``uid`` searching from the END (leases pop the
    tail, so the match is O(1) on the common path)."""
    for i in range(len(tasks) - 1, -1, -1):
        if int(tasks[i].get("uid", -1)) == uid:
            return tasks.pop(i)
    return None


def replay(records: list[dict]) -> dict | None:
    """Reconstruct the control-plane state from journal records: the
    LAST snapshot plus every delta after it.  Pure function — the
    equivalence property test drives it with recorded transitions.

    Returns ``None`` when no snapshot exists (empty/unusable journal).
    The result dict mirrors the snapshot provider's shape plus
    ``clean_shutdown`` and bookkeeping the restarting master applies.
    """
    snap_index = None
    for i, rec in enumerate(records):
        if rec.get("kind") == "snapshot":
            snap_index = i
    if snap_index is None:
        return None
    state = json.loads(json.dumps(records[snap_index]["state"]))  # deep copy
    disp = state["dispatcher"]
    servicer = state.setdefault(
        "servicer", {"cluster_version": 0, "model_version": 0, "stream": {}}
    )
    state.setdefault("callbacks_invoked", 0)
    state["clean_shutdown"] = False
    from elasticdl_tpu.utils.constants import TaskType

    def counters_for(task_type: int) -> dict:
        name = TaskType(task_type).name
        return disp.setdefault("counters", {}).setdefault(
            name,
            {"total_records": 0, "failed_records": 0, "exec_metrics": {}},
        )

    def queue_for(task_type: int) -> list:
        return (
            disp["pending_eval"]
            if task_type == int(TaskType.EVALUATION)
            else disp["pending"]
        )

    def stream_minted(task: dict):
        """A ``tasks_created`` delta in watermark-lease mode is a window
        mint: the offset cursor (and the source watermark floor — the
        source had published at least this much) advance with it."""
        stream = disp.get("stream")
        if stream is None or int(task["type"]) != int(TaskType.TRAINING):
            return
        end = int(task["end"])
        stream["next_offset"] = max(int(stream.get("next_offset", 0)), end)
        stream["source_watermark"] = max(
            int(stream.get("source_watermark", 0)), end
        )

    def stream_trained(task: dict):
        """A successful window report advances the trained watermark
        over the gap-free prefix — the same pop loop the live
        dispatcher runs (``_stream_complete_locked``)."""
        stream = disp.get("stream")
        if stream is None or int(task["type"]) != int(TaskType.TRAINING):
            return
        completed = stream.setdefault("completed", {})
        completed[str(task["start"])] = int(task["end"])
        watermark = int(stream.get("trained_watermark", 0))
        while str(watermark) in completed:
            watermark = int(completed.pop(str(watermark)))
        stream["trained_watermark"] = watermark

    for rec in records[snap_index + 1 :]:
        kind = rec.get("kind")
        if kind == "epoch":
            disp["epoch"] = int(rec["epoch"])
        elif kind == "tasks_created":
            tasks = rec.get("tasks", [])
            for t in tasks:
                queue_for(int(t["type"])).append(t)
                disp["next_task_uid"] = max(
                    int(disp.get("next_task_uid", 0)), int(t.get("uid", 0))
                )
                stream_minted(t)
            if tasks:
                counters_for(int(tasks[0]["type"]))["total_records"] += int(
                    rec.get("records", 0)
                )
        elif kind == "lease":
            task = _task_list_remove(
                queue_for(int(rec.get("task_type", 0))), int(rec["uid"])
            )
            if task is None:
                continue  # forged/duplicate lease: nothing to move
            disp["active"][str(rec["task_id"])] = {
                "worker_id": int(rec["worker_id"]),
                "task": task,
            }
            disp["next_task_id"] = max(
                int(disp.get("next_task_id", 0)), int(rec["task_id"])
            )
        elif kind == "report":
            entry = disp["active"].pop(str(rec["task_id"]), None)
            if entry is None:
                continue  # unknown lease (forged or double): dropped
            counters = counters_for(int(rec.get("task_type", 0)))
            exec_counters = rec.get("exec_counters", {}) or {}
            for key, value in exec_counters.items():
                if key == "fail_count":
                    counters["failed_records"] += int(value)
                else:
                    counters["exec_metrics"][key] = (
                        counters["exec_metrics"].get(key, 0) + value
                    )
            if not rec.get("success"):
                queue_for(int(rec.get("task_type", 0))).append(
                    entry["task"]
                )
            else:
                stream_trained(entry["task"])
        elif kind == "reclaim":
            entry = disp["active"].pop(str(rec["task_id"]), None)
            if entry is not None:
                queue_for(int(rec.get("task_type", 0))).append(
                    entry["task"]
                )
        elif kind == "version":
            servicer["model_version"] = max(
                int(servicer.get("model_version", 0)),
                int(rec["model_version"]),
            )
        elif kind == "generation":
            # monotone guard: a (forged or corrupt) rollback must not
            # resurrect a fenced generation on restore — the post-run
            # invariant checker still sees the raw record and trips
            prev = int(servicer.get("cluster_version", 0))
            servicer["cluster_version"] = max(prev, int(rec["cluster_version"]))
            # a generation bump is a reform: the live master resets the
            # step stream there, so replay must not resurrect the old
            # generation's memos into the new world — but a held (stale)
            # record must not clear memos the fenced generation produced
            if servicer["cluster_version"] > prev:
                servicer["stream"] = {}
        elif kind == "stream":
            # a resolution stamped for another world raced a reform: the
            # live master's reset_step_stream dropped it, so replay must
            # too — applying it would serve an old-world memo (an
            # already-recovered task) to the new generation
            stamp = int(rec.get("cluster_version", -1))
            if stamp in (-1, int(servicer.get("cluster_version", 0))):
                servicer.setdefault("stream", {})[
                    str(rec["stream_seq"])
                ] = rec["response"]
        elif kind == "stream_snapshot":
            # a full capture at this exact position: supersedes the main
            # snapshot's (earlier-captured) stream field and any deltas
            # replayed since
            servicer["stream"] = {
                str(seq): resp for seq, resp in rec["stream"].items()
            }
        elif kind == "callback":
            state["callbacks_invoked"] = (
                int(state.get("callbacks_invoked", 0)) + 1
            )
        elif kind == "world":
            state["world"] = {
                "cluster_version": int(rec["cluster_version"]),
                "worker_ids": [int(w) for w in rec["worker_ids"]],
                "world_size": int(rec["world_size"]),
                # slice topology (absent on pre-multislice journals)
                "num_slices": int(rec.get("num_slices", 1) or 1),
                "slices": {
                    str(k): int(v)
                    for k, v in (rec.get("slices") or {}).items()
                },
                "parked": bool(rec.get("parked")),
            }
        elif kind == "stage":
            state["stage"] = {
                "generation": int(rec["generation"]),
                "version": rec.get("version"),
                "complete": bool(rec.get("complete")),
            }
        elif kind == "stage_released":
            state["stage"] = None
        elif kind == "job_end":
            state["clean_shutdown"] = True
    return state


def load_state(journal_dir: str) -> dict | None:
    """Replay an on-disk journal (rotation shards included); ``None``
    when the directory holds no usable journal — a FIRST master start,
    not a restart."""
    from elasticdl_tpu.telemetry.events import read_jsonl

    path = journal_path(journal_dir)
    if not any(
        os.path.exists(p) for p in (path, f"{path}.1")
    ):
        return None
    records = read_jsonl(path)
    if not records:
        return None
    return replay(records)
