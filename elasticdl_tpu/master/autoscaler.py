"""Telemetry-driven autoscaler: grow/shrink the slice count on SLOs.

The master's run loop ticks :meth:`Autoscaler.evaluate` once per poll;
the decision inputs ride telemetry channels the control plane already
has — p95 step time derived from chief version reports (the servicer's
version-observer seam, no new RPC), and the pending-task backlog from
the dispatcher snapshot.  A decision is a REQUEST, not an action: the
master resizes the next world (``set_world_slices``) and asks its own
run loop to re-form (``request_reform``), exactly the path capacity
faults and chaos already take — so an autoscale resize is
indistinguishable from any other elective re-formation downstream
(fence, replica harvest, hot restore, exactly-once accounting).

All thresholds default to None/off; with no ``--autoscale_*`` flag set
the master never constructs this object and behavior is byte-identical
to an autoscaler-less build.
"""

from __future__ import annotations

import time

from elasticdl_tpu.telemetry.slo import StepTimePercentileTracker

DEFAULT_COOLDOWN_SECS = 30.0
# shrink only when every configured SLO sits under this fraction of its
# threshold (plus an empty backlog): hysteresis against flapping
SHRINK_HEADROOM = 0.25

# ONE percentile definition site: the tracker lives with the SLO engine
# (telemetry/slo.py) so the autoscaler's grow/shrink evidence and the
# watchdog's step-time objective can never disagree on what "p95 step
# time" means.  The name stays exported here — the decision-stream pin
# test holds the semantics byte-identical to the historical private
# window.
StepTimeTracker = StepTimePercentileTracker


class Autoscaler:
    def __init__(
        self,
        p95_step_ms: float | None = None,
        backlog_tasks: int | None = None,
        cooldown_secs: float | None = None,
        shrink: bool = False,
        min_slices: int = 1,
        max_slices: int = 1,
        tracker: StepTimeTracker | None = None,
    ):
        self.p95_step_ms = p95_step_ms
        self.backlog_tasks = backlog_tasks
        self.cooldown_secs = (
            cooldown_secs
            if cooldown_secs is not None
            else DEFAULT_COOLDOWN_SECS
        )
        self.shrink_enabled = bool(shrink)
        self.min_slices = max(1, int(min_slices or 1))
        self.max_slices = max(self.min_slices, int(max_slices or 1))
        self.tracker = tracker if tracker is not None else StepTimeTracker()
        self._last_decision_at: float | None = None
        self.decisions: list[dict] = []

    # the servicer version-observer hook (wired by Master.__init__)
    def note_version(self, worker_id: int, version: int):
        self.tracker.note_version(worker_id, version)

    def note_reform(self):
        """Any re-formation (autoscale-driven or not) restarts the
        cooldown AND the step-time baseline: the new world must produce
        fresh evidence before the next decision."""
        self._last_decision_at = time.monotonic()
        self.tracker.reset()

    def evaluate(
        self, backlog: int, current_slices: int, now: float | None = None
    ) -> dict | None:
        """One tick: returns a decision dict ``{"action", "from_slices",
        "to_slices", "reason", "p95_step_ms", "backlog"}`` or None.  The
        caller owns acting on it (resize + request_reform)."""
        now = now if now is not None else time.monotonic()
        if (
            self._last_decision_at is not None
            and now - self._last_decision_at < self.cooldown_secs
        ):
            return None
        p95 = self.tracker.p95_ms()
        decision = None
        if (
            self.backlog_tasks is not None
            and backlog >= self.backlog_tasks
            and current_slices < self.max_slices
        ):
            decision = self._decide(
                "grow",
                current_slices,
                current_slices + 1,
                f"backlog {backlog} >= {self.backlog_tasks}",
                p95,
                backlog,
            )
        elif (
            self.p95_step_ms is not None
            and p95 is not None
            and p95 >= self.p95_step_ms
            and current_slices < self.max_slices
        ):
            decision = self._decide(
                "grow",
                current_slices,
                current_slices + 1,
                f"p95 step {p95:.1f}ms >= {self.p95_step_ms:.1f}ms",
                p95,
                backlog,
            )
        elif self.shrink_enabled and current_slices > self.min_slices:
            # shrinking needs POSITIVE evidence of over-provisioning: a
            # MEASURED p95 under the headroom fraction of its SLO.  An
            # empty backlog alone is not evidence — pending counts only
            # UNLEASED tasks, so it reads 0 precisely while every worker
            # is busy mid-lease, and shrinking then would requeue the
            # leased tasks, spike the backlog over the grow threshold,
            # and flap shrink/grow every cooldown period.
            under_p95 = (
                self.p95_step_ms is not None
                and p95 is not None
                and p95 <= SHRINK_HEADROOM * self.p95_step_ms
            )
            under_backlog = backlog == 0
            if under_p95 and under_backlog:
                decision = self._decide(
                    "shrink",
                    current_slices,
                    current_slices - 1,
                    "all SLOs under headroom with empty backlog",
                    p95,
                    backlog,
                )
        if decision is not None:
            self._last_decision_at = now
        return decision

    def _decide(self, action, from_slices, to_slices, reason, p95, backlog):
        decision = {
            "action": action,
            "from_slices": from_slices,
            "to_slices": to_slices,
            "reason": reason,
            "p95_step_ms": round(p95, 3) if p95 is not None else None,
            "backlog": backlog,
        }
        self.decisions.append(decision)
        return decision


def build_autoscaler(args, fleet_slices: int) -> Autoscaler | None:
    """An Autoscaler when any ``--autoscale_*`` SLO is configured, else
    None (the dormant default — no observer, no tick, no state)."""
    p95 = getattr(args, "autoscale_p95_step_ms", None)
    backlog = getattr(args, "autoscale_backlog_tasks", None)
    if bool(getattr(args, "streaming", False)):
        # watermark-lease mode: --stream_lag_tasks is the dedicated
        # backlog threshold (lag behind the source watermark in task-
        # window units — the master converts before evaluate()); it
        # falls back to the shared --autoscale_backlog_tasks knob
        backlog = getattr(args, "stream_lag_tasks", None) or backlog
    if p95 is None and backlog is None:
        return None
    return Autoscaler(
        p95_step_ms=p95,
        backlog_tasks=backlog,
        cooldown_secs=getattr(args, "autoscale_cooldown_secs", None),
        shrink=bool(getattr(args, "autoscale_shrink", None)),
        min_slices=getattr(args, "min_slices", None) or 1,
        max_slices=fleet_slices,
    )
